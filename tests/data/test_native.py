"""Native C++ data-helper tests: closure parity with the numpy oracle,
negative-sampler invariants (SURVEY.md §4 parity-test strategy)."""

import numpy as np
import pytest

from hyperspace_tpu.data import wordnet

native = pytest.importorskip("hyperspace_tpu.data.native")


def _canon(pairs):
    return {(int(u), int(v)) for u, v in pairs}


def test_closure_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    n = 200
    # random DAG: each node picks ≤2 parents with smaller index
    edges = []
    for u in range(1, n):
        for p in rng.choice(u, size=min(u, rng.integers(0, 3)), replace=False):
            edges.append((u, int(p)))
    edges = np.asarray(edges, np.int32)
    got = native.transitive_closure(edges, n)
    want = wordnet._closure_numpy(edges, n)
    assert _canon(got) == _canon(want)


def test_closure_empty_and_chain():
    assert native.transitive_closure(np.zeros((0, 2), np.int32), 4).shape == (0, 2)
    chain = np.asarray([[1, 0], [2, 1], [3, 2]], np.int32)
    got = _canon(native.transitive_closure(chain, 4))
    assert got == {(1, 0), (2, 1), (2, 0), (3, 2), (3, 1), (3, 0)}


def test_negative_sampler_invariants():
    edges = np.asarray([[0, 1], [1, 2], [2, 3]], np.int32)
    neg = native.sample_negative_edges(edges, 50, 200, seed=7)
    assert neg.shape == (200, 2)
    es = _canon(edges)
    for u, v in neg:
        assert u < v and 0 <= u < 50 and v < 50
        assert (int(u), int(v)) not in es


def test_negative_sampler_deterministic():
    edges = np.asarray([[0, 1]], np.int32)
    a = native.sample_negative_edges(edges, 20, 50, seed=3)
    b = native.sample_negative_edges(edges, 20, 50, seed=3)
    np.testing.assert_array_equal(a, b)
    c = native.sample_negative_edges(edges, 20, 50, seed=4)
    assert not np.array_equal(a, c)


def test_prepare_edges_matches_numpy_oracle():
    """Native pipeline vs the ACTUAL numpy fallback used by graphs.prepare
    (same function object — no drift possible)."""
    from hyperspace_tpu.data.graphs import _prepare_edges_numpy

    rng = np.random.default_rng(0)
    for n, ne, sym, loops in [(40, 100, True, True), (40, 100, True, False),
                              (40, 100, False, True), (7, 0, True, True)]:
        edges = rng.integers(0, n, (ne, 2)).astype(np.int32)
        got = native.prepare_edges(edges, n, symmetrize=sym, self_loops=loops,
                                   pad_multiple=64)
        want = _prepare_edges_numpy(edges, n, symmetrize=sym,
                                    self_loops=loops, pad_multiple=64)
        for a, b, name in zip(got, want,
                              ("senders", "receivers", "mask", "rev", "deg")):
            if name == "rev" and not sym:
                continue
            np.testing.assert_array_equal(a, b, err_msg=name)


def test_locality_order_matches_python_oracle():
    """Native BFS relabeling vs the deque walk — exact order equality
    (adjacency order and seed tie-breaking must match, not just the set
    of visited nodes)."""
    from hyperspace_tpu.data.graphs import _locality_order_python

    rng = np.random.default_rng(1)
    for n, ne in [(1, 0), (30, 0), (60, 150), (200, 800)]:
        edges = (rng.integers(0, n, (ne, 2)).astype(np.int32)
                 if ne else np.zeros((0, 2), np.int32))
        got = native.locality_order(edges, n)
        want = _locality_order_python(edges, n)
        np.testing.assert_array_equal(got, want)
        assert sorted(got.tolist()) == list(range(n))  # a permutation


def test_sample_neighbors_matches_numpy_oracle():
    """C++ sampler vs the vectorized numpy twin: bit-exact draws (same
    per-cell splitmix64 stream), neighbors only, isolated -> self."""
    from hyperspace_tpu.models.hgcn_sampled import build_adjacency

    rng = np.random.default_rng(3)
    edges = rng.integers(0, 40, (120, 2)).astype(np.int32)
    indptr, indices = build_adjacency(edges, 41)  # node 40 isolated
    seeds = np.concatenate([rng.integers(0, 40, 30), [40]]).astype(np.int32)
    for seed in (0, 7):
        a = native.sample_neighbors(indptr, indices, seeds, 5, seed=seed)
        b = native.sample_neighbors_numpy(indptr, indices, seeds, 5,
                                          seed=seed)
        np.testing.assert_array_equal(a, b)
    assert np.all(a[-1] == 40)  # isolated node samples itself
    for i, u in enumerate(seeds[:-1]):
        nbrs = set(indices[indptr[u]:indptr[u + 1]].tolist())
        assert set(a[i].tolist()) <= nbrs
