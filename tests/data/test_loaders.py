"""Real-data loader fixture tests (VERDICT r2 next #4).

The on-disk parsers (`load_cora`, `load_ogbn_arxiv`, the WordNet closure
TSV) had never executed before this file: every quality claim ultimately
refers to these datasets, so a parse bug would invalidate the story the
day real data appears.  Each fixture is a hand-written miniature of the
real format; each test goes loader → prepare/split → a few real train
steps, not just a parse check.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G


# --- cora (Planetoid raw format) ---------------------------------------------

CORA_CONTENT = """\
p100\t1\t0\t0\t1\tGenetic_Algorithms
p200\t0\t1\t0\t0\tNeural_Networks
p300\t0\t0\t1\t1\tNeural_Networks
p400\t1\t1\t0\t0\tTheory
p500\t0\t0\t0\t1\tGenetic_Algorithms
p600\t1\t0\t1\t0\tTheory
"""

# includes one citation of an unknown paper id (real cora.cites has these
# when content rows are filtered) — the loader must drop it
CORA_CITES = """\
p100\tp200
p200\tp300
p300\tp400
p400\tp500
p500\tp600
p600\tp100
p100\tp300
p999\tp100
"""


@pytest.fixture
def cora_root(tmp_path):
    (tmp_path / "cora.content").write_text(CORA_CONTENT)
    (tmp_path / "cora.cites").write_text(CORA_CITES)
    return str(tmp_path)


def test_load_cora_parses(cora_root):
    edges, x, labels, ncls = G.load_cora(cora_root)
    assert x.shape == (6, 4) and x.dtype == np.float32
    assert labels.shape == (6,) and ncls == 3
    # first row: features 1,0,0,1; label ids assigned in encounter order
    np.testing.assert_array_equal(x[0], [1, 0, 0, 1])
    assert labels[0] == labels[4]  # both Genetic_Algorithms
    assert labels[1] == labels[2]  # both Neural_Networks
    # the p999 line referenced an unknown id and must be dropped
    assert len(edges) == 7
    assert edges.max() < 6


def test_load_graph_dispatches_to_disk(cora_root):
    edges, x, labels, ncls, source = G.load_graph("cora", cora_root)
    assert source == "disk"
    assert x.shape[0] == 6


def test_cora_trains_nc(cora_root):
    from hyperspace_tpu.models import hgcn

    edges, x, labels, ncls, _ = G.load_graph("cora", cora_root)
    n = x.shape[0]
    tr, va, te = G.node_split_masks(n, seed=0)
    g = G.prepare(edges, n, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te, pad_multiple=16)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(8, 4),
                          num_classes=ncls)
    model, opt, state = hgcn.init_nc(cfg, g, seed=0)
    ga = G.to_device(g)
    lab, msk = jnp.asarray(g.labels), jnp.asarray(g.train_mask)
    for _ in range(5):
        state, loss = hgcn.train_step_nc(model, opt, state, ga, lab, msk)
    assert np.isfinite(float(loss))


# --- ogbn-arxiv (OGB extracted-csv layout) ------------------------------------


@pytest.fixture
def arxiv_root(tmp_path):
    raw = tmp_path / "raw"
    raw.mkdir()
    rng = np.random.default_rng(0)
    n, f = 12, 5
    feats = rng.standard_normal((n, f)).round(3)
    labels = rng.integers(0, 4, n)
    edges = np.array([[i, (i + 1) % n] for i in range(n)]
                     + [[0, 5], [3, 9], [7, 2]])
    np.savetxt(raw / "edge.csv", edges, fmt="%d", delimiter=",")
    np.savetxt(raw / "node-feat.csv", feats, fmt="%.3f", delimiter=",")
    np.savetxt(raw / "node-label.csv", labels[:, None], fmt="%d",
               delimiter=",")
    return str(tmp_path), edges, feats, labels


def test_load_ogbn_arxiv_parses(arxiv_root):
    root, edges_w, feats_w, labels_w = arxiv_root
    edges, x, labels, ncls = G.load_ogbn_arxiv(root)
    np.testing.assert_array_equal(edges, edges_w)
    np.testing.assert_allclose(x, feats_w.astype(np.float32), atol=1e-6)
    np.testing.assert_array_equal(labels, labels_w)
    assert ncls == labels_w.max() + 1


def test_arxiv_trains_lp(arxiv_root):
    from hyperspace_tpu.models import hgcn

    root, *_ = arxiv_root
    edges, x, labels, ncls, source = G.load_graph("ogbn-arxiv", root)
    assert source == "disk"
    n = x.shape[0]
    split = G.split_edges(edges, n, x, val_frac=0.1, test_frac=0.1, seed=0,
                          pad_multiple=16)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(8, 4))
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = G.to_device(split.graph)
    pos = jnp.asarray(split.train_pos)
    for _ in range(5):
        state, loss = hgcn.train_step_lp(model, opt, n, state, ga, pos)
    assert np.isfinite(float(loss))
    ev = hgcn.evaluate_lp(model, state.params, split, "test", ga=ga)
    assert 0.0 <= ev["roc_auc"] <= 1.0


# --- WordNet closure TSV ------------------------------------------------------

WORDNET_TSV = """\
# child\tparent lines; comments and blanks ignored
dog.n.01\tcanine.n.02
cat.n.01\tfeline.n.01
canine.n.02\tcarnivore.n.01
feline.n.01\tcarnivore.n.01
carnivore.n.01\tmammal.n.01

dog.n.01\tcarnivore.n.01
"""


@pytest.fixture
def wordnet_tsv(tmp_path):
    p = tmp_path / "closure.tsv"
    p.write_text(WORDNET_TSV)
    return str(p)


def test_load_closure_tsv_parses(wordnet_tsv):
    from hyperspace_tpu.data import wordnet

    ds = wordnet.load_closure_tsv(wordnet_tsv)
    assert ds.num_nodes == 6
    assert ds.num_pairs == 6
    by_name = {n: i for i, n in enumerate(ds.names)}
    pairs = ds.adjacency_set()
    assert (by_name["dog.n.01"], by_name["canine.n.02"]) in pairs
    assert (by_name["dog.n.01"], by_name["carnivore.n.01"]) in pairs


def test_load_closure_tsv_closes_edges(wordnet_tsv):
    """already_closed=False must expand parent edges to full ancestry."""
    from hyperspace_tpu.data import wordnet

    ds = wordnet.load_closure_tsv(wordnet_tsv, already_closed=False)
    by_name = {n: i for i, n in enumerate(ds.names)}
    pairs = ds.adjacency_set()
    # dog -> mammal is only reachable transitively
    assert (by_name["dog.n.01"], by_name["mammal.n.01"]) in pairs
    assert (by_name["cat.n.01"], by_name["mammal.n.01"]) in pairs


def test_wordnet_tsv_trains(wordnet_tsv):
    from hyperspace_tpu.data import wordnet
    from hyperspace_tpu.models import poincare_embed as pe

    ds = wordnet.load_closure_tsv(wordnet_tsv, already_closed=False)
    cfg = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=3,
                                 batch_size=8, neg_samples=3,
                                 burnin_steps=0)
    state, opt = pe.init_state(cfg, seed=0)
    pairs = jnp.asarray(ds.pairs)
    for _ in range(5):
        state, loss = pe.train_step(cfg, opt, state, pairs)
    assert np.isfinite(float(loss))
    assert np.linalg.norm(np.asarray(state.table), axis=-1).max() < 1.0


# --- locality reordering ------------------------------------------------------


def test_locality_order_is_permutation_and_clusters_communities():
    """BFS relabeling must be a valid permutation and must turn an
    id-interleaved community graph into contiguous blocks (what the
    cluster-pair kernel needs from real citation graphs)."""
    rng = np.random.default_rng(0)
    n, k = 512, 4
    comm = np.arange(n) % k  # communities interleaved in id space
    edges = []
    for c in range(k):
        members = np.flatnonzero(comm == c)
        for _ in range(n):
            u, v = rng.choice(members, 2, replace=False)
            edges.append((u, v))
    edges = np.asarray(edges, np.int64)

    order = G.locality_order(edges, n)
    assert sorted(order.tolist()) == list(range(n))

    new_edges, new_x, new_labels, order2 = G.apply_locality_order(
        edges, np.eye(n, 8, dtype=np.float32), comm.astype(np.int32))
    np.testing.assert_array_equal(order, order2)
    # labels/features follow their nodes
    np.testing.assert_array_equal(new_labels, comm[order])
    # community locality: most edges now span a small id distance
    spread_before = np.abs(edges[:, 0] - edges[:, 1])
    spread_after = np.abs(new_edges[:, 0] - new_edges[:, 1])
    assert np.median(spread_after) < np.median(spread_before) / 2


def test_locality_order_preserves_training(cora_root):
    """Relabeled graphs are isomorphic: the NC task still trains."""
    from hyperspace_tpu.models import hgcn

    edges, x, labels, ncls, _ = G.load_graph("cora", cora_root)
    edges, x, labels, _ = G.apply_locality_order(edges, x, labels)
    n = x.shape[0]
    tr, va, te = G.node_split_masks(n, seed=0)
    g = G.prepare(edges, n, x, labels=labels, num_classes=ncls,
                  train_mask=tr, val_mask=va, test_mask=te, pad_multiple=16)
    cfg = hgcn.HGCNConfig(feat_dim=x.shape[1], hidden_dims=(8, 4),
                          num_classes=ncls)
    model, opt, state = hgcn.init_nc(cfg, g, seed=0)
    ga = G.to_device(g)
    lab, msk = jnp.asarray(g.labels), jnp.asarray(g.train_mask)
    for _ in range(5):
        state, loss = hgcn.train_step_nc(model, opt, state, ga, lab, msk)
    assert np.isfinite(float(loss))
