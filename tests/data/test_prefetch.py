"""HostPrefetcher contracts (data/prefetch.py): ordering, bounded
look-ahead, failure propagation, shutdown.

The prefetcher is the overlap half of the chunked-dispatch loop — the
sampled trainer's batch stream runs on it, so these semantics are
load-bearing for training correctness, not just throughput."""

import threading
import time

import pytest

from hyperspace_tpu.data.prefetch import HostPrefetcher


def test_yields_in_order_exactly_once():
    with HostPrefetcher(lambda i: i * 10) as p:
        assert [p.next() for _ in range(5)] == [0, 10, 20, 30, 40]


def test_start_offset_resumes_sequence():
    # the stream-resume contract: start=k yields fn(k), fn(k+1), ...
    with HostPrefetcher(lambda i: i, start=3) as p:
        assert [p.next() for _ in range(3)] == [3, 4, 5]


def test_lookahead_is_bounded():
    calls = []
    ev = threading.Event()

    def fn(i):
        calls.append(i)
        ev.set()
        return i

    with HostPrefetcher(fn, depth=2):
        ev.wait(timeout=5.0)
        deadline = time.monotonic() + 2.0
        # worker may hold one in-flight item beyond the 2 queued slots,
        # but must never run ahead unboundedly while nothing consumes
        while time.monotonic() < deadline and len(calls) < 3:
            time.sleep(0.01)
        time.sleep(0.1)
        assert len(calls) <= 3


def test_worker_error_reraises_with_cause():
    def fn(i):
        if i == 2:
            raise ValueError("chunk 2 broke")
        return i

    with HostPrefetcher(fn) as p:
        assert p.next() == 0
        assert p.next() == 1
        with pytest.raises(RuntimeError) as ei:
            p.next()
        assert isinstance(ei.value.__cause__, ValueError)
        assert "chunk 2 broke" in str(ei.value.__cause__)


def test_close_joins_worker_even_when_blocked_on_put():
    with HostPrefetcher(lambda i: i, depth=1) as p:
        p.next()  # worker now blocked producing/putting ahead
    assert not p._thread.is_alive()


def test_close_is_idempotent():
    p = HostPrefetcher(lambda i: i)
    p.next()
    p.close()
    p.close()
    assert not p._thread.is_alive()
