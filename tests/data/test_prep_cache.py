"""Persistent graph-prep cache (data/prep_cache.py) + its graphs.py
integration: hit/miss accounting, invalidation on config change, and —
the load-bearing contract — bit-identical artifacts on a hit."""

import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G
from hyperspace_tpu.data.prep_cache import PrepCache, key_hash


@pytest.fixture()
def cache(tmp_path):
    return PrepCache(root=str(tmp_path / "prep"))


def _edges(seed=0, n=200, e=600):
    rng = np.random.default_rng(seed)
    edges = rng.integers(0, n, (e, 2))
    return edges[edges[:, 0] != edges[:, 1]]


def test_get_or_build_counts_and_builds_once(cache):
    calls = []

    def build():
        calls.append(1)
        return {"a": np.arange(5)}

    first = cache.get_or_build("k", (1, "x"), build)
    second = cache.get_or_build("k", (1, "x"), build)
    assert len(calls) == 1
    assert cache.misses == 1 and cache.hits == 1
    np.testing.assert_array_equal(first["a"], second["a"])


def test_key_changes_invalidate(cache):
    cache.get_or_build("k", (1,), lambda: 1)
    cache.get_or_build("k", (2,), lambda: 2)     # knob changed → miss
    cache.get_or_build("other", (1,), lambda: 3)  # kind changed → miss
    assert cache.misses == 3 and cache.hits == 0
    # type-tagged hashing: the int 1 and the string "1" must not collide
    assert key_hash("k", (1,)) != key_hash("k", ("1",))


def test_corrupt_entry_rebuilds(cache):
    cache.get_or_build("k", (1,), lambda: np.arange(3))
    path = cache._path("k", key_hash("k", (1,)))
    with open(path, "wb") as f:
        f.write(b"not a pickle")
    out = cache.get_or_build("k", (1,), lambda: np.arange(3))
    np.testing.assert_array_equal(out, np.arange(3))
    assert cache.misses == 2  # the corrupt read counted as a miss


def test_prepare_hit_returns_identical_layout(cache):
    edges = _edges()
    g1 = G.prepare(edges, 200, np.ones((200, 4), np.float32),
                   pad_multiple=128, cache=cache)
    g2 = G.prepare(edges, 200, np.ones((200, 4), np.float32),
                   pad_multiple=128, cache=cache)
    assert cache.misses == 1 and cache.hits == 1
    for field in ("senders", "receivers", "edge_mask", "rev_perm", "deg"):
        np.testing.assert_array_equal(getattr(g1, field), getattr(g2, field))
    for a, b in zip(g1.csr_plan, g2.csr_plan):
        np.testing.assert_array_equal(a, b)
    # and the cached layout equals the uncached build exactly
    g3 = G.prepare(edges, 200, np.ones((200, 4), np.float32),
                   pad_multiple=128, cache=False)
    np.testing.assert_array_equal(g2.senders, g3.senders)
    np.testing.assert_array_equal(g2.receivers, g3.receivers)


def test_prepare_knob_change_misses(cache):
    edges = _edges()
    x = np.ones((200, 4), np.float32)
    G.prepare(edges, 200, x, pad_multiple=128, cache=cache)
    G.prepare(edges, 200, x, pad_multiple=256, cache=cache)
    G.prepare(edges, 200, x, pad_multiple=128, cluster_min_pair=8,
              cache=cache)
    assert cache.hits == 0 and cache.misses == 3


def test_prepare_cluster_split_round_trips(cache):
    # force the cluster split so the pickled payload carries the full
    # ClusterSplit/ClusterPlan structure
    edges = _edges(e=2000)
    x = np.ones((200, 4), np.float32)
    g1 = G.prepare(edges, 200, x, pad_multiple=128, cluster=True,
                   cluster_min_pair=2, cache=cache)
    g2 = G.prepare(edges, 200, x, pad_multiple=128, cluster=True,
                   cluster_min_pair=2, cache=cache)
    assert cache.hits == 1
    assert g1.cluster_split is not None and g2.cluster_split is not None
    assert g1.cluster_split.frac_clustered == g2.cluster_split.frac_clustered
    np.testing.assert_array_equal(g1.cluster_split.c_recv,
                                  g2.cluster_split.c_recv)
    np.testing.assert_array_equal(g1.cluster_split.s_rev_local,
                                  g2.cluster_split.s_rev_local)


def test_split_edges_hit_identical_split_tensors(cache):
    edges = _edges(e=800)
    x = np.ones((200, 4), np.float32)
    s1 = G.split_edges(edges, 200, x, seed=3, pad_multiple=128, cache=cache)
    hits_before = cache.hits
    s2 = G.split_edges(edges, 200, x, seed=3, pad_multiple=128, cache=cache)
    # both the lp-split entry and the edge-layout entry hit
    assert cache.hits >= hits_before + 2
    for f in ("train_pos", "val_pos", "val_neg", "test_pos", "test_neg"):
        np.testing.assert_array_equal(getattr(s1, f), getattr(s2, f))
    np.testing.assert_array_equal(s1.graph.senders, s2.graph.senders)
    # a different seed is a different split → miss
    misses_before = cache.misses
    G.split_edges(edges, 200, x, seed=4, pad_multiple=128, cache=cache)
    assert cache.misses > misses_before


def test_apply_locality_order_cached_identical(cache):
    edges = _edges(e=800)
    x = np.random.default_rng(0).normal(size=(200, 4)).astype(np.float32)
    e1, x1, _, o1 = G.apply_locality_order(edges, x, method="bfs",
                                           cache=cache)
    e2, x2, _, o2 = G.apply_locality_order(edges, x, method="bfs",
                                           cache=cache)
    assert cache.hits == 1
    np.testing.assert_array_equal(o1, o2)
    np.testing.assert_array_equal(e1, e2)
    np.testing.assert_array_equal(x1, x2)
    # method participates in the key
    G.apply_locality_order(edges, x, method="community", cache=cache)
    assert cache.misses == 2


def test_auto_gate_skips_cache_for_small_graphs(tmp_path, monkeypatch):
    # unit-test-sized graphs must never touch the disk under "auto"
    monkeypatch.setenv("HYPERSPACE_CACHE_DIR", str(tmp_path / "auto"))
    import hyperspace_tpu.data.prep_cache as pc

    monkeypatch.setattr(pc, "_default", None)
    G.prepare(_edges(), 200, np.ones((200, 4), np.float32),
              pad_multiple=128, cache="auto")
    assert not (tmp_path / "auto").exists()
    assert pc.stats() == {"hits": 0, "misses": 0}
