"""Realistic-locality dataset machinery (VERDICT r3 #3): the community
power-law generator, the OGB-csv disk roundtrip, and the community
(LPA+BFS) reordering."""

import numpy as np
import pytest

from hyperspace_tpu.data import graphs as G


def _small_graph(seed=0):
    return G.community_power_law_graph(
        num_nodes=3000, num_edges=24000, num_classes=8, feat_dim=16,
        sub_size=120, seed=seed)


def test_generator_shape_statistics():
    edges, x, labels, k = _small_graph()
    n = x.shape[0]
    assert edges.ndim == 2 and edges.shape[1] == 2
    assert np.all(edges >= 0) and np.all(edges < n)
    assert np.all(edges[:, 0] != edges[:, 1])  # no self loops
    assert labels.shape == (n,) and labels.max() < k
    # power-law degrees: hub far above mean
    deg = np.bincount(edges.ravel(), minlength=n)
    assert deg.max() > 10 * deg.mean()
    # community structure: most edges stay within the label group
    same = (labels[edges[:, 0]] == labels[edges[:, 1]]).mean()
    assert same > 0.55, same
    # determinism
    e2, x2, l2, _ = _small_graph()
    np.testing.assert_array_equal(edges, e2)
    np.testing.assert_array_equal(x, x2)


def test_ogb_csv_roundtrip(tmp_path):
    edges, x, labels, k = G.community_power_law_graph(
        num_nodes=200, num_edges=800, num_classes=5, feat_dim=8,
        sub_size=40, seed=1)
    root = str(tmp_path / "ds")
    G.write_ogb_csv_layout(root, edges, x, labels)
    e2, x2, l2, k2 = G.load_ogbn_arxiv(root)
    np.testing.assert_array_equal(e2, edges)
    np.testing.assert_array_equal(l2, labels)
    assert k2 == labels.max() + 1
    np.testing.assert_allclose(x2, x, rtol=1e-4, atol=1e-5)
    # the dispatching loader reports the disk source
    e3, x3, l3, k3, source = G.load_graph("ogbn-arxiv", root)
    assert source == "disk"
    np.testing.assert_array_equal(e3, edges)


def test_community_order_is_permutation_and_deterministic():
    edges, x, labels, k = _small_graph()
    n = x.shape[0]
    order = G.community_order(edges, n)
    assert sorted(order.tolist()) == list(range(n))
    np.testing.assert_array_equal(order, G.community_order(edges, n))
    with pytest.raises(IndexError):
        G.community_order(np.asarray([[0, n]]), n)


def test_community_order_beats_bfs_on_community_graph():
    """The point of the LPA order: more block-clusterable edges than the
    plain BFS on a community-structured graph (measured at full scale
    ~31% vs ~21%; this pins the small-scale direction with slack)."""
    from hyperspace_tpu.kernels.cluster import build_cluster_split

    edges, x, labels, k = _small_graph()
    n = x.shape[0]

    def frac(method):
        e2, x2, _, _ = G.apply_locality_order(edges, x, labels,
                                              method=method)
        g = G.prepare(e2, n, x2, pad_multiple=1024, cluster=False)
        sp = build_cluster_split(g.senders, g.receivers, g.edge_mask,
                                 g.deg, n, bn=64, bs=64, min_pair_edges=32)
        return sp.frac_clustered

    assert frac("community") >= frac("bfs") - 0.02, (
        frac("community"), frac("bfs"))


def test_apply_locality_order_rejects_unknown_method():
    edges, x, labels, k = _small_graph()
    with pytest.raises(ValueError):
        G.apply_locality_order(edges, x, labels, method="sorted")
