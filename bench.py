"""Benchmark entry point — the LAST stdout line is a compact JSON headline.

Output contract (VERDICT r4 missing #1): the driver records only the final
~2000 characters of stdout, so the FINAL line is a compact self-sufficient
headline record (``compact_headline``, hard-capped at ``COMPACT_LIMIT``
chars) and the full ever-growing detail record precedes it (and is written
to ``bench_full.json``).  ``tests/test_bench_cli.py`` asserts the tail
contract so it cannot regress.

Metrics tracked (BASELINE.json "metric"): HGCN samples/sec/chip on
ogbn-arxiv-scale graphs, and Poincaré-embedding epoch time; serving
throughput (``serve_qps`` — queries/s through the batcher + engine) rides
in detail under ``--metric auto`` and is selectable as the headline with
``--metric serve``.  The primary reported metric is selected by
``--metric`` (default: the first available in priority order
hgcn > poincare).  ``vs_baseline`` is null because BASELINE.json
``published`` is empty — no reference number exists in this environment
(SURVEY.md §6).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import signal
import sys
import threading
import time

# wall-clock budget (seconds) for the WHOLE bench run, env-tunable via
# BENCH_BUDGET_S / --budget-s.  BENCH_r05.json was rc=124 with
# ``parsed: null`` — the driver's hard timeout killed the process before
# any JSON landed, losing the whole round's reading; on that round's
# experimental backend even the watchdog timer was starved (native code
# holding the GIL).  Three defenses, layered: (1) the default budget
# sits WELL under the 870 s driver timeout so a slow backend still has
# ~2x headroom, (2) every leg — including the headline benchmark — runs
# under a SIGALRM deadline derived from the remaining budget (a signal
# interrupts Python-level work a threading.Timer can't reach), and
# (3) the last-resort watchdog thread emits whatever completed and
# exits 0 instead of dying unparsed.
DEFAULT_BUDGET_S = 420.0


class _LegTimeout(BaseException):
    """Raised by the SIGALRM deadline inside an over-budget leg.

    Derives from ``BaseException`` (like ``KeyboardInterrupt``) on
    purpose: the benched code is full of defensive ``except Exception``
    blocks (diagnostics, cache fallbacks), and the one-shot alarm firing
    inside one of those must not be swallowed there — the leg would run
    unbounded with the alarm already spent, recreating the BENCH_r05
    overrun this deadline exists to close."""


@contextlib.contextmanager
def _deadline(seconds: float):
    """Hard per-leg deadline: raise :class:`_LegTimeout` in the main
    thread after ``seconds`` via SIGALRM — unlike the watchdog's timer
    thread this interrupts pure-Python overruns (sleeps, slow host prep,
    long sampling loops) at the deadline, not at the next thread switch.
    No-op off the main thread or where SIGALRM does not exist."""
    if (not hasattr(signal, "SIGALRM")
            or threading.current_thread() is not threading.main_thread()):
        yield
        return

    def _raise(signum, frame):
        raise _LegTimeout(f"leg deadline after {seconds:.1f}s")

    old = signal.signal(signal.SIGALRM, _raise)
    signal.setitimer(signal.ITIMER_REAL, max(seconds, 0.001))
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


class _BudgetGuard:
    """Deadline bookkeeping + the emit-once watchdog."""

    def __init__(self, seconds: float):
        self.budget_s = float(seconds)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._claimed = False
        self._timer = None

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def remaining(self) -> float:
        return self.budget_s - self.elapsed()

    def claim_emit(self) -> bool:
        """True exactly once — whoever wins prints the artifact."""
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def arm(self, holder: dict, _exit=os._exit):
        """Watchdog: at the deadline, emit the best record available —
        the in-progress result (legs completed so far) or a bare
        budget_exhausted record — and exit 0.  The main path disarms it
        after its own emit, so the timer only ever fires on a run that
        would otherwise die to the driver's hard timeout with nothing
        parseable on stdout.  (``_exit`` is injectable for tests; the
        real one skips interpreter teardown, so stdout is flushed here.)"""

        def fire():
            if not self.claim_emit():
                return
            import copy

            fallback = {"metric": "budget_exhausted", "value": 0,
                        "unit": "", "vs_baseline": None,
                        "detail": {"budget_s": self.budget_s,
                                   "budget_exhausted": True,
                                   "elapsed_s": round(self.elapsed(), 1)}}
            try:
                # snapshot: the main thread is still mutating detail (a
                # leg mid-flight); serializing the live dict could raise
                # "dictionary changed size during iteration" AFTER the
                # emit was claimed, losing the artifact entirely
                result = copy.deepcopy(holder.get("result"))
                if result is None:
                    result = fallback
                result.setdefault("detail", {})
                result["detail"].update(fallback["detail"])
                emit(result)
            except Exception:  # noqa: BLE001 — emit SOMETHING, always
                print(json.dumps(fallback))
            sys.stdout.flush()
            _exit(0)

        self._timer = threading.Timer(max(self.remaining(), 0.001), fire)
        self._timer.daemon = True
        self._timer.start()

    def disarm(self):
        if self._timer is not None:
            self._timer.cancel()


def _time_steps(stepper, state, n_steps, repeats):
    """(min_seconds, repeat_spread) for ``n_steps`` calls of ``stepper``
    (the shared harness in benchmarks/hgcn_bench.py — one copy of the
    device_get-as-completion-barrier rationale).  The max/min spread
    lets callers record chip contention (VERDICT r4 #9: the Poincaré
    0.174→0.186 drift rode into the artifact with no contention
    marker)."""
    from hyperspace_tpu.benchmarks.hgcn_bench import spread, time_steps_all

    times, _, _ = time_steps_all(stepper, state, n_steps, repeats)
    return min(times), spread(times)


def _poincare_steppers(cfg, pairs, plan_steps):
    """(name -> (stepper, fresh_state)) for the three update strategies:
    dense (whole-table), sparse (device unique), planned (host-planned
    indices, no device sort / unsorted scatter)."""
    import dataclasses

    from hyperspace_tpu.models import poincare_embed as pe

    out = {}
    for name, c in (("dense", cfg),
                    ("sparse", dataclasses.replace(cfg, sparse=True))):
        state, opt = pe.init_state(c)
        step_fn = pe.make_train_step(c)
        out[name] = ((lambda st, c=c, o=opt, f=step_fn: f(c, o, st, pairs)),
                     state)
    state, opt = pe.init_state(cfg)
    plan = pe.plan_sparse_steps(cfg, pairs, plan_steps, seed=0)
    # the packed variant: one row gather + ONE sorted scatter-set per step
    # regardless of optimizer moment count (docs/benchmarks.md)
    out["planned"] = (
        (lambda st, o=opt, p=plan: pe.train_step_planned_packed(cfg, o, st, p)),
        pe.pack_state(cfg, state))
    return out, plan


def _time_planned_scan(cfg, plan, repeats):
    """(wall, spread) of one scanned planned epoch (all plan rows, one
    program)."""
    from hyperspace_tpu.models import poincare_embed as pe

    state, opt = pe.init_state(cfg)
    return _time_steps(
        (lambda st, o=opt, p=plan:
         pe.train_epoch_planned_packed(cfg, o, st, p)),
        pe.pack_state(cfg, state), 1, repeats)


def bench_poincare(repeats: int = 3) -> dict:
    """Epoch time for Poincaré embeddings on a WordNet-noun-scale tree.

    Times three stepwise update strategies — dense (whole-table expmap),
    sparse (device-side unique + row scatter), and planned-packed
    (host-planned indices, one gather + one sorted scatter-set;
    `poincare_embed.train_step_planned_packed`) —
    plus the two scanned-epoch programs (`train_epoch_scan`,
    `train_epoch_planned_packed`: the whole epoch under one `lax.scan`,
    one dispatch instead of steps_per_epoch), reporting the fastest as
    the headline.  ``detail.large_table`` re-times the strategies at an
    arxiv-scale table (≥500 k rows) with riemannian_adam, where the
    per-step moment/table traffic is what the sparse path exists to
    avoid (SURVEY.md §7 hard-part #2).
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.data.wordnet import synthetic_tree
    from hyperspace_tpu.models import poincare_embed as pe

    # WordNet nouns ≈ 82k nodes / ~750k closure pairs; the synthetic stand-in
    # (depth 5, branching 9) gives 66k nodes and a comparable closure size.
    ds = synthetic_tree(depth=5, branching=9)
    cfg = pe.PoincareEmbedConfig(
        num_nodes=ds.num_nodes, dim=10, batch_size=1024, neg_samples=10
    )
    pairs = jnp.asarray(ds.pairs)
    steps_per_epoch = max(1, ds.num_pairs // cfg.batch_size)

    epochs = {}
    spreads = {}
    steppers, plan = _poincare_steppers(cfg, pairs, steps_per_epoch)
    for name, (stepper, state) in steppers.items():
        t, spreads[name] = _time_steps(stepper, state, steps_per_epoch,
                                       repeats)
        epochs[name] = round(t, 4)
    # scanned epochs: all steps_per_epoch steps as ONE XLA program
    # (`train_epoch_scan` / `train_epoch_planned_packed`) — at this table
    # size the per-step device work is tiny, so the stepwise timings above
    # are dominated by dispatch latency the scan removes
    state, opt = pe.init_state(cfg)
    t, spreads["dense_scan"] = _time_steps(
        (lambda st, o=opt: pe.train_epoch_scan(cfg, o, st, pairs,
                                               steps_per_epoch)),
        state, 1, repeats)
    epochs["dense_scan"] = round(t, 4)
    t, spreads["planned_scan"] = (  # plan reused from _poincare_steppers
        _time_planned_scan(cfg, plan, repeats))
    epochs["planned_scan"] = round(t, 4)
    update = min(epochs, key=epochs.get)

    # arxiv-scale table: dense pays O(N) table+moment traffic per step,
    # the planned path O(batch); timed per-step over a fixed step count
    big = synthetic_tree(depth=6, branching=9)
    big_cfg = pe.PoincareEmbedConfig(
        num_nodes=big.num_nodes, dim=10, batch_size=1024, neg_samples=10,
        optimizer="radam")
    big_pairs = jnp.asarray(big.pairs)
    n_big_steps = 50
    large = {"num_nodes": big.num_nodes, "optimizer": "radam"}
    big_steppers, big_plan = _poincare_steppers(big_cfg, big_pairs,
                                                n_big_steps)
    for name, (stepper, state) in big_steppers.items():
        t, _ = _time_steps(stepper, state, n_big_steps, max(2, repeats - 1))
        large[f"{name}_step_ms"] = round(t / n_big_steps * 1e3, 3)
    t, _ = _time_planned_scan(big_cfg, big_plan, max(2, repeats - 1))
    large["planned_scan_step_ms"] = round(t / n_big_steps * 1e3, 3)
    large["update"] = min(
        ("dense", "sparse", "planned", "planned_scan"),
        key=lambda n: large[f"{n}_step_ms"])

    return {
        "metric": "poincare_embed_epoch_time",
        "value": epochs[update],
        "unit": "s",
        "vs_baseline": None,
        "detail": {
            "num_nodes": ds.num_nodes,
            "num_pairs": ds.num_pairs,
            "steps_per_epoch": steps_per_epoch,
            "batch_size": cfg.batch_size,
            **{f"{k}_epoch_s": v for k, v in epochs.items()},
            "update": update,
            # max/min over the timing repeats of the winning strategy —
            # ≫1 marks a contended chip session (VERDICT r4 #9)
            "repeat_spread": spreads.get(update),
            "large_table": large,
            "backend": jax.default_backend(),
        },
    }


def bench_hgcn(repeats: int = 3, dtype: str = "float32",
               agg_dtype: str = "bfloat16", use_att: bool = False,
               step: str = "pairs", decoder_dtype: str | None = "bfloat16") -> dict:
    """HGCN training throughput (samples/sec/chip) on an arxiv-scale graph.

    Default config (validated quality-neutral at full 169 k-node scale
    over 3 seeds — docs/benchmarks.md quality-anchor section): f32
    compute, bf16 *edge messages* and a bf16 decoder pass (everything
    accumulates f32), with the fully-planned-pairs train step whose
    decoder scatters are block-CSR.  Measured 987 k samples/s/chip vs
    812 k for the r01 default on the same chip/session.  ``--step lp
    --decoder-dtype float32 --agg-dtype float32`` reproduces pure-f32;
    ``--dtype bfloat16`` runs everything in bf16 (faster, AUC degrades,
    opt-in); ``--use-att`` benches the attention-aggregation model.
    """
    import jax

    from hyperspace_tpu.benchmarks.hgcn_bench import run_hgcn_bench

    return run_hgcn_bench(repeats=repeats, backend=jax.default_backend(),
                          dtype=dtype, agg_dtype=agg_dtype, use_att=use_att,
                          step=step, decoder_dtype=decoder_dtype)


def bench_sampled(repeats: int = 2) -> dict:
    """Minibatch-trainer detail metric: supervised samples/s (the
    labeled-seeds-per-second unit; docs/benchmarks.md r03b)."""
    from hyperspace_tpu.benchmarks.hgcn_bench import run_sampled_bench

    return run_sampled_bench(repeats=repeats)


# the serve pipeline's stage taxonomy (docs/observability.md "Span-level
# tracing"): the first four are boundary stages — differences of
# consecutive lifecycle stamps that sum to e2e exactly by construction —
# the last two are nested engine windows inside `dispatch`
STAGE_BOUNDARY = ("queue_wait", "collate_wait", "dispatch", "serialize")
STAGE_NAMES = STAGE_BOUNDARY + ("device_compute", "rescore")


def _stage_breakdown(delta, leg: str, e2e_mean=None) -> dict:
    """Per-stage mean + p99 table from a snapshot delta's
    ``hist/serve/stage/<name>_ms`` families, with the decomposition
    invariant CHECKED: the boundary stages' means must sum to the e2e
    mean within 5 % (``e2e_mean`` overrides the delta's own e2e
    histogram when the delta window saw spans-off traffic too).  Raises
    — a silently-drifting decomposition would report a breakdown that
    no longer explains the headline latency."""
    stages: dict = {}
    for name in STAGE_NAMES:
        h = delta.get(f"hist/serve/stage/{name}_ms")
        if h and h["count"]:
            stages[name] = {"n": h["count"],
                            "mean_ms": round(h["sum"] / h["count"], 4),
                            "p99_ms": h["p99"]}
    if e2e_mean is None:
        e2e = delta.get("hist/serve/e2e_ms")
        if e2e and e2e["count"]:
            e2e_mean = e2e["sum"] / e2e["count"]
    if e2e_mean:
        total = sum(stages[s]["mean_ms"] for s in STAGE_BOUNDARY
                    if s in stages)
        ratio = total / e2e_mean
        if not 0.95 <= ratio <= 1.05:
            raise RuntimeError(
                f"{leg}: stage decomposition broke — boundary stages sum "
                f"to {total:.3f} ms vs e2e mean {e2e_mean:.3f} ms "
                f"(ratio {ratio:.3f}, want within 5%)")
        stages["e2e_mean_ms"] = round(e2e_mean, 4)
        stages["sum_vs_e2e"] = round(ratio, 4)
    return stages


def bench_serve(repeats: int = 2) -> dict:
    """Serving throughput: warm ``topk_neighbors`` queries/s per bucket.

    Builds a synthetic Poincaré table, warms one (bucket, k) executable
    per bucket of the request batcher's ladder, then times cache-miss
    batches at each bucket size (min-of-repeats; value = best bucket's
    queries/s).  Also reported: per-bucket **latency percentiles**
    (p50/p95/p99 of the ``serve/e2e_ms`` request histogram, as a DELTA
    over each bucket's timed pass alone — ``detail.latency_ms.b<N>``,
    the SLO contract numbers ROADMAP item 3 will gate on), the
    recompile count during warmup (one per bucket is the contract) and
    during the timed phase (0 is the contract — a nonzero means the
    timings include the compiler), and a cached-batcher pass over a hot
    id set whose hit/padding ratios — counter deltas over that pass
    alone, not the warmup-diluted process-cumulative gauges — land in
    the artifact (docs/benchmarks.md "serve_qps").

    Since r10 an **IVF recall leg** rides along (``detail.ivf``): a
    cluster-structured 50k table, an IVF index built on it
    (serve/index.py), per-nprobe recall@10 vs the exact engine and
    warm qps, and the contract numbers ``qps_at_recall99`` /
    ``speedup_at_recall99`` — the queries/s the approximate path
    sustains while keeping recall@10 >= 0.99, and its ratio to the
    exact scan on the same table.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.serve.batcher import RequestBatcher
    from hyperspace_tpu.serve.engine import QueryEngine
    from hyperspace_tpu.telemetry import registry as telem

    telem.install_jax_monitoring_hook()
    rng = np.random.default_rng(0)
    n, dim, k = 50_000, 16, 10
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    # cache OFF for the timed phase: every id must hit the device path
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=256, cache_size=0)
    reg = telem.default_registry()
    c0 = reg.get("jax/recompiles")
    for b in bat.buckets:  # warmup: one compile per (bucket, k)
        bat.topk(rng.integers(0, n, size=b).tolist(), k)
    c1 = reg.get("jax/recompiles")
    detail = {
        "num_nodes": n, "dim": dim, "k": k, "buckets": list(bat.buckets),
        "chunk_rows": eng.chunk_rows, "scan_mode": eng.scan_mode,
        # scan precision + table dtype as executed: BENCH_r* serve_qps
        # trajectories must be comparable across precision modes
        "precision": eng.precision, "dtype": str(table.dtype),
        "recompiles_warmup": c1 - c0, "backend": jax.default_backend(),
    }
    best = 0.0
    latency = {}
    for b in bat.buckets:
        times = []
        lat_base = reg.mark()  # per-bucket latency delta window
        for _ in range(max(2, repeats)):
            ids = rng.integers(0, n, size=b).tolist()
            t0 = time.perf_counter()
            bat.topk(ids, k)
            times.append(time.perf_counter() - t0)
        qps = b / min(times)
        detail[f"qps_b{b}"] = round(qps, 1)
        best = max(best, qps)
        # p50/p95/p99 of the batcher's per-request e2e histogram over
        # THIS bucket's timed requests alone (mark/snapshot delta) —
        # the per-qps-bucket SLO numbers, sourced from hist/serve/e2e_ms.
        # "n" is the sample count behind them: at the default repeats
        # the window holds only a few requests, and a percentile with
        # its basis hidden would read as sturdier than it is
        e2e = reg.snapshot(baseline=lat_base).get("hist/serve/e2e_ms")
        if e2e:
            latency[f"b{b}"] = {
                "n": e2e["count"],
                **{q: e2e[q] for q in ("p50", "p95", "p99")}}
    detail["latency_ms"] = latency
    detail["recompiles_steady"] = reg.get("jax/recompiles") - c1
    # cache effectiveness: a cached batcher over a small hot id set.
    # The serve counters are process-cumulative and the timed phase
    # above ran cache-DISABLED, so report deltas over this pass alone
    # (registry mark/snapshot) — not the warmup-diluted globals.
    cached = RequestBatcher(eng, min_bucket=8, max_bucket=256)
    base = reg.mark()
    hot = rng.integers(0, 256, size=(8, 100))
    for row in hot:
        cached.topk(row.tolist(), k)
    delta = reg.snapshot(baseline=base)
    hits = delta.get("serve/cache_hit", 0)
    lookups = hits + delta.get("serve/cache_miss", 0)
    slots = delta.get("serve/slots", 0)
    detail["cache"] = {
        "cache_hit": hits,
        "cache_miss": delta.get("serve/cache_miss", 0),
        "cache_hit_rate": round(hits / max(lookups, 1), 4),
        "padded_waste": delta.get("serve/padded_waste", 0),
        "padded_waste_ratio": round(
            delta.get("serve/padded_waste", 0) / max(slots, 1), 4),
    }

    # --- per-stage latency decomposition (ISSUE 17): spans on for a
    # dedicated pass, mean + p99 per stage from the stage histograms
    # (``detail.stages``), and the construction invariant CHECKED at
    # bench load — the four boundary stages are differences of
    # consecutive lifecycle stamps, so their means must sum to the e2e
    # mean within 5 % (a drift means a stage boundary stopped being
    # stamped — exactly the regression this leg exists to catch)
    from hyperspace_tpu.telemetry import spans as _spans

    stage_base = reg.mark()
    _spans.enable()
    try:
        for _ in range(max(2, repeats)):
            bat.topk(rng.integers(0, n, size=64).tolist(), k)
    finally:
        _spans.disable()
    detail["stages"] = _stage_breakdown(
        reg.snapshot(baseline=stage_base), "serve_qps")

    # --- fused_vs_unfused (r12): the Pallas scan-top-k kernel
    # (scan_mode=fused, kernels/scan_topk.py — distance tiles in
    # registers, running top-k in the kernel carry) against the default
    # two-stage scan: SAME 50k table, SAME bucket ladder, paired ids.
    # Per-bucket per-mode failure degrades to a detail error (the r10
    # ivf_error pattern) instead of sinking the leg; the headline
    # serve_fused_speedup is the largest bucket's fused/two_stage qps
    # ratio (where the fused kernel matters most).  On CPU both run XLA
    # (the fused path is the kernel's twin) — the ratio there tracks
    # the twin's merge loop, not the TPU win (docs/benchmarks.md r12).
    def _fused_leg():
        out = {"k": k, "buckets": {}}
        engines = {}
        for m in ("two_stage", "fused"):
            engines[m] = QueryEngine(table, ("poincare", 1.0), scan_mode=m)
        out["chunk_rows"] = {m: e.chunk_rows for m, e in engines.items()}
        for b in bat.buckets:
            ids = rng.integers(0, n, size=b).astype(np.int32)
            row = {}
            for m, e in engines.items():
                try:
                    _, dd = e.topk_neighbors(ids, k)  # compile + warm
                    jax.device_get(dd)
                    ts = []
                    for _ in range(max(2, repeats)):
                        t0 = time.perf_counter()
                        _, dd = e.topk_neighbors(ids, k)
                        jax.device_get(dd)
                        ts.append(time.perf_counter() - t0)
                    row[m] = round(b / min(ts), 1)
                except Exception as err:  # noqa: BLE001 — one mode
                    # failing must not discard the other mode's reading
                    # or the remaining buckets; the deadline _LegTimeout
                    # is a BaseException and still flies through
                    row[f"{m}_error"] = repr(err)
            if row.get("two_stage") and row.get("fused"):
                row["ratio"] = round(row["fused"] / row["two_stage"], 3)
            out["buckets"][f"b{b}"] = row
        # the headline is pinned to the LARGEST bucket (where the fused
        # kernel matters most) and says so — a failed largest bucket
        # leaves it absent rather than silently substituting another
        # bucket's ratio into the gated trend
        top = bat.buckets[-1]
        ratio = out["buckets"][f"b{top}"].get("ratio")
        if ratio is not None:
            out["serve_fused_speedup"] = ratio
            out["speedup_bucket"] = top
        return out

    try:
        detail["fused_vs_unfused"] = _fused_leg()
    except Exception as e:  # noqa: BLE001 — the fused A/B must not
        # sink the serve_qps reading (the deadline _LegTimeout is a
        # BaseException and still flies through)
        detail["fused_error"] = repr(e)

    # --- IVF recall leg (r10): recall@10 vs the exact engine per
    # nprobe, and the headline **qps at recall@10 >= 0.99** (ROADMAP
    # item 2's contract).  The table here is CLUSTER-STRUCTURED (512
    # Poincaré clusters at moderate radii) — the structure real
    # embedding tables have (trees/communities), and the regime an IVF
    # index is for; an isotropic blob admits no sub-linear index by
    # construction (docs/benchmarks.md r10).
    def _ivf_leg():
        from hyperspace_tpu.serve.index import build_index

        ncl, ncells = 512, 192
        centers = rng.standard_normal((ncl, dim)) * 0.25
        vv = (centers[rng.integers(0, ncl, size=n)]
              + rng.standard_normal((n, dim)) * 0.05)
        ctable = np.asarray(PoincareBall(1.0).expmap0(
            jnp.asarray(vv, jnp.float32)))
        ids = rng.integers(0, n, size=256).astype(np.int32)

        def timed_qps(e):
            _, dd = e.topk_neighbors(ids, k)  # compile + warm
            jax.device_get(dd)
            ts = []
            for _ in range(max(2, repeats)):
                t0 = time.perf_counter()
                _, dd = e.topk_neighbors(ids, k)
                jax.device_get(dd)
                ts.append(time.perf_counter() - t0)
            return len(ids) / min(ts)

        ex = QueryEngine(ctable, ("poincare", 1.0))
        exact_qps = timed_qps(ex)
        ei, _ = (np.asarray(a) for a in ex.topk_neighbors(ids, k))
        t0 = time.perf_counter()
        idx = build_index(ctable, ("poincare", 1.0), ncells, iters=8,
                          seed=0, balance=3.0)
        out = {"table": "clustered", "ncells": ncells,
               "max_cell": idx.max_cell,
               "build_s": round(time.perf_counter() - t0, 2),
               "exact_qps": round(exact_qps, 1), "probes": {}}
        qps_at = 0.0
        for npb in (1, 2, 4, 8):
            try:
                e = QueryEngine(ctable, ("poincare", 1.0), index=idx,
                                nprobe=npb)
                ii, _ = (np.asarray(a) for a in e.topk_neighbors(ids, k))
                rec = float(np.mean([len(set(ei[j]) & set(ii[j])) / k
                                     for j in range(len(ids))]))
                qps = timed_qps(e)
            except Exception as e:  # noqa: BLE001 — one probe setting
                # failing (e.g. an under-filled low-nprobe probe on an
                # unlucky platform/seed) must not discard the baseline
                # and the other probes' already-measured rows; the
                # deadline _LegTimeout is a BaseException and still
                # flies through
                out["probes"][f"np{npb}"] = {"error": repr(e)}
                continue
            out["probes"][f"np{npb}"] = {"recall10": round(rec, 4),
                                         "qps": round(qps, 1)}
            if rec >= 0.99:
                qps_at = max(qps_at, qps)
        # the headline pair: best qps among probe settings that keep
        # recall@10 >= 0.99, and its ratio to the exact scan (> 1 means
        # the index pays for itself at production-grade recall)
        out["qps_at_recall99"] = round(qps_at, 1)
        out["speedup_at_recall99"] = round(qps_at / max(exact_qps, 1e-9), 2)
        return out

    try:
        detail["ivf"] = _ivf_leg()
    except Exception as e:  # noqa: BLE001 — the recall leg must not
        # sink the serve_qps reading (the deadline _LegTimeout is a
        # BaseException and still flies through)
        detail["ivf_error"] = repr(e)
    return {"metric": "serve_qps", "value": round(best, 1),
            "unit": "queries/s", "vs_baseline": None, "detail": detail}


def bench_cold_start(repeats: int = 1) -> dict:
    """Cold start → time-to-first-query, as REAL subprocess restarts
    (docs/benchmarks.md r14).

    The serve stack's cold-start cost is compile time: every (bucket,
    k) executable is built on first hit, so a fresh process's first
    query pays XLA (and a cold bucket's first hit pays it again at
    p99).  This leg measures the whole pillar stack end-to-end — spawn
    ``cli.serve serve`` (the stdin JSONL loop) against a small
    artifact, stamp ``spawn → first topk response`` wall-clock
    (``ttfq_ms``), then hold the bucket and read the stats
    ``recompiles`` counter — under three restart regimes:

    - ``cache_off``: persistent compilation cache disabled — the
      historical behavior, every restart recompiles everything;
    - ``warm_cache``: second process over a pre-populated
      ``compile_cache_dir`` — the first query deserializes its
      executable instead of compiling;
    - ``warm_prewarm``: warm cache + ``prewarm=1`` — the whole ladder
      is deserialized BEFORE the first line is read, so the first query
      on ANY bucket is warm (``recompiles_steady`` 0 is the contract).

    Value = the ``warm_prewarm`` ttfq (ms); the regime deltas are the
    pillar's measured win.  CPU note: process spawn + the jax import
    dominate ttfq on this image — the honest floor a restart pays —
    so the cache's effect reads in the ``recompiles_first`` column and
    the off-vs-warm delta, not in the import constant.
    """
    import subprocess
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall

    n, dim, k = 4096, 8, 5
    rng = np.random.default_rng(0)
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))

    def run_once(art: str, cache: str, prewarm: bool,
                 queries: int = 3) -> dict:
        args = [sys.executable, "-m", "hyperspace_tpu.cli.serve", "serve",
                f"artifact={art}", f"compile_cache_dir={cache}",
                f"prewarm={'1' if prewarm else '0'}", f"k={k}",
                "max_bucket=64"]
        # the subprocess pins CPU: the bench process may hold the real
        # chip (libtpu is single-client — a second grab wedges, the
        # r05 loss shape), and the leg's subject is restart + cache
        # mechanics, which the CPU path exercises end-to-end
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        t0 = time.perf_counter()
        proc = subprocess.Popen(args, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True,
                                env=env,
                                cwd=os.path.dirname(os.path.abspath(__file__)))
        try:
            def ask(req: dict) -> dict:
                proc.stdin.write(json.dumps(req) + "\n")
                proc.stdin.flush()
                line = proc.stdout.readline()
                if not line:
                    raise RuntimeError(
                        f"serve subprocess died rc={proc.poll()}")
                return json.loads(line)

            first = ask({"op": "topk", "ids": [0, 1, 2], "k": k})
            ttfq = time.perf_counter() - t0
            if "error" in first:
                raise RuntimeError(f"first query failed: {first}")
            r1 = ask({"op": "stats"})["recompiles"]
            for i in range(queries):  # same bucket, fresh ids: steady state
                ask({"op": "topk", "ids": [3 * i + 3, 3 * i + 4, 3 * i + 5],
                     "k": k})
            r2 = ask({"op": "stats"})["recompiles"]
            proc.stdin.close()
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        return {"ttfq_ms": round(ttfq * 1e3, 1),
                "recompiles_first": r1,
                "recompiles_steady": r2 - r1}

    detail: dict = {"num_nodes": n, "dim": dim, "k": k,
                    "backend": jax.default_backend()}
    with tempfile.TemporaryDirectory() as tmp:
        from hyperspace_tpu.serve import export_artifact

        art = os.path.join(tmp, "artifact")
        export_artifact(art, table, ("poincare", 1.0),
                        model_config={"c": 1.0})
        cache = os.path.join(tmp, "compile_cache")
        detail["cache_off"] = run_once(art, "0", prewarm=False)
        # priming run: prewarm=1 walks the WHOLE ladder, so every bucket
        # executable lands in the persistent cache for the runs below
        detail["cache_cold_prime"] = run_once(art, cache, prewarm=True)
        detail["warm_cache"] = run_once(art, cache, prewarm=False)
        detail["warm_prewarm"] = run_once(art, cache, prewarm=True)
    value = detail["warm_prewarm"]["ttfq_ms"]
    # duplicated under unambiguous names so the compact-field paths work
    # in BOTH auto mode (nested under detail.cold_start) and headline
    # mode (flat detail) — a flat "recompiles_steady" path would also
    # match the serve/serve_http headline details and mislabel them
    detail["cold_ttfq_ms"] = value
    detail["recompiles_steady"] = detail["warm_prewarm"]["recompiles_steady"]
    detail["cold_recompiles_steady"] = detail["recompiles_steady"]
    return {"metric": "cold_ttfq_ms", "value": value, "unit": "ms",
            "vs_baseline": None, "detail": detail}


def open_loop_arrivals(n: int, qps: float, mode: str = "poisson",
                       seed: int = 0):
    """Arrival offsets (seconds from start) for ``n`` requests at a
    fixed OFFERED rate of ``qps`` — the open-loop load model: arrivals
    are scheduled by the clock, never by the previous response, so a
    slow server accumulates queueing instead of silently throttling the
    load (the closed-loop blind spot; docs/benchmarks.md r13).
    ``mode="poisson"`` draws i.i.d. exponential gaps (memoryless
    arrivals — the production-traffic null model); ``"even"`` spaces
    them exactly 1/qps apart (deterministic, for A/B noise control)."""
    import numpy as np

    if n <= 0 or qps <= 0:
        raise ValueError(f"need n > 0 and qps > 0; got n={n} qps={qps}")
    if mode == "even":
        return np.arange(n) / qps
    if mode != "poisson":
        raise ValueError(f"arrivals mode {mode!r} (want poisson|even)")
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / qps, size=n))


def bench_serve_http(repeats: int = 2, *, qps: float = 120.0,
                     duration_s: float = 2.0, table_rows: int = 20_000,
                     arrivals: str = "poisson",
                     overload_qps: float = 1200.0,
                     overload_s: float = 0.8) -> dict:
    """HTTP front-door latency at FIXED OFFERED LOAD (docs/serving.md
    "HTTP front door", docs/benchmarks.md r13).

    Starts the asyncio server (serve/server.py) over a continuous-
    batching collator in-process, warms every bucket executable
    closed-loop, then drives an **open-loop generator** (fixed offered
    qps, Poisson or evenly-spaced arrivals, one in-process asyncio
    client connection per request) through ``POST /v1/topk``:

    - ``repeats`` passes per request-size class (1 / 16 / 64 ids — the
      b8/b16/b64 rungs they pad to when alone), each class reporting
      p50/p95/p99 of ``serve/e2e_ms`` as a registry mark/snapshot DELTA
      over its passes (``detail.latency_ms.b<N>``; more repeats = more
      samples behind the percentiles, the open-loop analog of
      min-of-N), plus the aggregate distribution across all passes —
      ``http_p99_ms``, the compact headline;
    - ``recompiles_steady`` over the timed passes (0 is the contract —
      the warmup covers the ladder, so collation can never hand the
      compiler a fresh shape mid-leg);
    - an **overload pass**: offered load far past capacity into a
      ``queue_max=8`` bounded batcher — every request is answered and
      the excess sheds with HTTP 429 (``shed_rate``), never unbounded
      queueing.

    Value = the aggregate p99 (ms) at the configured offered load.
    CPU readings are wall-clock noisy; the shed/recompile columns are
    the stable contract rows.
    """
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.serve.batcher import RequestBatcher, bucket_for
    from hyperspace_tpu.serve.engine import QueryEngine
    from hyperspace_tpu.serve.server import HttpFrontDoor
    from hyperspace_tpu.telemetry import registry as telem

    telem.install_jax_monitoring_hook()
    rng = np.random.default_rng(0)
    n, dim, k = table_rows, 16, 10
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    # cache OFF so every request exercises the collated device path;
    # admission bound generous — the timed passes must not shed
    bat = RequestBatcher(eng, min_bucket=8, max_bucket=64, cache_size=0,
                         queue_max=256)
    reg = telem.default_registry()

    async def _post(host, port, payload):
        reader, writer = await asyncio.open_connection(host, port)
        try:
            body = json.dumps(payload).encode("utf-8")
            writer.write(
                (f"POST /v1/topk HTTP/1.1\r\nHost: bench\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 "Connection: close\r\n\r\n").encode("latin-1") + body)
            await writer.drain()
            data = await reader.read()
        finally:
            writer.close()
        head, _, _body = data.partition(b"\r\n\r\n")
        return int(head.split(None, 2)[1])

    async def _open_loop(host, port, sizes, pass_qps, n_req, seed):
        """Fire n_req requests of ``sizes``-id batches at pass_qps;
        returns {status: count}.  Arrival times come from the clock
        (open loop), not from responses."""
        offsets = open_loop_arrivals(n_req, pass_qps, arrivals, seed)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        tasks = []
        for off in offsets:
            delay = t0 + float(off) - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            ids = rng.integers(0, n, size=sizes).tolist()
            tasks.append(asyncio.ensure_future(
                _post(host, port, {"ids": ids, "k": k})))
        results = await asyncio.gather(*tasks, return_exceptions=True)
        statuses: dict = {}
        for r in results:
            key = (f"error:{type(r).__name__}"
                   if isinstance(r, BaseException) else str(int(r)))
            statuses[key] = statuses.get(key, 0) + 1
        return statuses

    def _percentiles(delta):
        e2e = delta.get("hist/serve/e2e_ms")
        if not e2e:
            return None
        return {"n": e2e["count"],
                **{q: e2e[q] for q in ("p50", "p95", "p99")}}

    async def _run():
        detail = {
            "num_nodes": n, "dim": dim, "k": k,
            "buckets": list(bat.buckets), "offered_qps": qps,
            "arrivals": arrivals, "duration_s": duration_s,
            "backend": jax.default_backend(),
        }
        door = HttpFrontDoor(bat, max_wait_us=2000)
        await door.start()
        c0 = reg.get("jax/recompiles")
        # closed-loop warmup: one compile per (bucket, k) — every rung
        # of the ladder, so collation can never surface a cold shape
        # during the timed passes
        for b in bat.buckets:
            await _post(door.host, door.port,
                        {"ids": rng.integers(0, n, size=b).tolist(),
                         "k": k})
        c1 = reg.get("jax/recompiles")
        detail["recompiles_warmup"] = c1 - c0

        latency = {}
        agg_base = reg.mark()
        n_req = max(8, int(qps * duration_s))
        # one size class per ladder region: single-id (the continuous-
        # batching regime — collation forms its buckets), a mid bucket,
        # and the top bucket; each pads to a DISTINCT rung when alone.
        # ``repeats`` open-loop passes per class widen the sample count
        # behind the percentiles (the open-loop analog of min-of-N).
        for si, size in enumerate((1, 16, 64)):
            pass_base = reg.mark()
            statuses: dict = {}
            for rep in range(max(1, repeats)):
                got = await _open_loop(door.host, door.port, size, qps,
                                       n_req, 16 * si + rep)
                for key, v in got.items():
                    statuses[key] = statuses.get(key, 0) + v
            row = _percentiles(reg.snapshot(baseline=pass_base)) or {}
            row["statuses"] = statuses
            latency[f"b{bucket_for(size, bat.buckets)}"] = row
        detail["latency_ms"] = latency
        agg = _percentiles(reg.snapshot(baseline=agg_base))
        if agg is None:
            # no request observed a latency = none succeeded: the leg
            # FAILED — never emit p99=0, which the lower-is-better
            # trend gate would read as the best round ever
            await door.drain()
            raise RuntimeError(
                "serve_http: no successful timed request — statuses "
                f"{ {k: v['statuses'] for k, v in latency.items()} }")
        detail["aggregate_ms"] = agg
        detail["http_p99_ms"] = agg["p99"]
        detail["recompiles_steady"] = reg.get("jax/recompiles") - c1

        # observability-overhead pairs: the SAME shapes with the access
        # log + SLO window + SPAN LAYER armed vs off — the "~free when
        # on" contract (docs/observability.md; the span layer's budget
        # is <= 1.05x, ISSUE 17).  Order is BALANCED (off,on,on,off)
        # and each mode takes its min-of-N p99: on a noisy CPU host
        # whichever pass runs first in a pair reads slower for reasons
        # that have nothing to do with instrumentation (measured 0.4–
        # 2.6× swings with the order reversed) — min-of-N per mode is
        # the repo's standard noise treatment, applied per mode here
        import tempfile

        from hyperspace_tpu.serve.access import AccessLog
        from hyperspace_tpu.telemetry import spans as _spans
        from hyperspace_tpu.telemetry.window import SloWindow

        obs_n = max(8, n_req // 2)
        obs_dir = tempfile.mkdtemp(prefix="bench_obs_")
        alog = AccessLog(os.path.join(obs_dir, "access.jsonl"))
        p99s: dict = {"off": [], "on": []}
        stage_base = reg.mark()  # only on-passes feed stage histograms
        on_e2e_sum = 0.0
        on_e2e_n = 0
        try:
            for i, mode in enumerate(("off", "on", "on", "off")):
                if mode == "on":
                    bat.access_sink = alog.emit
                    bat.window = SloWindow(30.0)
                    _spans.enable()
                pass_base = reg.mark()
                await _open_loop(door.host, door.port, 16, qps, obs_n,
                                 40 + i)
                pass_delta = reg.snapshot(baseline=pass_base)
                row = _percentiles(pass_delta)
                _spans.disable()
                bat.access_sink = None
                bat.window = None
                if mode == "on":
                    # the on-passes' own e2e basis for the stage-sum
                    # check (the stage window below spans off-passes
                    # whose e2e carries no stage samples)
                    e2e = pass_delta.get("hist/serve/e2e_ms")
                    if e2e and e2e["count"]:
                        on_e2e_sum += e2e["sum"]
                        on_e2e_n += e2e["count"]
                if row:
                    p99s[mode].append(row["p99"])
        finally:
            _spans.disable()
            bat.access_sink = None
            bat.window = None
            alog.close()
            import shutil

            shutil.rmtree(obs_dir, ignore_errors=True)
        if p99s["off"] and p99s["on"] and min(p99s["off"]):
            off_p99, on_p99 = min(p99s["off"]), min(p99s["on"])
            detail["observability"] = {
                "requests_per_pass": obs_n,
                "p99_off_ms": off_p99, "p99_on_ms": on_p99,
                "p99_pairs": p99s,
                "access_lines": alog.lines,
                "overhead_ratio": round(on_p99 / off_p99, 4),
            }
        else:
            detail["observability"] = {"error": "paired pass empty",
                                       "pairs": p99s}
        # the per-stage breakdown beside http_p99_ms (ISSUE 17): mean +
        # p99 per stage over the spans-on passes, with the boundary-sum
        # == e2e invariant checked against those passes' own e2e mean
        detail["stages"] = _stage_breakdown(
            reg.snapshot(baseline=stage_base), "serve_http",
            e2e_mean=(on_e2e_sum / on_e2e_n if on_e2e_n else None))
        await door.drain()

        # overload pass: offered load far past capacity into a small
        # bounded queue — the excess must shed with HTTP 429 (never
        # queue unboundedly) and EVERY request must still be answered
        obat = RequestBatcher(eng, min_bucket=8, max_bucket=64,
                              cache_size=0, queue_max=8,
                              deadline_ms=1000.0, ladder_down_after=3)
        odoor = HttpFrontDoor(obat, max_wait_us=2000)
        await odoor.start()
        offered = max(16, int(overload_qps * overload_s))
        statuses = await _open_loop(odoor.host, odoor.port, 1,
                                    overload_qps, offered, 99)
        await odoor.drain()
        answered = sum(v for s, v in statuses.items()
                       if not s.startswith("error"))
        shed = statuses.get("429", 0)
        detail["overload"] = {
            "offered": offered, "offered_qps": overload_qps,
            "queue_max": 8, "statuses": statuses,
            "answered": answered,
            "shed": shed,
            "deadline_exceeded": statuses.get("504", 0),
        }
        detail["shed_rate"] = round(shed / offered, 3)
        detail["deadline_rate"] = round(
            statuses.get("504", 0) / offered, 3)
        return detail

    detail = asyncio.run(_run())
    return {"metric": "serve_http_p99_ms", "value": detail["http_p99_ms"],
            "unit": "ms", "vs_baseline": None, "detail": detail}


def bench_live_index(repeats: int = 1, *, qps: float = 80.0,
                     duration_s: float = 3.0,
                     table_rows: int = 6_000) -> dict:
    """Live mutable index under sustained load (docs/serving.md "Live
    index and rollover", ISSUE 18).

    One in-process HTTP front door over a :class:`LiveQueryEngine`
    (serve/delta.py) with the rollover coordinator armed
    (serve/rollover.py), driven through three phases:

    - **freshness**: serialized insert → query-by-the-new-id probes
      (each inserted vector is a near-duplicate of a known anchor row,
      so the probe's top-1 is checkable), then deletes with
      must-not-return probes, then one explicit compaction —
      ``upsert_visible_ms`` is the enqueue→applied histogram the
      batcher's mutation envelope observes (PR 15 machinery);
    - **steady + rollover**: an open-loop query stream at fixed offered
      qps CONCURRENT with a continuous upsert stream and sequential
      staleness probes (upsert a near-duplicate, immediately query it
      through the result cache — the generation-folded scan signature
      must make the pre-mutation cache rows unreachable), with a full
      blue-green rollover fired mid-stream; ``p99_during_rollover_ms``
      is the e2e delta over the rollover span, and the steady-state
      recompile counters are split pre-roll / rollover / post-flip
      (the contract: 0 outside the rollover's own standby build);
    - **oracle**: final live answers vs a frozen engine rebuilt from
      scratch over the final master table (deleted ids host-filtered
      from an overfetched oracle top-k) — ``recall_vs_oracle``.

    Value = the aggregate e2e p99 (ms) over the concurrent phase.  The
    contract columns are ``errors`` / ``stale_results`` /
    ``recompiles_steady`` — all must be 0 (``live_ok``).
    """
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.parallel.host_table import HostEmbedTable
    from hyperspace_tpu.serve.batcher import RequestBatcher
    from hyperspace_tpu.serve.delta import LiveQueryEngine
    from hyperspace_tpu.serve.engine import QueryEngine
    from hyperspace_tpu.serve.rollover import RolloverCoordinator
    from hyperspace_tpu.serve.server import HttpFrontDoor
    from hyperspace_tpu.telemetry import registry as telem

    telem.install_jax_monitoring_hook()
    rng = np.random.default_rng(7)
    n, dim, k, cap = table_rows, 16, 10, 512
    spec = ("poincare", 1.0)
    base_arr = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))

    def _make_batcher(arr):
        live = LiveQueryEngine(
            QueryEngine(np.array(arr), spec),
            HostEmbedTable.from_array(np.array(arr)),
            capacity=cap, auto_compact=False)
        # cache ON on purpose: the staleness probes below are only a
        # proof if a stale cache row COULD have answered them
        return live, RequestBatcher(live, min_bucket=8, max_bucket=64,
                                    cache_size=4096, queue_max=256)

    live, bat = _make_batcher(base_arr)
    reg = telem.default_registry()
    deleted_ids: set = set()
    # disjoint id pools so concurrent writers never collide: the random
    # update stream, the probe ids (rewritten to near-duplicates of...)
    # and the probe TARGET anchors (...which must stay untouched)
    update_pool = rng.permutation(n)[:128].tolist()
    probe_pool = [int(i) for i in range(n) if i not in set(update_pool)]
    probe_ids, anchor_ids = probe_pool[:200], probe_pool[200:400]

    async def _http(host, port, method, path, payload=None):
        reader, writer = await asyncio.open_connection(host, port)
        try:
            body = (b"" if payload is None
                    else json.dumps(payload).encode("utf-8"))
            writer.write(
                (f"{method} {path} HTTP/1.1\r\nHost: bench\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 "Connection: close\r\n\r\n").encode("latin-1") + body)
            await writer.drain()
            data = await reader.read()
        finally:
            writer.close()
        head, _, rbody = data.partition(b"\r\n\r\n")
        try:
            parsed = json.loads(rbody.decode("utf-8"))
        except ValueError:
            parsed = None
        return int(head.split(None, 2)[1]), parsed

    def _percentiles(delta, name="hist/serve/e2e_ms"):
        h = delta.get(name)
        if not h:
            return None
        return {"n": h["count"], **{q: h[q] for q in ("p50", "p95", "p99")}}

    async def _run():
        detail = {
            "num_nodes": n, "dim": dim, "k": k, "delta_cap": cap,
            "offered_qps": qps, "duration_s": duration_s,
            "backend": jax.default_backend(),
        }
        door = HttpFrontDoor(bat, max_wait_us=2000)

        def standby_builder(target):
            # in-process blue-green: the standby is rebuilt from the
            # CURRENT live master (write-through makes it the truth) and
            # the known tombstones are re-applied before the flip gate
            cur = door.batcher.engine
            live2, bat2 = _make_batcher(cur.master.to_array())
            if deleted_ids:
                live2.delete(sorted(deleted_ids))
            return bat2

        door.rollover = RolloverCoordinator(door, standby_builder,
                                            prewarm_ks=(k,))
        await door.start()
        host, port = door.host, door.port
        c0 = reg.get("jax/recompiles")
        # warm the whole ladder through the LIVE path (base scan with
        # the traced drop mask + the delta-segment scan per bucket)
        for b in bat.buckets:
            await _http(host, port, "POST", "/v1/topk",
                        {"ids": rng.integers(0, n, size=b).tolist(),
                         "k": k})
        detail["recompiles_warmup"] = reg.get("jax/recompiles") - c0

        stale = errors = 0
        next_id = n

        # --- phase 1: freshness (serialized insert/delete probes) -----
        ins_n, del_m = 8 * max(1, repeats), 4 * max(1, repeats)
        fresh_base = reg.mark()
        inserted = []
        for i in range(ins_n):
            anchor = int(anchor_ids[-(i + 1)])
            vec = base_arr[anchor] + rng.normal(0, 1e-4, dim)
            s, _r = await _http(host, port, "POST", "/v1/upsert",
                                {"ids": [next_id],
                                 "rows": [vec.tolist()]})
            errors += s != 200
            s, r = await _http(host, port, "POST", "/v1/topk",
                               {"ids": [next_id], "k": k})
            if s != 200:
                errors += 1
            elif r["neighbors"][0][0] != anchor:
                stale += 1  # the new row's nearest MUST be its anchor
            inserted.append(next_id)
            next_id += 1
        for di, gone in enumerate(inserted[:del_m]):
            s, _r = await _http(host, port, "POST", "/v1/delete",
                                {"ids": [gone]})
            errors += s != 200
            # query the tombstone's OWN anchor: the near-duplicate
            # would be its top-1 if any stale row could still answer
            s, r = await _http(host, port, "POST", "/v1/topk",
                               {"ids": [int(anchor_ids[-(di + 1)])],
                                "k": k})
            if s != 200:
                errors += 1
            elif gone in r["neighbors"][0]:
                stale += 1
            deleted_ids.add(gone)
        detail["freshness"] = {
            "inserted": ins_n, "deleted": del_m,
            "upsert_visible_ms": _percentiles(
                reg.snapshot(baseline=fresh_base),
                "hist/serve/upsert_visible_ms"),
        }
        # one explicit compaction (auto_compact stays off so the timed
        # phase below cannot hide a compile in a background thread);
        # the re-clustered base is a NEW table shape — re-warm it and
        # book those compiles to the compaction, not to steady state
        c_pre = reg.get("jax/recompiles")
        detail["compaction"] = live.compact()
        for b in bat.buckets:
            await _http(host, port, "POST", "/v1/topk",
                        {"ids": rng.integers(0, n, size=b).tolist(),
                         "k": k})
        detail["recompiles_compaction"] = reg.get("jax/recompiles") - c_pre

        # --- phase 2: steady load + mid-stream blue-green rollover ----
        h0 = (await _http(host, port, "GET", "/healthz"))[1]
        stop = asyncio.Event()
        pause = asyncio.Event()
        probe_lock = asyncio.Lock()
        statuses: dict = {}

        async def query_stream():
            n_req = max(16, int(qps * duration_s))
            offsets = open_loop_arrivals(n_req, qps, "poisson", 3)
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            tasks = []
            for off in offsets:
                delay = t0 + float(off) - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                ids = rng.integers(0, n, size=4).tolist()
                tasks.append(asyncio.ensure_future(
                    _http(host, port, "POST", "/v1/topk",
                          {"ids": ids, "k": k})))
            for s, _r in await asyncio.gather(*tasks):
                statuses[str(s)] = statuses.get(str(s), 0) + 1

        async def update_stream():
            i = 0
            while not stop.is_set():
                uid = int(update_pool[i % len(update_pool)])
                # pure-numpy ball point: the steady phase must not run
                # ANY fresh jax op (its tiny one-time compiles would
                # read as steady-state recompile pollution)
                g = rng.standard_normal(dim) * 0.3
                vec = g / (1.0 + float(np.linalg.norm(g)))
                s, _r = await _http(host, port, "POST", "/v1/upsert",
                                    {"ids": [uid],
                                     "rows": [vec.tolist()]})
                statuses[str(s)] = statuses.get(str(s), 0) + 1
                i += 1
                await asyncio.sleep(1.0 / max(qps / 5.0, 1.0))

        probe_stats = {"probes": 0}

        async def probe_stream():
            nonlocal stale, errors
            i = 0
            while not stop.is_set():
                if pause.is_set():
                    await asyncio.sleep(0.05)
                    continue
                async with probe_lock:
                    p = int(probe_ids[i % len(probe_ids)])
                    q = int(anchor_ids[i % (len(anchor_ids) - ins_n)])
                    vec = base_arr[q] + rng.normal(0, 1e-4, dim)
                    s1, _r = await _http(host, port, "POST", "/v1/upsert",
                                         {"ids": [p],
                                          "rows": [vec.tolist()]})
                    s2, r = await _http(host, port, "POST", "/v1/topk",
                                        {"ids": [p], "k": k})
                    if s1 != 200 or s2 != 200:
                        errors += 1
                    elif r["neighbors"][0][0] != q:
                        stale += 1  # a cached pre-mutation row answered
                    probe_stats["probes"] += 1
                i += 1
                await asyncio.sleep(0.1)

        steady_base = reg.mark()
        c_steady0 = reg.get("jax/recompiles")
        qtask = asyncio.ensure_future(query_stream())
        utask = asyncio.ensure_future(update_stream())
        ptask = asyncio.ensure_future(probe_stream())
        await asyncio.sleep(duration_s * 0.35)
        # quiesce the probes (an upsert→verify pair must not straddle
        # the flip: its write would land on the outgoing engine), then
        # roll over mid-stream with queries + updates still flowing
        async with probe_lock:
            pause.set()
        c_roll0 = reg.get("jax/recompiles")
        roll_base = reg.mark()
        t_roll = time.perf_counter()
        s, flip = await _http(host, port, "POST", "/admin/rollover",
                              {"target": "inproc-standby"})
        roll_s = time.perf_counter() - t_roll
        errors += s != 200
        detail["p99_during_rollover_ms"] = (_percentiles(
            reg.snapshot(baseline=roll_base)) or {}).get("p99")
        c_flip = reg.get("jax/recompiles")
        pause.clear()
        await qtask
        stop.set()
        await asyncio.gather(utask, ptask)
        h1 = (await _http(host, port, "GET", "/healthz"))[1]
        agg = _percentiles(reg.snapshot(baseline=steady_base))
        if agg is None:
            await door.drain()
            raise RuntimeError(
                f"live_index: no successful timed request — {statuses}")
        detail["aggregate_ms"] = agg
        detail["live_p99_ms"] = agg["p99"]
        detail["achieved_qps"] = round(agg["n"] / duration_s, 1)
        detail["statuses"] = statuses
        detail["staleness_probes"] = probe_stats["probes"]
        errors += sum(v for key, v in statuses.items() if key != "200")
        detail["rollover"] = {
            "flip": flip, "seconds": round(roll_s, 3),
            "fingerprint_changed": h0["fingerprint"] != h1["fingerprint"],
        }
        detail["recompiles_preroll"] = c_roll0 - c_steady0
        detail["recompiles_rollover"] = c_flip - c_roll0
        detail["recompiles_steady"] = (reg.get("jax/recompiles") - c_flip
                                       + detail["recompiles_preroll"])
        await door.drain()

        # --- phase 3: recall vs a rebuilt-from-scratch frozen oracle --
        cur = door.batcher.engine
        arr = cur.master.to_array()
        oracle = QueryEngine(np.array(arr), spec)
        probe = rng.permutation(n)[:48].astype(np.int64)
        li, _ld = cur.topk_neighbors(probe, k)
        oi, _od = oracle.topk_neighbors(
            probe, k + len(deleted_ids), exclude_self=True)
        oi = np.asarray(oi)
        hits = 0
        for row in range(probe.size):
            want = [j for j in oi[row].tolist()
                    if j not in deleted_ids][:k]
            hits += len(set(np.asarray(li)[row].tolist()) & set(want))
        detail["recall_vs_oracle"] = round(hits / (probe.size * k), 4)
        detail["errors"] = errors
        detail["stale_results"] = stale
        detail["live_ok"] = (errors == 0 and stale == 0
                             and detail["recompiles_steady"] == 0
                             and detail["recall_vs_oracle"] >= 0.99)
        return detail

    detail = asyncio.run(_run())
    return {"metric": "live_index_p99_ms", "value": detail["live_p99_ms"],
            "unit": "ms", "vs_baseline": None, "detail": detail}


def bench_resilience(repeats: int = 1) -> dict:
    """Chaos recovery + overload shedding (docs/resilience.md).

    Two sub-legs, both assertions-as-measurements — the artifact rows
    ARE the acceptance evidence the chaos suite gates on:

    - **chaos_train**: a tiny Poincaré run with one seeded NaN fault
      (``train.step_nan``) under ``rollback=2`` — recovery means the
      run completes its full step budget with a finite loss and
      EXACTLY ONE rollback; the row records both.
    - **overload**: a bounded-queue batcher (``queue_max=4``,
      ``deadline_ms=250``) hammered by 16 concurrent threads — the
      shed-rate column, the degradation ladder's peak level and
      whether it recovered (hysteresis observed), and the p99 of
      admitted ``serve/e2e_ms`` vs the deadline.
    """
    import tempfile
    import threading

    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.resilience import faults
    from hyperspace_tpu.serve.batcher import RequestBatcher
    from hyperspace_tpu.serve.engine import QueryEngine
    from hyperspace_tpu.serve.errors import ServeError
    from hyperspace_tpu.telemetry import registry as telem

    detail: dict = {}
    reg = telem.default_registry()

    # --- chaos train: poisoned chunk -> one rollback -> finite finish
    from hyperspace_tpu.data.wordnet import synthetic_tree
    from hyperspace_tpu.models import poincare_embed as pe
    from hyperspace_tpu.train import loop as train_loop

    ds = synthetic_tree(depth=4, branching=3)
    cfg = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=8,
                                 batch_size=64, neg_samples=8,
                                 burnin_steps=0)
    state, opt = pe.init_state(cfg, seed=0)
    step_fn = pe.make_train_step(cfg)
    pairs = jnp.asarray(ds.pairs)

    class _Run:  # duck-typed RunConfig (the loop's contract)
        steps, eval_every, log, tensorboard_dir = 24, 6, None, None
        ckpt_every, resume = 6, False
        rollback, rollback_lr_backoff = 2, 0.5

    base = reg.mark()
    with tempfile.TemporaryDirectory() as tmp:
        _Run.ckpt_dir = os.path.join(tmp, "ck")
        faults.install([faults.FaultSpec(site="train.step_nan",
                                         kind="nan", after=8)])
        try:
            state, loss = train_loop.run_loop(
                _Run(), state, lambda st: step_fn(cfg, opt, st, pairs))
        finally:
            faults.clear()
    delta = reg.snapshot(baseline=base)
    final_loss = float(loss)
    detail["chaos_train"] = {
        "steps": int(state.step),
        "final_loss": round(final_loss, 4),
        "final_loss_finite": final_loss == final_loss,
        "rollbacks": int(delta.get("resilience/rollbacks", 0)),
        "faults_fired": int(delta.get("fault/fired", 0)),
        "recovered": (final_loss == final_loss
                      and delta.get("resilience/rollbacks", 0) == 1),
    }

    # --- overload: bounded queue + ladder under 16 concurrent threads
    rng = np.random.default_rng(0)
    n, dim, k = 20_000, 16, 10
    deadline_ms, queue_max, workers, per_worker = 250.0, 4, 16, 6
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))
    eng = QueryEngine(table, ("poincare", 1.0))
    # down_after=3: the queue-full shed path must show BEFORE the
    # ladder degrades (instant cache-only refusals would otherwise
    # drain the queue so fast it never fills again)
    bat = RequestBatcher(eng, cache_size=0, queue_max=queue_max,
                         deadline_ms=deadline_ms, ladder_down_after=3,
                         ladder_up_after=3)
    # warm the compile OUTSIDE the deadline (first call pays XLA)
    bat.topk(rng.integers(0, n, size=64).tolist(), k, deadline_ms=60_000)
    base = reg.mark()
    outcomes = {"served": 0, "error": 0}
    kinds: dict = {}
    olock = threading.Lock()
    barrier = threading.Barrier(workers)
    max_level = {"v": 0}

    def worker(wid):
        wrng = np.random.default_rng(wid)
        barrier.wait()
        for _ in range(per_worker):
            ids = wrng.integers(0, n, size=64).tolist()
            try:
                bat.topk(ids, k)
                with olock:
                    outcomes["served"] += 1
            except ServeError as e:
                with olock:
                    outcomes["error"] += 1
                    kinds[e.kind] = kinds.get(e.kind, 0) + 1
            with olock:
                max_level["v"] = max(max_level["v"], bat._ladder.level)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # calm sequential traffic: the ladder must step back up (hysteresis)
    hot = rng.integers(0, 256, size=8).tolist()
    for _ in range(12):
        try:
            bat.topk(hot, k)
        except ServeError:
            pass  # early calm calls may still be cache-only
        if bat._ladder.level == 0:
            break
    delta = reg.snapshot(baseline=base)
    offered = workers * per_worker
    shed = int(delta.get("serve/shed", 0))
    e2e = delta.get("hist/serve/e2e_ms") or {}
    detail["overload"] = {
        "offered": offered, "queue_max": queue_max, "workers": workers,
        "deadline_ms": deadline_ms,
        "served": outcomes["served"], "errors": kinds,
        # shed = queue-full refusals (serve/shed); refused_rate adds the
        # ladder's cache-only refusals — both answer `overloaded`
        "shed": shed, "shed_rate": round(shed / offered, 3),
        "refused_rate": round(kinds.get("overloaded", 0) / offered, 3),
        "deadline_exceeded": int(delta.get("serve/deadline_exceeded", 0)),
        "degraded": int(delta.get("serve/degraded", 0)),
        "degrade_recovered": int(delta.get("serve/degrade_recovered", 0)),
        "degrade_max_level": max_level["v"],
        "ladder_recovered": bat._ladder.level == 0,
        "e2e_p99_ms": e2e.get("p99"),
        "p99_within_deadline": (e2e.get("p99") is not None
                                and e2e["p99"] <= deadline_ms),
    }
    ok = (detail["chaos_train"]["recovered"]
          and detail["overload"]["ladder_recovered"])
    return {"metric": "resilience_ok", "value": int(ok), "unit": "bool",
            "vs_baseline": None, "detail": detail}


def bench_multihost(repeats: int = 1, *, steps: int = 24,
                    chunk: int = 8) -> dict:
    """Pod-scaling leg (r19): the SAME chunked HGCN LP workload timed
    as a 1-process run and as a REAL 2-process × 2-virtual-device
    ``jax.distributed`` loopback fleet (``benchmarks/mh_worker.py
    --task bench`` — each process times its replica, process 0
    aggregates behind a coordination barrier).

    Rows per process count: step time, aggregate fleet throughput
    (``steps_per_s`` — nprocs replicas × steps / slowest process).
    Headline value = ``scaling_efficiency`` — 2-proc fleet throughput
    over 2× the 1-proc throughput (1.0 = perfect linear scaling; CPU
    loopback runs share cores, so well under 1.0 is expected and the
    TREND, not the level, is the signal).  ``multihost_ok`` gates the
    reading: per-chunk loss trajectories at both process counts must
    be finite and match (the degenerate-DP determinism contract —
    docs/multihost.md), so a scaling number from diverged replicas can
    never look green.

    Worker groups are bounded subprocesses, killed on ANY exit from
    this leg (including the SIGALRM ``_LegTimeout``) — a deadline here
    must not strand orphans holding the artifact's stdout tail.
    """
    import subprocess
    import tempfile

    import numpy as np

    root = os.path.dirname(os.path.abspath(__file__))

    def _run_group(nprocs: int, workdir: str, timeout: float) -> dict:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # workers set their own device count
        extra = env.get("PYTHONPATH")
        env["PYTHONPATH"] = os.pathsep.join(
            [root] + (extra.split(os.pathsep) if extra else []))
        procs = [subprocess.Popen(
            [sys.executable, "-m", "hyperspace_tpu.benchmarks.mh_worker",
             "--pid", str(p), "--nprocs", str(nprocs),
             "--port", str(port), "--workdir", workdir,
             "--task", "bench", "--steps", str(steps),
             "--chunk", str(chunk)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for p in range(nprocs)]
        outs = []
        try:
            for pr in procs:
                out, _ = pr.communicate(timeout=timeout)
                outs.append(out)
        finally:
            for pr in procs:  # no orphans on timeout or _LegTimeout
                if pr.poll() is None:
                    pr.kill()
                    pr.wait()
        for pr, out in zip(procs, outs):
            if pr.returncode != 0:
                raise RuntimeError(
                    f"multihost worker rc={pr.returncode}: {out[-400:]}")
        for out in outs:
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    return json.loads(line[len("RESULT "):])
        raise RuntimeError("no RESULT line from multihost group")

    detail: dict = {"steps": steps, "chunk": chunk, "procs": {}}
    with tempfile.TemporaryDirectory() as tmp:
        for nprocs in (1, 2):
            best = None
            for r in range(max(1, repeats)):
                wd = os.path.join(tmp, f"n{nprocs}_r{r}")
                os.makedirs(wd, exist_ok=True)
                res = _run_group(nprocs, wd, timeout=120)
                if best is None or res["steps_per_s"] > best["steps_per_s"]:
                    best = res
            detail["procs"][str(nprocs)] = {
                "step_time_s": round(best["step_time_s"], 6),
                "steps_per_s": round(best["steps_per_s"], 1),
                "elapsed_s": round(best["elapsed_s"], 3),
                "devices": best["devices"],
                "losses": [round(l, 6) for l in best["losses"]],
            }
    one, two = detail["procs"]["1"], detail["procs"]["2"]
    eff = two["steps_per_s"] / (2.0 * one["steps_per_s"])
    detail["scaling_efficiency"] = round(eff, 3)
    l1 = np.asarray(one["losses"])
    l2 = np.asarray(two["losses"])
    detail["multihost_ok"] = bool(
        np.all(np.isfinite(l1)) and np.all(np.isfinite(l2))
        and l1.shape == l2.shape and np.allclose(l1, l2, atol=1e-6))
    return {"metric": "multihost_scaling_efficiency",
            "value": detail["scaling_efficiency"],
            "unit": "x (2-proc fleet / 2x 1-proc throughput)",
            "vs_baseline": None, "detail": detail}


def bench_precision(repeats: int = 2) -> dict:
    """f32-vs-bf16 timing pairs on the SAME shapes (docs/precision.md).

    Two legs, each run under both precision presets so the pair in one
    artifact is an apples-to-apples MXU/bandwidth comparison:

    - **train step**: the HVAE sampled step (the policy's biggest train
      win — the conv/dense stacks are the model's whole MXU mass; the
      manifold latent stays f32 under both presets);
    - **serve scan**: one warm ``topk_neighbors`` batch over a synthetic
      Poincaré table — f32 scan vs bf16-scan + f32-rescore
      (``serve/engine.py`` precision modes).

    Value = train-step speedup (f32 ms / bf16 ms; > 1 means bf16 wins).
    On CPU backends bf16 often does NOT win — the pair is recorded
    either way so the trajectory is honest per backend.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.models import hvae
    from hyperspace_tpu.serve.engine import QueryEngine

    rng = np.random.default_rng(0)
    n_steps = 10
    images = rng.random((1024, 28, 28)).astype(np.float32)
    train = {}
    for name in ("f32", "bf16"):
        cfg = hvae.HVAEConfig(precision=name, batch_size=256)
        model, opt, state = hvae.init_model(cfg, seed=0)
        x_all = jnp.asarray(images, cfg.dtype)
        t, _ = _time_steps(
            lambda st: hvae.train_step_sampled(model, opt, st, x_all)[:2],
            state, n_steps, max(2, repeats))
        train[name] = round(t / n_steps * 1e3, 3)

    n, dim, k, b = 20_000, 16, 10, 256
    table = np.asarray(PoincareBall(1.0).expmap0(
        jnp.asarray(rng.standard_normal((n, dim)) * 0.3, jnp.float32)))
    q = jnp.asarray(rng.integers(0, n, size=b), jnp.int32)
    serve = {}
    for name in ("f32", "bf16"):
        eng = QueryEngine(table, ("poincare", 1.0), precision=name)
        _, d = eng.topk_neighbors(q, k)  # compile + warm
        jax.device_get(d)
        times = []
        for _ in range(max(2, repeats)):
            t0 = time.perf_counter()
            _, d = eng.topk_neighbors(q, k)
            jax.device_get(d)
            times.append(time.perf_counter() - t0)
        serve[name] = round(min(times) * 1e3, 3)

    return {
        "metric": "precision_train_speedup",
        "value": round(train["f32"] / max(train["bf16"], 1e-9), 3),
        "unit": "x (f32 ms / bf16 ms)",
        "vs_baseline": None,
        "detail": {
            "train_workload": "hvae",
            "train_batch": 256,
            "train_step_ms": train,
            "serve_table": [n, dim],
            "serve_batch": b,
            "serve_k": k,
            "serve_scan_ms": serve,
            "serve_speedup": round(
                serve["f32"] / max(serve["bf16"], 1e-9), 3),
            "backend": jax.default_backend(),
        },
    }


def bench_big_table(repeats: int = 1, *, rows: int = 10_000_000,
                    dim: int = 8, ncells: int = 0,
                    train_rows: int = 200_000,
                    queries: int = 32, k: int = 10) -> dict:
    """Beyond-HBM table leg (r15, ROADMAP item 3): a ``rows``-node
    synthetic clustered Poincaré table **generated in host shards**
    (``parallel/host_table.HostEmbedTable.build`` — no [N, D] device
    residency during generation or index build), measured end to end:

    - **build_s**: the host-streamed IVF build (``serve/index.py``
      ``host_resident`` path — sampled k-means++ seeding, chunked
      Lloyd, spill on gathered rows only);
    - **lanes** f32 / bf16 / int8 / int4 / pq: measured per-lane
      scan-copy bytes (``table_mb`` — the capacity story: int8 is ~4×
      f32, int4 ~6×, pq ~10× at the default subspace count; pq counts
      its codebooks) and ``qps_at_recall99`` — warm probing queries/s
      at the smallest nprobe keeping recall@10 >= 0.99 vs the exact
      f32 scan (a lane whose quantization error never reaches 0.99
      reports 0.0 — the pq row is the honest one to watch);
    - **train**: host-resident planned-sparse step time
      (``train/host_embed.py`` — hot-row cache + chunk write-back) vs
      the in-HBM packed trainer at ``train_rows`` (a size both fit),
      plus the host path alone at the FULL table size;

    Headline value = the int8 lane's ``qps_at_recall99`` (the 4×-
    capacity lane has to hold production recall to count).  Per-lane
    and train failures degrade to ``*_error`` detail rows, never sink
    the leg.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.parallel.host_table import HostEmbedTable
    from hyperspace_tpu.serve.engine import QueryEngine
    from hyperspace_tpu.serve.index import auto_ncells, build_index

    rows, dim = int(rows), int(dim)
    spec = ("poincare", 1.0)
    rng = np.random.default_rng(0)
    ncl = min(512, max(rows // 64, 4))
    centers = rng.standard_normal((ncl, dim)) * 0.25

    def fill(start, nr):  # deterministic per block: ball points around
        r = np.random.default_rng((1234, start))  # clustered centers
        v = (centers[r.integers(0, ncl, nr)]
             + r.standard_normal((nr, dim)) * 0.05)
        nv = np.maximum(np.linalg.norm(v, axis=1, keepdims=True), 1e-12)
        return (np.tanh(nv) * v / nv).astype(np.float32)  # expmap0, c=1

    t0 = time.perf_counter()
    master = HostEmbedTable.build(rows, dim, fill,
                                  shard_rows=min(1 << 20, rows))
    gen_s = time.perf_counter() - t0
    # budget-shaped build knobs: ~√N cells capped at 512, ONE Lloyd
    # iteration (clustered synthetic data converges in one), wide
    # streamed blocks (fewer dispatches; device peak stays one block)
    ncells = int(ncells) or min(auto_ncells(rows), 512)
    t0 = time.perf_counter()
    idx = build_index(master, spec, ncells, iters=1, seed=0, balance=3.0,
                      chunk=min(1 << 18, max(rows, 4096)))
    build_s = time.perf_counter() - t0
    detail = {
        "rows": rows, "dim": dim, "ncells": ncells,
        "max_cell": idx.max_cell, "gen_s": round(gen_s, 2),
        "build_s": round(build_s, 2), "backend": jax.default_backend(),
        "table_mb": {}, "lanes": {},
    }

    # serve lanes: exact f32 ground truth once, then per-lane probing
    full = master.to_array()  # host copy for the engines (device work
    ids = rng.integers(0, rows, size=queries).astype(np.int32)  # is theirs)

    def timed_qps(e, nprobe=None):
        _, dd = e.topk_neighbors(ids, k, nprobe=nprobe)  # compile + warm
        jax.device_get(dd)
        ts = []
        for _ in range(max(2, repeats)):
            t0 = time.perf_counter()
            _, dd = e.topk_neighbors(ids, k, nprobe=nprobe)
            jax.device_get(dd)
            ts.append(time.perf_counter() - t0)
        return len(ids) / min(ts)

    exact = QueryEngine(full, spec)
    truth, _ = (np.asarray(a) for a in exact.topk_neighbors(ids, k))
    detail["exact_qps"] = round(timed_qps(exact), 1)
    del exact
    value = 0.0
    widths = [npb for npb in (1, 2, 4, 8, 16) if npb < ncells]
    for lane in ("f32", "bf16", "int8", "int4", "pq"):
        try:
            out = {"probes": {}, "qps_at_recall99": 0.0}
            # ONE engine per lane at the widest probe; each ladder step
            # narrows via the per-call nprobe override (the degradation
            # ladder's lever) — re-quantizing and re-uploading a 10M-row
            # table per width would be most of the lane's wall clock
            e = QueryEngine(full, spec, precision=lane, index=idx,
                            nprobe=max(widths))
            mb = e.scan_table.nbytes
            if e.scan_scale is not None:
                mb += e.scan_scale.nbytes
            if getattr(e, "pq_codebooks", None) is not None:
                mb += e.pq_codebooks.nbytes  # trained centers ride along
            out["table_mb"] = round(mb / 2**20, 1)
            detail["table_mb"][lane] = out["table_mb"]
            qps_at = 0.0
            for npb in widths:
                ii, _ = (np.asarray(a) for a in
                         e.topk_neighbors(ids, k, nprobe=npb))
                rec = float(np.mean([len(set(truth[j]) & set(ii[j])) / k
                                     for j in range(len(ids))]))
                qps = timed_qps(e, nprobe=npb)
                out["probes"][f"np{npb}"] = {"recall10": round(rec, 4),
                                             "qps": round(qps, 1)}
                if rec >= 0.99:
                    qps_at = qps
                    break  # smallest qualifying probe width is the
            del e
            out["qps_at_recall99"] = round(qps_at, 1)  # honest number
            detail["lanes"][lane] = out
            if lane == "int8":
                value = out["qps_at_recall99"]
        except Exception as err:  # noqa: BLE001 — per-lane failure
            # keeps the other lanes' rows (deadline _LegTimeout is a
            # BaseException and still flies through)
            detail["lanes"][f"{lane}_error"] = repr(err)
    del full

    # train: host-resident vs in-HBM at a size both fit, then host at
    # the full size (rsgd — packed rows are the table itself)
    try:
        from hyperspace_tpu.models import poincare_embed as pe
        from hyperspace_tpu.train import host_embed as he

        tn = int(min(train_rows, rows))
        cfg_t = pe.PoincareEmbedConfig(num_nodes=tn, dim=dim,
                                       batch_size=1024, neg_samples=10,
                                       optimizer="rsgd")
        pairs_t = rng.integers(0, tn, size=(100_000, 2)).astype(np.int32)
        cs, steps = 8, 24
        state, opt = pe.init_state(cfg_t, 0)
        tr = he.HostPlannedTrainer.from_state(cfg_t, opt, state,
                                              chunk_steps=cs, seed=1)
        tr.run(pairs_t, cs)  # warm
        t0 = time.perf_counter()
        tr.run(pairs_t, steps)
        host_ms = (time.perf_counter() - t0) / steps * 1e3
        state2, opt2 = pe.init_state(cfg_t, 0)
        # the packed program donates the state buffers — time the run
        # over the RETURNED state, never the consumed one
        state2, _ = he.run_planned_inhbm(cfg_t, opt2, state2, pairs_t,
                                         cs, chunk_steps=cs, seed=1)
        t0 = time.perf_counter()
        he.run_planned_inhbm(cfg_t, opt2, state2, pairs_t, steps,
                             chunk_steps=cs, seed=1)
        inhbm_ms = (time.perf_counter() - t0) / steps * 1e3
        detail["train"] = {
            "rows": tn, "chunk_steps": cs,
            "host_step_ms": round(host_ms, 2),
            "inhbm_step_ms": round(inhbm_ms, 2),
            "host_vs_inhbm": round(host_ms / max(inhbm_ms, 1e-9), 2),
        }
        if rows > tn:
            cfg_f = dataclasses.replace(cfg_t, num_nodes=rows)
            opt_f = pe.make_optimizer(cfg_f)
            trf = he.HostPlannedTrainer(
                cfg_f, opt_f, master, opt_f.init(jnp.zeros((1, dim))),
                jax.random.PRNGKey(0), chunk_steps=cs, seed=1)
            pairs_f = rng.integers(0, rows,
                                   size=(200_000, 2)).astype(np.int32)
            trf.run(pairs_f, cs)  # warm
            t0 = time.perf_counter()
            trf.run(pairs_f, steps)
            detail["train"]["host_step_ms_full"] = round(
                (time.perf_counter() - t0) / steps * 1e3, 2)
    except Exception as err:  # noqa: BLE001 — the serve lanes' rows
        # survive a train-leg failure (deadline flies through)
        detail["train_error"] = repr(err)

    return {"metric": "big_table_qps_at_recall99", "value": value,
            "unit": "queries/s", "vs_baseline": None, "detail": detail}


def bench_multitenant(repeats: int = 1, *, qps: float = 100.0,
                      duration_s: float = 2.0, table_rows: int = 4_000,
                      mix=(0.8, 0.15, 0.05)) -> dict:
    """Multi-tenant front door under a skewed tenant mix (docs/
    serving.md "Multi-tenant front door", ISSUE 20).

    One in-process HTTP front door over an :class:`EngineRegistry`
    (serve/registry.py) holding THREE tenant stacks (hot/mid/cold —
    the offered mix is Zipf-flavored: ``mix`` of the traffic each),
    driven open-loop through four phases:

    - **steady**: fixed offered load with the tenant sampled per
      request from ``mix`` — ``aggregate_qps`` (answered/s across all
      tenants, the headline) plus per-tenant p50/p95/p99 from each
      tenant's own ``serve/e2e_ms@tenant=`` histogram delta, and
      ``recompiles_steady`` (0 is the contract: the warmup walked
      every tenant's bucket ladder, and tenants share no programs
      beyond their scan signature);
    - **fairness**: the cold tenant's trickle is measured solo, then
      again while the hot tenant saturates the shared one-worker
      dispatch executor — the deficit-round-robin dispatcher bounds
      the damage, ``fairness`` = starved p99 / solo p99 (lower is
      better; the verdict allows max(200 ms, 20x solo) on a noisy
      CPU host);
    - **isolation**: every tenant's served top-k must be BITWISE the
      answer of a solo engine over its own table — cross-tenant cache
      or program leaks cannot fail politely;
    - **paging storm**: a second registry under a device budget that
      holds ONE resident engine; round-robin queries force whole-
      engine evict/re-admit cycles and every post-re-admission answer
      must stay bitwise (the host-resident artifact is the master
      copy), with the observed cold-admission latencies reported.

    Value = steady ``aggregate_qps`` (higher is better).
    ``multitenant_ok`` rolls up recompiles==0 + isolation + fairness +
    paging-actually-paged.
    """
    import asyncio
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.manifolds import PoincareBall
    from hyperspace_tpu.serve.engine import QueryEngine
    from hyperspace_tpu.serve.registry import EngineRegistry
    from hyperspace_tpu.serve.server import HttpFrontDoor
    from hyperspace_tpu.telemetry import registry as telem

    telem.install_jax_monitoring_hook()
    rng = np.random.default_rng(0)
    n, dim, k = table_rows, 16, 10
    names = ("hot", "mid", "cold")
    tables = {
        name: np.asarray(PoincareBall(1.0).expmap0(jnp.asarray(
            rng.standard_normal((n, dim)) * 0.3, jnp.float32)))
        for name in names
    }
    solo = {name: QueryEngine(tables[name], ("poincare", 1.0))
            for name in names}
    probe_ids = [0, 3, 17, 29]
    expect = {name: solo[name].topk_neighbors(
        np.asarray(probe_ids, np.int32), k) for name in names}
    reg = telem.default_registry()

    async def _post(host, port, payload):
        reader, writer = await asyncio.open_connection(host, port)
        try:
            body = json.dumps(payload).encode("utf-8")
            writer.write(
                (f"POST /v1/topk HTTP/1.1\r\nHost: bench\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 "Connection: close\r\n\r\n").encode("latin-1") + body)
            await writer.drain()
            data = await reader.read()
        finally:
            writer.close()
        head, _, raw = data.partition(b"\r\n\r\n")
        status = int(head.split(None, 2)[1])
        try:
            return status, json.loads(raw.decode())
        except ValueError:
            return status, {}

    async def _drive(host, port, tenant_of, size, pass_qps, n_req,
                     seed):
        """Open-loop pass: ``tenant_of(i)`` names each request's
        tenant (clock-scheduled arrivals — a starved tenant queues,
        it never throttles the offered load)."""
        offsets = open_loop_arrivals(n_req, pass_qps, "poisson", seed)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        tasks = []
        for i, off in enumerate(offsets):
            delay = t0 + float(off) - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            payload = {"ids": rng.integers(0, n, size=size).tolist(),
                       "k": k, "tenant": tenant_of(i)}
            tasks.append(asyncio.ensure_future(
                _post(host, port, payload)))
        results = await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = loop.time() - t0
        statuses: dict = {}
        for r in results:
            key = (f"error:{type(r).__name__}"
                   if isinstance(r, BaseException) else str(int(r[0])))
            statuses[key] = statuses.get(key, 0) + 1
        return statuses, elapsed

    def _tenant_p(delta, tenant):
        e2e = delta.get(f"hist/serve/e2e_ms@tenant={tenant}")
        if not e2e or not e2e.get("count"):
            return None
        return {"n": e2e["count"],
                **{q: e2e[q] for q in ("p50", "p95", "p99")}}

    def _mk_registry(budget_mb, art_dir):
        r = EngineRegistry(device_budget_mb=budget_mb,
                           max_wait_us=2000.0)
        for name in names:
            r.add_tenant(name, os.path.join(art_dir, name),
                         weight=1.0, window_s=0.0,
                         batcher_kw=dict(min_bucket=8, max_bucket=64,
                                         cache_size=0, queue_max=256))
        return r

    async def _probe_bitwise(host, port, name):
        """One tenant's served top-k vs its solo engine, bit for bit
        — the structural-isolation (and post-re-admission) check."""
        status, body = await _post(
            host, port, {"ids": probe_ids, "k": k, "tenant": name})
        if status != 200:
            return False
        li, ld = (np.asarray(a) for a in expect[name])
        return (np.array_equal(li, np.asarray(body["neighbors"]))
                and np.array_equal(
                    ld.astype(np.float32).view(np.uint32),
                    np.asarray(body["dists"],
                               np.float32).view(np.uint32)))

    async def _run(art_dir):
        detail: dict = {
            "num_nodes": n, "dim": dim, "k": k, "tenants": list(names),
            "mix": list(mix), "offered_qps": qps,
            "duration_s": duration_s,
            "backend": jax.default_backend(),
        }
        registry = _mk_registry(0.0, art_dir)
        door = HttpFrontDoor(registry=registry, max_wait_us=2000)
        await door.start()
        c0 = reg.get("jax/recompiles")
        # closed-loop warmup: every tenant × every bucket rung, so the
        # mixed-tenant timed phase can never hand the compiler a fresh
        # shape (collation may pad any tenant's queue to any rung)
        for name in names:
            for b in registry.resolve(name).batcher.buckets:
                await _post(door.host, door.port,
                            {"ids": rng.integers(0, n, size=b).tolist(),
                             "k": k, "tenant": name})
        c1 = reg.get("jax/recompiles")
        detail["recompiles_warmup"] = c1 - c0

        # --- steady: Zipf-mix offered load, per-tenant percentiles ---
        n_req = max(16, int(qps * duration_s))
        picks = rng.choice(len(names), size=n_req, p=list(mix))
        base = reg.mark()
        statuses, elapsed = await _drive(
            door.host, door.port, lambda i: names[picks[i]], 16, qps,
            n_req, 7)
        delta = reg.snapshot(baseline=base)
        answered = sum(v for s, v in statuses.items()
                       if not s.startswith("error"))
        detail["steady"] = {
            "statuses": statuses,
            "aggregate_qps": round(answered / max(elapsed, 1e-9), 1),
            "per_tenant_ms": {t: _tenant_p(delta, t) for t in names},
        }
        agg = delta.get("hist/serve/e2e_ms")
        if not agg or not agg.get("count"):
            await door.drain()
            raise RuntimeError(
                f"multitenant: no successful steady request — "
                f"{statuses}")
        detail["aggregate_qps"] = detail["steady"]["aggregate_qps"]
        detail["steady"]["p99_ms"] = agg["p99"]
        detail["recompiles_steady"] = reg.get("jax/recompiles") - c1

        # --- isolation: every tenant bitwise vs its solo engine ------
        # (probed BEFORE the fairness flood: the flood legitimately
        # walks the hot tenant down its degradation ladder, and a
        # degraded answer is supposed to differ)
        isolation = {t: await _probe_bitwise(door.host, door.port, t)
                     for t in names}
        detail["isolation_bitwise"] = isolation

        # --- fairness: cold trickle solo, then under a hot flood ----
        trickle_qps, trickle_n = 25.0, 30
        base = reg.mark()
        await _drive(door.host, door.port, lambda i: "cold", 16,
                     trickle_qps, trickle_n, 21)
        solo_p = _tenant_p(reg.snapshot(baseline=base), "cold")
        base = reg.mark()
        flood_n = max(32, int(qps * 6 * 1.2))
        _, _ = await asyncio.gather(
            _drive(door.host, door.port, lambda i: "hot", 16, qps * 6,
                   flood_n, 33),
            _drive(door.host, door.port, lambda i: "cold", 16,
                   trickle_qps, trickle_n, 34))
        starved_p = _tenant_p(reg.snapshot(baseline=base), "cold")
        if solo_p and starved_p:
            solo_p99 = max(solo_p["p99"], 0.05)
            detail["fairness_detail"] = {
                "solo_p99_ms": solo_p["p99"],
                "starved_p99_ms": starved_p["p99"],
                "trickle_qps": trickle_qps, "flood_qps": qps * 6,
            }
            detail["starved_p99_ms"] = starved_p["p99"]
            detail["fairness"] = round(starved_p["p99"] / solo_p99, 3)
            fairness_ok = starved_p["p99"] <= max(200.0, 20 * solo_p99)
        else:
            detail["fairness_detail"] = {"error": "empty fairness pass"}
            fairness_ok = False
        detail["fairness_ok"] = fairness_ok
        await door.drain()

        # --- paging storm: budget holds ONE engine; round-robin ------
        table_mb = tables["hot"].nbytes / (1 << 20)
        budget_mb = round(table_mb * 1.5, 3)  # one fits, two never do
        storm = _mk_registry(budget_mb, art_dir)
        sdoor = HttpFrontDoor(registry=storm, max_wait_us=2000)
        await sdoor.start()
        cold_ms, paged_bitwise = [], True
        for _round in range(2):
            for name in names:
                t0 = time.perf_counter()
                ok = await _probe_bitwise(sdoor.host, sdoor.port, name)
                cold_ms.append(round(
                    (time.perf_counter() - t0) * 1e3, 1))
                paged_bitwise = paged_bitwise and ok
        sstats = storm.stats()
        admits = sum(s["registry"]["admissions"]
                     for s in sstats.values())
        evicts = sum(s["registry"]["evictions"]
                     for s in sstats.values())
        await sdoor.drain()
        paging_ok = paged_bitwise and evicts > 0 and admits > len(names)
        detail["paging"] = {
            "device_budget_mb": budget_mb,
            "table_mb": round(table_mb, 3),
            "admissions": admits, "evictions": evicts,
            "bitwise_after_readmit": paged_bitwise,
            "cold_admit_ms": cold_ms,
        }

        detail["multitenant_ok"] = bool(
            detail["recompiles_steady"] == 0
            and all(isolation.values()) and fairness_ok and paging_ok)
        return detail

    with tempfile.TemporaryDirectory() as tmp:
        from hyperspace_tpu.serve import export_artifact

        for name in names:
            export_artifact(os.path.join(tmp, name), tables[name],
                            ("poincare", 1.0), model_config={"c": 1.0})
        detail = asyncio.run(_run(tmp))
    return {"metric": "multitenant_agg_qps",
            "value": detail["aggregate_qps"], "unit": "queries/s",
            "vs_baseline": None, "detail": detail}


def _get(d, *path):
    """Nested dict lookup returning None on any missing key."""
    for k in path:
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d


# compact-headline fields, highest priority first: when the compact line
# must shrink to fit the tail budget, keys are dropped from the END of
# this list.  Each entry: (compact_key, path into the full result).
_COMPACT_FIELDS = (
    ("step_time_s", ("detail", "step_time_s")),
    ("frac_hbm_roofline", ("detail", "frac_hbm_roofline")),
    ("bytes_per_step", ("detail", "bytes_per_step")),
    ("repeat_spread", ("detail", "repeat_spread")),
    ("error", ("detail", "error")),
    ("failed_benchmark", ("detail", "failed_benchmark")),
    ("budget_exhausted", ("detail", "budget_exhausted")),
    ("skipped_legs", ("detail", "skipped_legs")),
    ("timed_out_legs", ("detail", "timed_out_legs")),
    ("serve_qps", ("detail", "serve", "qps")),
    ("serve_recompiles_steady", ("detail", "serve", "recompiles_steady")),
    # per-qps-bucket p50/p95/p99 (ms) from the serve/e2e_ms histogram:
    # the first path is the auto-mode nested leg, the second fires when
    # bench_serve IS the headline (--metric serve) and detail is flat
    ("serve_latency_ms", ("detail", "serve", "latency_ms")),
    ("latency_ms", ("detail", "latency_ms")),
    # qps at recall@10 >= 0.99 over the IVF index (r10): first path is
    # auto mode's nested serve leg, second fires when bench_serve IS
    # the headline (--metric serve)
    ("serve_qps_r99", ("detail", "serve", "ivf", "qps_at_recall99")),
    ("qps_r99", ("detail", "ivf", "qps_at_recall99")),
    # fused/two_stage qps ratio at the largest bucket (r12): first path
    # is auto mode's nested serve leg, second fires when bench_serve IS
    # the headline (--metric serve)
    ("serve_fused_speedup",
     ("detail", "serve", "fused_vs_unfused", "serve_fused_speedup")),
    ("fused_speedup",
     ("detail", "fused_vs_unfused", "serve_fused_speedup")),
    # HTTP front door at fixed offered load (r13): aggregate p99 and
    # the overload pass's 429 shed rate — one path pair per field for
    # auto mode's nested leg vs --metric serve_http's flat detail.
    # Lower is better for both; scripts/bench_trend.py registers the
    # shed/deadline tokens direction-correctly.
    ("http_p99_ms", ("detail", "serve_http", "http_p99_ms")),
    ("http_p99_ms", ("detail", "http_p99_ms")),
    ("http_shed_rate", ("detail", "serve_http", "shed_rate")),
    ("http_shed_rate", ("detail", "shed_rate")),
    # live mutable index leg (r18): steady p99 under a concurrent
    # upsert stream, p99 across the blue-green flip, upsert-to-visible
    # latency and the three zero-contract columns (errors, stale
    # results, post-prewarm recompiles roll up into live_ok).  First
    # path is auto mode's nested leg, second fires when
    # bench_live_index IS the headline (--metric live_index).
    ("live_p99_ms", ("detail", "live_index", "live_p99_ms")),
    ("live_p99_ms", ("detail", "live_p99_ms")),
    ("p99_during_rollover_ms",
     ("detail", "live_index", "p99_during_rollover_ms")),
    ("p99_during_rollover_ms", ("detail", "p99_during_rollover_ms")),
    ("upsert_visible_ms",
     ("detail", "live_index", "freshness", "upsert_visible_ms", "p99")),
    ("upsert_visible_ms",
     ("detail", "freshness", "upsert_visible_ms", "p99")),
    ("live_ok", ("detail", "live_index", "live_ok")),
    ("live_ok", ("detail", "live_ok")),
    ("live_recall_vs_oracle",
     ("detail", "live_index", "recall_vs_oracle")),
    ("live_recall_vs_oracle", ("detail", "recall_vs_oracle")),
    # cold-start time-to-first-query at warm cache + prewarm (r14) and
    # its recompile contract: first path pair for auto mode's nested
    # leg, second when bench_cold_start IS the headline
    ("cold_ttfq_ms", ("detail", "cold_start", "cold_ttfq_ms")),
    ("cold_ttfq_ms", ("detail", "cold_ttfq_ms")),
    ("cold_recompiles_steady",
     ("detail", "cold_start", "recompiles_steady")),
    ("cold_recompiles_steady", ("detail", "cold_recompiles_steady")),
    # beyond-HBM big-table leg (r15): the int8 lane's qps at recall
    # >= 0.99, its scan-copy megabytes (4× capacity vs f32 — lower is
    # better, bench_trend's bytes/mb tokens), the streamed IVF build
    # time and the host-resident vs in-HBM train-step ratio.  First
    # path is auto mode's nested leg, second fires when
    # bench_big_table IS the headline (--metric big_table)
    ("big_qps_r99_int8",
     ("detail", "big_table", "lanes", "int8", "qps_at_recall99")),
    ("big_qps_r99_int8", ("detail", "lanes", "int8", "qps_at_recall99")),
    ("big_table_mb_int8", ("detail", "big_table", "table_mb", "int8")),
    ("big_table_mb_int8", ("detail", "table_mb", "int8")),
    # r16 sub-int8 lanes: the capacity ladder below int8 (int4 packed
    # nibbles + f16 scales; pq codes + codebooks) — same lower-is-
    # better mb gating via bench_trend's size tokens
    ("big_table_mb_int4", ("detail", "big_table", "table_mb", "int4")),
    ("big_table_mb_int4", ("detail", "table_mb", "int4")),
    ("big_table_mb_pq", ("detail", "big_table", "table_mb", "pq")),
    ("big_table_mb_pq", ("detail", "table_mb", "pq")),
    ("big_build_s", ("detail", "big_table", "build_s")),
    ("big_build_s", ("detail", "build_s")),
    ("big_host_step_ms",
     ("detail", "big_table", "train", "host_step_ms")),
    ("big_host_step_ms", ("detail", "train", "host_step_ms")),
    ("precision_train_ms", ("detail", "precision", "train_step_ms")),
    ("precision_serve_ms", ("detail", "precision", "serve_scan_ms")),
    # pod-scale loopback scaling leg (r19): 2-proc fleet throughput
    # over 2× 1-proc (higher is better — bench_trend's scaling/
    # efficiency tokens), gated by the cross-process-count loss-match
    # verdict (multihost_ok — a sentinel, excluded from trend gating).
    # First path is auto mode's nested leg, second fires when
    # bench_multihost IS the headline (--metric multihost)
    ("multihost_scaling_efficiency",
     ("detail", "multihost", "scaling_efficiency")),
    ("multihost_scaling_efficiency", ("detail", "scaling_efficiency")),
    ("multihost_ok", ("detail", "multihost", "multihost_ok")),
    ("multihost_ok", ("detail", "multihost_ok")),
    # multi-tenant front door leg (r20): steady aggregate qps at the
    # Zipf mix (higher is better — bench_trend's qps token), the DRR
    # fairness ratio + the starved tenant's contended p99 (lower is
    # better — the fairness/starved tokens), gated by the rolled-up
    # verdict (multitenant_ok — a sentinel, excluded from trend
    # gating).  First path is auto mode's nested leg, second fires
    # when bench_multitenant IS the headline (--metric multitenant)
    ("multitenant_agg_qps", ("detail", "multitenant", "aggregate_qps")),
    ("multitenant_agg_qps", ("detail", "aggregate_qps")),
    ("tenant_fairness", ("detail", "multitenant", "fairness")),
    ("tenant_fairness", ("detail", "fairness")),
    ("starved_p99_ms", ("detail", "multitenant", "starved_p99_ms")),
    ("starved_p99_ms", ("detail", "starved_p99_ms")),
    ("multitenant_ok", ("detail", "multitenant", "multitenant_ok")),
    ("multitenant_ok", ("detail", "multitenant_ok")),
    # failure-domain leg (PR 9): chaos recovery + the shed-rate column
    ("resilience_ok", ("detail", "resilience", "ok")),
    ("shed_rate", ("detail", "resilience", "overload", "shed_rate")),
    ("chaos_rollbacks",
     ("detail", "resilience", "chaos_train", "rollbacks")),
    ("frac_clustered", ("detail", "frac_clustered")),
    ("num_nodes", ("detail", "num_nodes")),
    ("devices", ("detail", "devices")),
    ("backend", ("detail", "backend")),
    ("use_att", ("detail", "use_att")),
    ("lr", ("detail", "lr")),
    ("loss", ("detail", "loss")),
    ("att_step_s", ("detail", "use_att_arm", "step_time_s")),
    ("att_samples_per_s_per_chip",
     ("detail", "use_att_arm", "samples_per_s_per_chip")),
    ("poincare_epoch_s", ("detail", "poincare_embed_epoch_time_s")),
    ("sampled_samples_per_s",
     ("detail", "hgcn_sampled", "supervised_samples_per_s")),
    ("sampled_incl_samples_per_s",
     ("detail", "hgcn_sampled", "sampling_inclusive_samples_per_s")),
    ("realistic_mean_step_s", ("detail", "realistic", "mean_step_s")),
    ("realistic_att_step_s", ("detail", "realistic", "att_step_s")),
    ("realistic_frac_clustered",
     ("detail", "realistic", "mean_frac_clustered")),
    ("hvae_scan_chunk_step_ms",
     ("detail", "workloads", "hvae", "scan_chunk_step_ms")),
    ("product_scan_chunk_step_ms",
     ("detail", "workloads", "product_embed", "scan_chunk_step_ms")),
    ("reorder", ("detail", "reorder")),
    ("source", ("detail", "source")),
    ("dtype", ("detail", "dtype")),
    ("step", ("detail", "step")),
)

# hard byte budget for the LAST stdout line.  The driver records only the
# final 2000 characters of stdout (BENCH_r04.json was truncated to
# ``parsed: null`` when the single ever-growing JSON line outgrew that);
# 1400 leaves headroom for the newline and any driver framing.
COMPACT_LIMIT = 1400


def _json_default(o):
    """Last-resort serializer: a leg dropping a numpy scalar/array (or
    anything else json can't take) into detail must degrade that VALUE,
    never swallow the whole emit — BENCH_r04 ended with ``parsed: null``
    and rc=0, i.e. a run that completed but whose artifact didn't."""
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except Exception:  # noqa: BLE001  # hyperlint: disable=swallow-base-exception — numpy import failure: degrade to str(o) below
        pass
    return str(o)


def compact_headline(result: dict, limit: int = COMPACT_LIMIT) -> str:
    """One SMALL self-sufficient JSON line — always printed LAST.

    Carries metric/value/unit/vs_baseline plus a priority-ordered subset
    of the detail; guaranteed ≤ ``limit`` characters by dropping
    lowest-priority detail keys (never the metric/value themselves).
    """
    fields = []
    for key, path in _COMPACT_FIELDS:
        v = _get(result, *path)
        if v is not None:
            if isinstance(v, str) and len(v) > 200:
                v = v[:200]
            fields.append((key, v))
    while True:
        line = json.dumps({
            "metric": result.get("metric"),
            "value": result.get("value"),
            "unit": result.get("unit"),
            "vs_baseline": result.get("vs_baseline"),
            "detail": dict(fields),
        }, default=_json_default)
        if len(line) <= limit or not fields:
            return line
        fields.pop()


def emit(result: dict) -> None:
    """Print the full result, then the compact headline as the FINAL line.

    The driver's tail capture (last 2000 chars of stdout) therefore always
    contains one complete parseable JSON record with the headline metric,
    regardless of how large the full detail grows.  The full record is
    also written to ``bench_full.json`` beside this file.

    The compact line is the contract: nothing that can go wrong with the
    full record (unserializable detail, a read-only checkout) may keep
    it off stdout — a final fallback headline prints even if the compact
    builder itself raises.
    """
    import os

    try:
        full_line = json.dumps(result, default=_json_default)
    except Exception:  # noqa: BLE001 — circular detail etc.
        full_line = None
    if full_line is not None:
        try:
            # BENCH_FULL_JSON redirects the artifact copy (tests point it
            # at a tmp dir so a real subprocess run never clobbers the
            # checkout's last genuine bench_full.json)
            path = os.environ.get("BENCH_FULL_JSON") or os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "bench_full.json")
            with open(path, "w") as f:
                f.write(full_line + "\n")
        except OSError:
            pass  # read-only checkout: stdout still carries everything
        print(full_line)
    try:
        line = compact_headline(result)
    except Exception:  # noqa: BLE001 — the headline must still land
        line = json.dumps({"metric": result.get("metric", "error"),
                           "value": result.get("value", 0), "unit": "",
                           "vs_baseline": None,
                           "detail": {"emit_degraded": True}},
                          default=_json_default)
    print(line)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--metric",
                   choices=["auto", "hgcn", "poincare", "serve",
                            "serve_http", "live_index", "cold_start",
                            "big_table", "multihost", "multitenant"],
                   default="auto")
    p.add_argument("--big-rows", type=int, default=10_000_000,
                   help="--metric big_table: synthetic table rows "
                        "(generated in host shards; r15 beyond-HBM leg)")
    p.add_argument("--big-dim", type=int, default=8,
                   help="--metric big_table: table feature width")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32")
    p.add_argument("--agg-dtype", choices=["float32", "bfloat16"],
                   default="bfloat16")
    p.add_argument("--use-att", action="store_true",
                   help="attention aggregation (GAT-style) instead of mean")
    p.add_argument("--step", choices=["lp", "pairs"], default="pairs")
    p.add_argument("--decoder-dtype", choices=["float32", "bfloat16"],
                   default="bfloat16")
    p.add_argument("--budget-s", type=float,
                   default=float(os.environ.get("BENCH_BUDGET_S",
                                                DEFAULT_BUDGET_S)),
                   help="wall-clock budget: optional legs are skipped "
                        "once they can't fit, and a watchdog emits the "
                        "partial artifact at the deadline")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation cache directory "
                        "(hyperspace_tpu/compile_cache.py; default ON "
                        "under <repo>/.cache/jax_compile, 0 disables) — "
                        "round N+1's compiles become deserializations")
    args = p.parse_args()

    # cache BEFORE any leg compiles; a broken cache dir degrades to
    # cold compiles with a note, never sinks the artifact
    cc_dir = None
    try:
        from hyperspace_tpu import compile_cache as _compile_cache

        cc_dir = _compile_cache.activate(args.compile_cache_dir)
    except ValueError as e:
        print(f"[bench] compile cache disabled: {e}", file=sys.stderr)

    import functools
    import traceback

    guard = _BudgetGuard(args.budget_s)
    holder: dict = {"result": None}
    # sub-10 s budgets (tests, smoke) keep the leg-skip behavior but not
    # the watchdog — a near-zero timer would race the normal emit path
    if args.budget_s >= 10:
        guard.arm(holder)

    hgcn_fn = functools.partial(bench_hgcn, dtype=args.dtype,
                                agg_dtype=args.agg_dtype,
                                use_att=args.use_att, step=args.step,
                                decoder_dtype=args.decoder_dtype)
    primary = {"poincare": bench_poincare,
               "serve": bench_serve,
               "serve_http": bench_serve_http,
               "live_index": bench_live_index,
               "cold_start": bench_cold_start,
               "big_table": functools.partial(
                   bench_big_table, rows=args.big_rows,
                   dim=args.big_dim),
               "multihost": bench_multihost,
               "multitenant": bench_multitenant}.get(args.metric,
                                                     hgcn_fn)
    primary_name = args.metric if args.metric != "auto" else "hgcn"

    # the headline metric NEVER switches silently: a failure of the
    # selected benchmark (hgcn under auto) is reported as metric="error"
    # with the traceback, not papered over with a different green metric
    failed = False
    try:
        try:
            # a positive budget bounds even the headline benchmark: a
            # budget_exhausted record that parses beats a perfect record
            # the driver's hard timeout never saw.  budget<=0 keeps the
            # documented "skip every optional leg, run the headline
            # unbounded" escape hatch.
            with (_deadline(guard.remaining()) if args.budget_s > 0
                  else contextlib.nullcontext()):
                result = primary(repeats=args.repeats)
        except _LegTimeout:
            result = {"metric": "budget_exhausted", "value": 0, "unit": "",
                      "vs_baseline": None,
                      "detail": {"budget_exhausted": True,
                                 "timed_out_legs": [primary_name]}}
        except Exception as e:
            failed = True
            result = {"metric": "error", "value": 0, "unit": "",
                      "vs_baseline": None,
                      "detail": {"error": repr(e),
                                 "traceback": traceback.format_exc(),
                                 "failed_benchmark": primary_name}}
        holder["result"] = result  # legs below mutate detail in place,
        skipped: list = []         # so the watchdog emits live progress
        timed_out: list = []

        def leg(name: str, min_s: float, fn) -> None:
            """Run one optional detail leg if the remaining budget can
            plausibly fit it (``min_s`` — a rough floor, not a promise),
            under a hard deadline at the remaining budget (BENCH_r05:
            the floor check alone lets one slow leg on an experimental
            backend eat the whole budget); skipped and timed-out legs
            are listed in the artifact instead of silently missing."""
            if guard.remaining() < min_s:
                skipped.append(name)
                return
            try:
                with _deadline(guard.remaining()):
                    fn(result["detail"])
            except _LegTimeout:
                timed_out.append(name)
            except Exception as e:  # noqa: BLE001 — legs never sink the run
                result["detail"][f"{name}_error"] = repr(e)

        if args.metric == "auto":
            # both BASELINE metrics in the one JSON line: hgcn stays the
            # headline (or the error record), the poincare epoch time
            # rides in detail either way
            def poincare_leg(d):
                pr = bench_poincare(repeats=max(1, args.repeats - 1))
                d["poincare_embed_epoch_time_s"] = pr["value"]
                d["poincare"] = pr["detail"]

            def sampled_leg(d):  # minibatch trainer (honest unit)
                d["hgcn_sampled"] = bench_sampled(
                    repeats=max(1, args.repeats - 1))

            def realistic_leg(d):  # disk → loader → reorder → cluster
                from hyperspace_tpu.benchmarks.hgcn_bench import (
                    run_realistic_bench,
                )

                d["realistic"] = run_realistic_bench(
                    repeats=max(1, args.repeats - 1))

            def workloads_leg(d):
                # workloads 3-5 one-liners + the 4k-token flash fwd+bwd
                # leg; these ms-scale legs keep their own repeats default
                # (4): min-of-more-repeats is the r04 drift fix
                from hyperspace_tpu.benchmarks.workloads_bench import (
                    run_workloads_bench,
                )

                d["workloads"] = run_workloads_bench()

            def serve_leg(d):  # serving perf, tracked from PR 4 on
                r = bench_serve(repeats=max(1, args.repeats - 1))
                d["serve"] = {"qps": r["value"], **r["detail"]}

            def serve_http_leg(d):  # open-loop HTTP latency (r13)
                r = bench_serve_http(repeats=max(1, args.repeats - 1))
                d["serve_http"] = {"p99_ms": r["value"], **r["detail"]}

            def live_index_leg(d):  # live upserts + rollover (r18)
                r = bench_live_index()
                d["live_index"] = r["detail"]

            def cold_start_leg(d):  # restart TTFQ + cache regimes (r14)
                r = bench_cold_start()
                d["cold_start"] = r["detail"]

            def precision_leg(d):  # f32/bf16 pairs, tracked from PR 5 on
                r = bench_precision(repeats=max(1, args.repeats - 1))
                d["precision"] = {"train_speedup": r["value"],
                                  **r["detail"]}

            def big_table_leg(d):  # beyond-HBM table lanes (r15) — a
                # scaled-down table in auto mode (the full 10M-row leg
                # is --metric big_table); still host-resident end to
                # end, so the streamed build + hot-row trainer + all
                # three lanes exercise the real code paths every round
                r = bench_big_table(repeats=max(1, args.repeats - 1),
                                    rows=300_000, ncells=192,
                                    train_rows=100_000)
                d["big_table"] = r["detail"]
                d["big_table"]["big_table_qps_at_recall99"] = r["value"]

            def resilience_leg(d):  # chaos recovery + shed rate (PR 9)
                r = bench_resilience()
                d["resilience"] = {"ok": r["value"], **r["detail"]}

            def multihost_leg(d):  # pod-scale loopback scaling (r19)
                r = bench_multihost()
                d["multihost"] = r["detail"]

            def multitenant_leg(d):  # engine registry + DRR (r20)
                r = bench_multitenant()
                d["multitenant"] = r["detail"]

            def use_att_leg(d):
                # the attention arm on the same graph/protocol (VERDICT
                # r3 #1).  Distinct key: detail["use_att"] is the
                # headline's config-as-executed bool and must not be
                # clobbered.  With --use-att the primary already IS this
                # arm — don't run the multi-minute bench twice.
                src = (d if args.use_att
                       else hgcn_fn(repeats=max(1, args.repeats - 1),
                                    use_att=True)["detail"])
                d["use_att_arm"] = {
                    "step_time_s": src["step_time_s"],
                    "samples_per_s_per_chip": round(
                        src["num_nodes"] / src["step_time_s"]
                        / src["devices"], 1),
                    "lr": src["lr"],
                    "clip_norm": src["clip_norm"],
                    "loss": src["loss"],
                }

            # rough per-leg floors (seconds on the usual remote chip) —
            # generous enough that a leg given the green light normally
            # finishes well before the watchdog deadline
            leg("poincare", 60, poincare_leg)
            leg("hgcn_sampled", 45, sampled_leg)
            leg("serve_qps", 40, serve_leg)
            leg("serve_http", 35, serve_http_leg)
            leg("live_index", 40, live_index_leg)
            leg("cold_start", 60, cold_start_leg)
            leg("big_table", 75, big_table_leg)
            leg("precision", 40, precision_leg)
            leg("resilience", 25, resilience_leg)
            leg("multihost", 90, multihost_leg)
            leg("multitenant", 45, multitenant_leg)
            leg("realistic", 150, realistic_leg)
            leg("workloads", 90, workloads_leg)
            leg("use_att_arm", 0 if args.use_att else 120, use_att_leg)

        try:
            # the run's telemetry counters (prep-cache, prefetch,
            # recompiles — docs/observability.md) ride in the artifact:
            # cross-round counter drift is a regression signal the
            # timing numbers alone can't show
            from hyperspace_tpu.telemetry import registry as _telem

            snap = _telem.snapshot()
            if snap:
                result["detail"]["telemetry"] = snap
        except Exception:  # noqa: BLE001  # hyperlint: disable=swallow-base-exception — optional diagnostics never sink the bench; the artifact must still emit
            pass
        result["detail"]["budget_s"] = args.budget_s
        result["detail"]["compile_cache"] = cc_dir or "off"
        result["detail"]["elapsed_s"] = round(guard.elapsed(), 1)
        if skipped:
            result["detail"]["skipped_legs"] = skipped
        if timed_out:
            result["detail"].setdefault("timed_out_legs", []).extend(timed_out)
        if guard.claim_emit():
            emit(result)
    finally:
        guard.disarm()
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
