"""Product-manifold embeddings with learned curvature (reference workload 5).

BASELINE.json configs[4]: mixed-curvature (hyperbolic × spherical ×
Euclidean) embeddings with **learned curvature**, **multi-host**; semantics
per Gu et al. 2019 (SURVEY.md §2 "Product-manifold embedder", §3.4).

Learned curvature forces a design departure from the statically-tagged
optimizers in :mod:`hyperspace_tpu.optim`: the parameter's manifold changes
every step (its curvatures are themselves parameters), so the Riemannian
update is done inline in the train step — build the Product manifold from
``softplus(c_raw)``, convert the Euclidean gradient, expmap — while the
curvature parameters take an Adam step from the same backward pass.  The
whole thing is still one XLA program; the gradient w.r.t. curvature flows
through every distance because manifolds are pytrees of traced scalars.

Multi-host (SURVEY.md §3.4): the same jitted step under a mesh whose
leading ``host`` axis rides DCN; batch sharded over (host, data), table
replicated (the gradient all-reduce GSPMD inserts is the reference's NCCL
all-reduce).  ``train_step_sharded`` takes the mesh; Python never
communicates across hosts.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu.manifolds import Euclidean, PoincareBall, Product, Sphere
from hyperspace_tpu.parallel.mesh import batch_sharding, replicated


FACTOR_KINDS = {"poincare": PoincareBall, "sphere": Sphere, "euclidean": Euclidean}


@dataclasses.dataclass(frozen=True)
class ProductEmbedConfig:
    num_nodes: int = 0
    # (kind, ambient_dim) per factor; curvature learned for non-Euclidean
    factors: tuple = (("poincare", 5), ("sphere", 5), ("euclidean", 2))
    init_c: float = 1.0
    lr_table: float = 0.3
    lr_curv: float = 1e-2
    neg_samples: int = 10
    batch_size: int = 256
    burnin_steps: int = 50
    burnin_factor: float = 0.05
    init_scale: float = 1e-2
    dtype: Any = jnp.float32
    # mixed-precision policy (hyperspace_tpu/precision.py).  Like the
    # Poincaré embedder, this workload is all boundary-sensitive math on
    # a master-parameter table (plus the learned-curvature softplus), so
    # "bf16" is bit-identical to "f32" BY DESIGN — the serving scan is
    # where the bf16 win lives (serve/engine precision="bf16").
    precision: str = "f32"

    @property
    def total_dim(self) -> int:
        return sum(d for _, d in self.factors)

    @property
    def num_curved(self) -> int:
        return sum(1 for k, _ in self.factors if k != "euclidean")


def build_manifold(cfg: ProductEmbedConfig, c_raw: jax.Array) -> Product:
    """Product manifold with curvatures softplus(c_raw) (traced, learnable)."""
    factors, i = [], 0
    for kind, dim in cfg.factors:
        if kind == "euclidean":
            factors.append(Euclidean())
        else:
            factors.append(FACTOR_KINDS[kind](jax.nn.softplus(c_raw[i])))
            i += 1
    return Product(factors, [d for _, d in cfg.factors])


class Params(NamedTuple):
    table: jax.Array  # [N, total_dim] points on the product manifold
    c_raw: jax.Array  # [num_curved] inverse-softplus curvatures


class TrainState(NamedTuple):
    params: Params
    curv_opt_state: Any
    key: jax.Array
    step: jax.Array


def init_state(cfg: ProductEmbedConfig, seed: int = 0) -> tuple[TrainState, Any]:
    from hyperspace_tpu import precision as precision_mod

    precision_mod.get_policy(cfg.precision)  # validate the name early
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    c_raw = jnp.full((cfg.num_curved,),
                     float(np.log(np.expm1(cfg.init_c))), cfg.dtype)
    m = build_manifold(cfg, c_raw)
    v = cfg.init_scale * jax.random.normal(
        k_init, (cfg.num_nodes, cfg.total_dim), cfg.dtype)
    table = m.expmap0(m.proju(m.origin(v.shape, cfg.dtype), v))
    curv_opt = optax.adam(cfg.lr_curv)
    state = TrainState(
        Params(table, c_raw), curv_opt.init(c_raw), key, jnp.zeros((), jnp.int32))
    return state, curv_opt


def loss_fn(params: Params, cfg: ProductEmbedConfig,
            u_idx: jax.Array, v_idx: jax.Array, neg_idx: jax.Array) -> jax.Array:
    """Ranking loss -log softmax(-d(u, ·)) (Nickel & Kiela form, product
    distance d² = Σ factor d² per Gu et al.)."""
    m = build_manifold(cfg, params.c_raw)
    u = params.table[u_idx]
    cand = jnp.concatenate([v_idx[:, None], neg_idx], axis=1)
    cv = params.table[cand]
    d = m.dist(u[:, None, :], cv)
    logits = -d
    collide = (neg_idx == v_idx[:, None]) | (neg_idx == u_idx[:, None])
    mask = jnp.concatenate([jnp.zeros_like(v_idx[:, None], bool), collide], axis=1)
    logits = jnp.where(mask, -jnp.inf, logits)
    return jnp.mean(jax.nn.logsumexp(logits, axis=1) - logits[:, 0])


def _step_body(cfg: ProductEmbedConfig, curv_opt, state: TrainState,
               pairs: jax.Array, constrain=None):
    """Shared step body; ``constrain(u, v, neg)`` pins batch shardings when
    running under a mesh (identity when single-device)."""
    key, k_batch, k_neg = jax.random.split(state.key, 3)
    rows = jax.random.randint(k_batch, (cfg.batch_size,), 0, pairs.shape[0])
    batch = pairs[rows]
    u_idx, v_idx = batch[:, 0], batch[:, 1]
    neg_idx = jax.random.randint(
        k_neg, (cfg.batch_size, cfg.neg_samples), 0, cfg.num_nodes)
    if constrain is not None:
        u_idx, v_idx, neg_idx = constrain(u_idx, v_idx, neg_idx)

    loss, grads = jax.value_and_grad(loss_fn)(state.params, cfg, u_idx, v_idx, neg_idx)

    # Riemannian SGD on the table under the *current* manifold
    lr = jnp.where(state.step < cfg.burnin_steps,
                   cfg.lr_table * cfg.burnin_factor, cfg.lr_table)
    m = build_manifold(cfg, state.params.c_raw)
    rg = m.egrad2rgrad(state.params.table, grads.table)
    table = m.expmap(state.params.table, -lr * rg)

    # Adam on the curvatures
    c_upd, curv_opt_state = curv_opt.update(
        grads.c_raw, state.curv_opt_state, state.params.c_raw)
    c_raw = optax.apply_updates(state.params.c_raw, c_upd)

    # the curvature change moves the manifold itself (sphere radius, ball
    # boundary) — re-project the table onto the *new* manifold
    table = build_manifold(cfg, c_raw).proj(table)

    new_state = TrainState(Params(table, c_raw), curv_opt_state, key, state.step + 1)
    return new_state, loss


@partial(jax.jit, static_argnames=("cfg", "curv_opt"), donate_argnames=("state",))
def train_step(cfg: ProductEmbedConfig, curv_opt, state: TrainState,
               pairs: jax.Array):
    return _step_body(cfg, curv_opt, state, pairs)


def make_sharded_step(cfg: ProductEmbedConfig, curv_opt, mesh):
    """The multi-host variant: same body, GSPMD shardings pinned.

    Batch indices are drawn on device and constrained to the (host, data)
    axes; the table and optimizer state are replicated, so XLA inserts the
    gradient all-reduce (ICI within a host, DCN across hosts) exactly where
    the reference used NCCL.
    """
    repl = replicated(mesh)

    def constrain(u, v, neg):
        return (
            jax.lax.with_sharding_constraint(u, batch_sharding(mesh, 1)),
            jax.lax.with_sharding_constraint(v, batch_sharding(mesh, 1)),
            jax.lax.with_sharding_constraint(neg, batch_sharding(mesh, 2)),
        )

    def body(state, pairs):
        return _step_body(cfg, curv_opt, state, pairs, constrain=constrain)

    return jax.jit(body, in_shardings=(repl, repl), out_shardings=(repl, repl),
                   donate_argnums=(0,))


def curvatures(cfg: ProductEmbedConfig, params: Params) -> list[float]:
    return [float(c) for c in jax.nn.softplus(params.c_raw)]


# --- evaluation ---------------------------------------------------------------


@partial(jax.jit, static_argnames=("cfg",))
def _rank_chunk(cfg: ProductEmbedConfig, params: Params,
                u_idx: jax.Array, v_idx: jax.Array) -> jax.Array:
    m = build_manifold(cfg, params.c_raw)
    u = params.table[u_idx]
    d_all = m.dist(u[:, None, :], params.table[None, :, :])
    d_pos = jnp.take_along_axis(d_all, v_idx[:, None], axis=1)
    closer = (d_all < d_pos).astype(jnp.int32)
    closer = closer.at[jnp.arange(u_idx.shape[0]), u_idx].set(0)
    closer = closer.at[jnp.arange(u_idx.shape[0]), v_idx].set(0)
    return jnp.sum(closer, axis=1) + 1


def evaluate(cfg: ProductEmbedConfig, params: Params, pairs, batch: int = 1024) -> dict:
    """Mean rank / MAP over held pairs (same protocol as Poincaré embed)."""
    pairs = np.asarray(pairs)
    ranks = []
    for s in range(0, len(pairs), batch):
        chunk = pairs[s : s + batch]
        r = _rank_chunk(cfg, params, jnp.asarray(chunk[:, 0]), jnp.asarray(chunk[:, 1]))
        ranks.append(np.asarray(r))
    ranks = np.concatenate(ranks)
    by_u: dict[int, list[int]] = {}
    for (u, v), r in zip(pairs, ranks):
        by_u.setdefault(int(u), []).append(int(r))
    aps, filtered = [], []
    for u, rs in by_u.items():
        rs = sorted(rs)
        aps.append(np.mean([(i + 1) / max(r, i + 1) for i, r in enumerate(rs)]))
        filtered.extend(max(r - i, 1) for i, r in enumerate(rs))
    return {"mean_rank": float(np.mean(filtered)), "map": float(np.mean(aps))}
