"""Poincaré embeddings (Nickel & Kiela 2017) — reference workload 1.

An embedding table on the curvature-c ball, trained so that ancestors are
close to their descendants: for a positive pair (u, v) and k sampled
negatives n₁..n_k,

    loss = -log [ exp(-d(u,v)) / (exp(-d(u,v)) + Σ exp(-d(u,nᵢ))) ].

Everything per-step — negative sampling, gather, distance matrix, loss,
gradient, Riemannian update — is one XLA program (the BASELINE.json single
compiled-train-step requirement).  Negatives are drawn on device with
``jax.random`` so the host feeds only the static closure array once.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.optim.rsgd import riemannian_sgd


@dataclasses.dataclass(frozen=True)
class PoincareEmbedConfig:
    num_nodes: int = 0
    dim: int = 10  # BASELINE.json configs[0]: 10-dim ball
    c: float = 1.0
    lr: float = 0.3
    neg_samples: int = 10
    batch_size: int = 512
    burnin_steps: int = 100
    burnin_factor: float = 0.01
    init_scale: float = 1e-3
    dtype: Any = jnp.float32


class TrainState(NamedTuple):
    table: jax.Array  # [N, d] points on the ball
    opt_state: Any
    key: jax.Array
    step: jax.Array


def init_table(cfg: PoincareEmbedConfig, key: jax.Array) -> jax.Array:
    """Uniform init in a tiny ball around the origin (N&K 2017 init)."""
    u = jax.random.uniform(
        key, (cfg.num_nodes, cfg.dim), cfg.dtype, -cfg.init_scale, cfg.init_scale
    )
    return u


def make_optimizer(cfg: PoincareEmbedConfig):
    ball = PoincareBall(cfg.c)
    return riemannian_sgd(
        cfg.lr,
        tags=ball,  # single-leaf param tree: the whole table is on the ball
        burnin_steps=cfg.burnin_steps,
        burnin_factor=cfg.burnin_factor,
    )


def loss_fn(
    table: jax.Array,
    u_idx: jax.Array,
    v_idx: jax.Array,
    neg_idx: jax.Array,
    c,
) -> jax.Array:
    """Batch loss. u_idx, v_idx: [B]; neg_idx: [B, K]."""
    ball = PoincareBall(c)
    u = table[u_idx]  # [B, d]
    cand = jnp.concatenate([v_idx[:, None], neg_idx], axis=1)  # [B, 1+K]
    cv = table[cand]  # [B, 1+K, d]
    d = ball.dist(u[:, None, :], cv)  # [B, 1+K]
    logits = -d
    # Mask sampled negatives that collide with the positive v or the query u
    # itself — otherwise ~K/N of rows get a log(2) loss floor and a gradient
    # pushing the true ancestor away. (Collisions with *other* ancestors of u
    # remain, as in standard on-the-fly sampled-softmax training.)
    collide = (neg_idx == v_idx[:, None]) | (neg_idx == u_idx[:, None])
    mask = jnp.concatenate([jnp.zeros_like(v_idx[:, None], bool), collide], axis=1)
    logits = jnp.where(mask, -jnp.inf, logits)
    # -log softmax(-d)[0]
    return jnp.mean(jax.nn.logsumexp(logits, axis=1) - logits[:, 0])


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_step(
    cfg: PoincareEmbedConfig,
    opt,
    state: TrainState,
    pairs: jax.Array,  # [P, 2] the full closure, resident on device
) -> tuple[TrainState, jax.Array]:
    key, k_batch, k_neg = jax.random.split(state.key, 3)
    num_pairs = pairs.shape[0]
    rows = jax.random.randint(k_batch, (cfg.batch_size,), 0, num_pairs)
    batch = pairs[rows]  # [B, 2]
    u_idx, v_idx = batch[:, 0], batch[:, 1]
    neg_idx = jax.random.randint(
        k_neg, (cfg.batch_size, cfg.neg_samples), 0, cfg.num_nodes
    )
    loss, grads = jax.value_and_grad(loss_fn)(state.table, u_idx, v_idx, neg_idx, cfg.c)
    updates, opt_state = opt.update(grads, state.opt_state, state.table)
    table = optax.apply_updates(state.table, updates)
    return TrainState(table, opt_state, key, state.step + 1), loss


def init_state(cfg: PoincareEmbedConfig, seed: int = 0) -> tuple[TrainState, optax.GradientTransformation]:
    """Build the initial state *and* its matching optimizer.

    Returned together so opt_state and the transformation can never be
    constructed from diverging configs.
    """
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    table = init_table(cfg, k_init)
    opt = make_optimizer(cfg)
    return TrainState(table, opt.init(table), key, jnp.zeros((), jnp.int32)), opt


# --- evaluation: MAP and mean rank over the closure (SURVEY.md §3.5) ----------


@jax.jit
def _rank_chunk(table: jax.Array, u_idx: jax.Array, v_idx: jax.Array, c):
    """For each pair (u, v): rank of v among all nodes by distance from u."""
    ball = PoincareBall(c)
    u = table[u_idx]  # [B, d]
    d_all = ball.dist(u[:, None, :], table[None, :, :])  # [B, N]
    d_pos = jnp.take_along_axis(d_all, v_idx[:, None], axis=1)  # [B, 1]
    # rank = #nodes strictly closer than v (excluding u itself and v)
    closer = (d_all < d_pos).astype(jnp.int32)
    closer = closer.at[jnp.arange(u_idx.shape[0]), u_idx].set(0)
    closer = closer.at[jnp.arange(u_idx.shape[0]), v_idx].set(0)
    return jnp.sum(closer, axis=1) + 1  # 1-based rank


def evaluate(table: jax.Array, pairs, c, batch: int = 1024) -> dict:
    """Mean rank and MAP of ground-truth ancestors, ranking all N nodes.

    Chunked distance matrix (SURVEY.md §3.5) — N×B blocks stream through the
    device; nothing materializes N×N.
    """
    import numpy as np

    pairs = np.asarray(pairs)
    ranks = []
    for s in range(0, len(pairs), batch):
        chunk_pairs = pairs[s : s + batch]
        r = _rank_chunk(
            table, jnp.asarray(chunk_pairs[:, 0]), jnp.asarray(chunk_pairs[:, 1]), c
        )
        ranks.append(np.asarray(r))
    ranks = np.concatenate(ranks)

    # N&K protocol: rank each ancestor v against *non-ancestor* nodes only
    # ("filtered"): sorting u's unfiltered ranks, the i-th has exactly i other
    # positives above it, so its filtered rank is r_i - i and the precision at
    # its position is (i+1)/r_i.
    by_u: dict[int, list[int]] = {}
    for (u, v), r in zip(pairs, ranks):
        by_u.setdefault(int(u), []).append(int(r))
    aps, filtered_ranks = [], []
    for u, rs in by_u.items():
        rs = sorted(rs)
        aps.append(np.mean([(i + 1) / max(r, i + 1) for i, r in enumerate(rs)]))
        filtered_ranks.extend(max(r - i, 1) for i, r in enumerate(rs))
    return {
        "mean_rank": float(np.mean(filtered_ranks)),
        "map": float(np.mean(aps)),
    }
