"""Poincaré embeddings (Nickel & Kiela 2017) — reference workload 1.

An embedding table on the curvature-c ball, trained so that ancestors are
close to their descendants: for a positive pair (u, v) and k sampled
negatives n₁..n_k,

    loss = -log [ exp(-d(u,v)) / (exp(-d(u,v)) + Σ exp(-d(u,nᵢ))) ].

Everything per-step — negative sampling, gather, distance matrix, loss,
gradient, Riemannian update — is one XLA program (the BASELINE.json single
compiled-train-step requirement).  Negatives are drawn on device with
``jax.random`` so the host feeds only the static closure array once.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from hyperspace_tpu.manifolds import PoincareBall
from hyperspace_tpu.optim.radam import RAdamState, riemannian_adam
from hyperspace_tpu.optim.rsgd import riemannian_sgd


@dataclasses.dataclass(frozen=True)
class PoincareEmbedConfig:
    num_nodes: int = 0
    dim: int = 10  # BASELINE.json configs[0]: 10-dim ball
    c: float = 1.0
    lr: float = 0.3
    neg_samples: int = 10
    batch_size: int = 512
    burnin_steps: int = 100
    burnin_factor: float = 0.01
    init_scale: float = 1e-3
    dtype: Any = jnp.float32
    # "rsgd" (Nickel & Kiela) or "radam" (Bécigneul & Ganea transported
    # moments) — both run inside the same single XLA-compiled train step
    optimizer: str = "rsgd"
    # sparse=True uses train_step_sparse: only the rows a batch touches are
    # gathered, updated and scattered back (SURVEY.md §7 hard-part #2) —
    # O(B·(2+K)·d) update work instead of O(N·d)
    sparse: bool = False
    # negative sampling policy for the DENSE step paths:
    #   "uniform" (default, bit-identical to the pre-mining build) draws
    #   neg_samples ids uniformly per row;
    #   "mined" draws a shared candidate pool of mine_pool ids uniformly,
    #   then keeps each row's neg_samples NEAREST pool members (sampled
    #   hard-negative mining) via the fused scan-top-k kernel
    #   (kernels/scan_topk.py; XLA twin on CPU) — the mining distances
    #   are stop_gradient'ed, so only the loss's own distance terms
    #   train.  Collisions with the row's u/v are masked by the loss as
    #   before.  The host-planned sparse paths keep uniform draws (their
    #   negatives are planned before the embeddings exist).
    neg_mode: str = "uniform"
    # candidate-pool size for neg_mode="mined" (0 = max(4*neg_samples, 64))
    mine_pool: int = 0
    # mixed-precision policy (hyperspace_tpu/precision.py).  This
    # workload is ALL boundary-sensitive math: the table is a master
    # parameter (policy: f32), and the per-step compute is the ball
    # distance + Riemannian update (policy: boundary/param, f32), so
    # "bf16" is bit-identical to "f32" here BY DESIGN — regression-
    # tested, because a bf16 cast creeping into this step is exactly the
    # failure the policy exists to prevent.  The workload's bf16 win
    # lives in the serving scan (serve/engine precision="bf16").
    precision: str = "f32"


class TrainState(NamedTuple):
    table: jax.Array  # [N, d] points on the ball
    opt_state: Any
    key: jax.Array
    step: jax.Array


def init_table(cfg: PoincareEmbedConfig, key: jax.Array) -> jax.Array:
    """Uniform init in a tiny ball around the origin (N&K 2017 init)."""
    u = jax.random.uniform(
        key, (cfg.num_nodes, cfg.dim), cfg.dtype, -cfg.init_scale, cfg.init_scale
    )
    return u


def make_optimizer(cfg: PoincareEmbedConfig):
    ball = PoincareBall(cfg.c)
    if cfg.optimizer == "radam":
        # burn-in as a schedule (radam has no native burn-in knob)
        lr = cfg.lr
        if cfg.burnin_steps > 0:
            factor, steps = cfg.burnin_factor, cfg.burnin_steps
            lr = lambda n: cfg.lr * jnp.where(n < steps, factor, 1.0)
        return riemannian_adam(lr, tags=ball)
    if cfg.optimizer != "rsgd":
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    return riemannian_sgd(
        cfg.lr,
        tags=ball,  # single-leaf param tree: the whole table is on the ball
        burnin_steps=cfg.burnin_steps,
        burnin_factor=cfg.burnin_factor,
    )


def _ranking_loss(u, cv, u_idx, v_idx, neg_idx, c):
    """-log softmax(-d)[positive]: u [B, d] against cv [B, 1+K, d]
    (column 0 = the positive v), with sampled negatives that collide with
    the positive v or the query u itself masked out -- otherwise ~K/N of
    rows get a log(2) loss floor and a gradient pushing the true ancestor
    away.  (Collisions with *other* ancestors of u remain, as in standard
    on-the-fly sampled-softmax training.)  The one loss body every step
    variant (dense / sparse / planned / packed) shares."""
    ball = PoincareBall(c)
    d = ball.dist(u[:, None, :], cv)
    logits = -d
    collide = (neg_idx == v_idx[:, None]) | (neg_idx == u_idx[:, None])
    mask = jnp.concatenate(
        [jnp.zeros_like(v_idx[:, None], bool), collide], axis=1)
    logits = jnp.where(mask, -jnp.inf, logits)
    return jnp.mean(jax.nn.logsumexp(logits, axis=1) - logits[:, 0])


def loss_fn(
    table: jax.Array,
    u_idx: jax.Array,
    v_idx: jax.Array,
    neg_idx: jax.Array,
    c,
) -> jax.Array:
    """Batch loss. u_idx, v_idx: [B]; neg_idx: [B, K]."""
    u = table[u_idx]  # [B, d]
    cand = jnp.concatenate([v_idx[:, None], neg_idx], axis=1)  # [B, 1+K]
    return _ranking_loss(u, table[cand], u_idx, v_idx, neg_idx, c)


def _mine_negatives(cfg: PoincareEmbedConfig, table: jax.Array,
                    u_idx: jax.Array, k_neg: jax.Array) -> jax.Array:
    """Sampled hard-negative mining (``neg_mode="mined"``): draw a
    shared uniform candidate pool, keep each row's ``neg_samples``
    nearest pool members under the ball metric — one fused scan-top-k
    over the pool slab (kernels/scan_topk.py), no [B, pool] distance
    matrix in HBM on the kernel path.  Everything is stop_gradient'ed:
    mining picks indices, the loss computes its own distances."""
    from hyperspace_tpu.kernels import scan_topk as fused_kernel

    pool = cfg.mine_pool or max(4 * cfg.neg_samples, 64)
    pool_idx = jax.random.randint(k_neg, (pool,), 0, cfg.num_nodes)
    tbl = jax.lax.stop_gradient(table)
    _, sel = fused_kernel.scan_topk(
        tbl[pool_idx], tbl[u_idx], jnp.zeros_like(u_idx), 0,
        spec=("poincare", cfg.c), k=cfg.neg_samples, n=pool,
        exclude_self=False)
    # sel slots are pool positions (always valid: neg_samples <= pool)
    return pool_idx[sel]                                  # [B, K]


def _check_neg_mode(cfg: PoincareEmbedConfig, *, dense: bool):
    if cfg.neg_mode not in ("uniform", "mined"):
        raise ValueError(
            f"neg_mode must be 'uniform' or 'mined'; got {cfg.neg_mode!r}")
    if cfg.neg_mode == "mined":
        if not dense:
            raise ValueError(
                "neg_mode='mined' needs the dense step paths (mining "
                "reads the live table; the host-planned sparse paths "
                "draw their negatives before the embeddings exist) — "
                "drop sparse=true or neg_mode")
        if not 0 < cfg.neg_samples <= (cfg.mine_pool
                                       or max(4 * cfg.neg_samples, 64)):
            raise ValueError(
                f"mine_pool={cfg.mine_pool} must hold at least "
                f"neg_samples={cfg.neg_samples} candidates")
        # mining has NO two-stage fallback (it IS the fused kernel), so
        # the kernel's hard caps must fail here, at config time, with a
        # config-shaped message — not mid-training from inside jit
        from hyperspace_tpu.kernels import scan_topk as fused_kernel

        if not fused_kernel.supports(("poincare", cfg.c),
                                     k=cfg.neg_samples, dim=cfg.dim):
            raise ValueError(
                f"neg_mode='mined' mines through the fused scan-top-k "
                f"kernel, which caps neg_samples at "
                f"{fused_kernel.FUSED_MAX_K} and dim at "
                f"{fused_kernel.FUSED_MAX_DIM}; got neg_samples="
                f"{cfg.neg_samples}, dim={cfg.dim} — lower them or "
                "drop neg_mode")


def _dense_step_body(
    cfg: PoincareEmbedConfig,
    opt,
    state: TrainState,
    pairs: jax.Array,
) -> tuple[TrainState, jax.Array]:
    """Un-jitted dense step body: device-side batch + negative sampling
    (uniform, or sampled hard-negative mining under ``neg_mode="mined"``
    — :func:`_mine_negatives`), loss, grad, whole-table Riemannian
    update.  Shared verbatim by :func:`train_step` (one dispatch per
    step) and :func:`train_epoch_scan` (one dispatch per epoch) so the
    two trajectories are the same computation."""
    # trace-time and free: direct train_step/train_epoch_scan callers
    # (bench, tests) get the same config-shaped errors make_train_step
    # raises — a bad mined config must never surface kernel internals
    _check_neg_mode(cfg, dense=True)
    key, k_batch, k_neg = jax.random.split(state.key, 3)
    num_pairs = pairs.shape[0]
    rows = jax.random.randint(k_batch, (cfg.batch_size,), 0, num_pairs)
    batch = pairs[rows]  # [B, 2]
    u_idx, v_idx = batch[:, 0], batch[:, 1]
    if cfg.neg_mode == "mined":
        neg_idx = _mine_negatives(cfg, state.table, u_idx, k_neg)
    else:
        neg_idx = jax.random.randint(
            k_neg, (cfg.batch_size, cfg.neg_samples), 0, cfg.num_nodes
        )
    loss, grads = jax.value_and_grad(loss_fn)(state.table, u_idx, v_idx, neg_idx, cfg.c)
    updates, opt_state = opt.update(grads, state.opt_state, state.table)
    table = optax.apply_updates(state.table, updates)
    return TrainState(table, opt_state, key, state.step + 1), loss


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_step(
    cfg: PoincareEmbedConfig,
    opt,
    state: TrainState,
    pairs: jax.Array,  # [P, 2] the full closure, resident on device
) -> tuple[TrainState, jax.Array]:
    return _dense_step_body(cfg, opt, state, pairs)


@partial(jax.jit, static_argnames=("cfg", "opt", "steps"),
         donate_argnames=("state",))
def train_epoch_scan(
    cfg: PoincareEmbedConfig,
    opt,
    state: TrainState,
    pairs: jax.Array,  # [P, 2] the full closure, resident on device
    steps: int,
) -> tuple[TrainState, jax.Array]:
    """``steps`` dense steps as ONE XLA program (`lax.scan` over the step
    body).  At WordNet scale the per-step device work is ~tens of µs of
    compute on a [66 k, 10] table, so an epoch of separate dispatches is
    dominated by launch latency; scanning the epoch removes all but one
    dispatch.  Bitwise the same trajectory as ``steps`` calls of
    :func:`train_step` (same body, same PRNG stream).  Returns the final
    state and the [steps] per-step losses."""

    def body(st, _):
        return _dense_step_body(cfg, opt, st, pairs)

    return jax.lax.scan(body, state, None, length=steps)


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_step_sparse(
    cfg: PoincareEmbedConfig,
    opt,
    state: TrainState,
    pairs: jax.Array,  # [P, 2] the full closure, resident on device
) -> tuple[TrainState, jax.Array]:
    """Sparse-row variant of `train_step` (SURVEY.md §7 hard-part #2).

    The dense step differentiates a gather into a full [N, d] cotangent and
    expmaps the whole table; fine at WordNet scale, ruinous for arxiv-scale
    tables.  Here the batch's unique touched rows (≤ B·(2+K), static shape)
    are gathered, the loss is computed on the gathered sub-table, and only
    those rows are updated and scattered back — update work is O(B·(2+K)·d)
    regardless of N.  TPU mechanics: `jnp.unique(..., size=...)` keeps the
    shape static; sentinel-padded slots point one past the table, gather
    clips them (their gradient is identically zero) and the final scatter
    uses ``mode="drop"`` so they never write back.

    Optimizer-state semantics for stateful optimizers (radam): moment rows
    are gathered/updated/scattered with the same index set — untouched rows
    keep stale moments ("lazy" sparse Adam, geoopt's
    SparseRiemannianAdam/torch SparseAdam semantics), while bias correction
    uses the global step count.  For rsgd the sparse step is mathematically
    identical to the dense one (untouched rows: expmap(x, 0) = x).
    """
    # a mined config reaching the sparse step directly would otherwise
    # silently train on uniform negatives — reject like make_train_step
    _check_neg_mode(cfg, dense=False)
    key, k_batch, k_neg = jax.random.split(state.key, 3)
    num_pairs = pairs.shape[0]
    rows_sel = jax.random.randint(k_batch, (cfg.batch_size,), 0, num_pairs)
    batch = pairs[rows_sel]  # [B, 2]
    u_idx, v_idx = batch[:, 0], batch[:, 1]
    neg_idx = jax.random.randint(
        k_neg, (cfg.batch_size, cfg.neg_samples), 0, cfg.num_nodes
    )

    b = cfg.batch_size
    all_idx = jnp.concatenate([u_idx, v_idx, neg_idx.reshape(-1)])
    # return_inverse gives every slot mapping in the one unique call — the
    # r02 version re-derived them with three searchsorted passes
    uniq, inv = jnp.unique(all_idx, size=all_idx.shape[0],
                           fill_value=cfg.num_nodes, return_inverse=True)
    rows = state.table[jnp.minimum(uniq, cfg.num_nodes - 1)]  # [U, d]

    def sub_loss(rows):
        cand_slots = jnp.concatenate(
            [inv[b : 2 * b, None], inv[2 * b :].reshape(b, -1)], axis=1)
        return _ranking_loss(rows[inv[:b]], rows[cand_slots],
                             u_idx, v_idx, neg_idx, cfg.c)

    loss, g_rows = jax.value_and_grad(sub_loss)(rows)

    # run the optimizer transform on the gathered rows; gather/scatter any
    # per-row optimizer state (radam moments) with the same index set
    opt_state = state.opt_state
    if isinstance(opt_state, RAdamState):
        row_state = RAdamState(
            count=opt_state.count,
            mu=opt_state.mu[jnp.minimum(uniq, cfg.num_nodes - 1)],
            nu=opt_state.nu[jnp.minimum(uniq, cfg.num_nodes - 1)],
        )
        updates, row_state = opt.update(g_rows, row_state, rows)
        # explicit casts: under x64 the bias-corrected moments come back
        # f64; scattering them into the f32 state arrays must not rely on
        # implicit (and soon-to-be-removed) scatter dtype promotion
        new_opt_state = RAdamState(
            count=row_state.count,
            mu=opt_state.mu.at[uniq].set(
                row_state.mu.astype(opt_state.mu.dtype), mode="drop"),
            nu=opt_state.nu.at[uniq].set(
                row_state.nu.astype(opt_state.nu.dtype), mode="drop"),
        )
    else:  # stateless-per-row (rsgd: count only)
        updates, new_opt_state = opt.update(g_rows, opt_state, rows)
    new_rows = optax.apply_updates(rows, updates)
    table = state.table.at[uniq].set(
        new_rows.astype(state.table.dtype), mode="drop")
    return TrainState(table, new_opt_state, key, state.step + 1), loss


def make_train_step(cfg: PoincareEmbedConfig):
    """The configured step function: ``f(cfg, opt, state, pairs)``."""
    _check_neg_mode(cfg, dense=not cfg.sparse)
    return train_step_sparse if cfg.sparse else train_step


# --- host-planned sparse updates (VERDICT r2 next #2) -------------------------
#
# `train_step_sparse` pays a device-side sort (jnp.unique) every step —
# measured 3.6x slower than the dense step on TPU at WordNet scale, because
# the table work it saves is smaller than the sort latency it adds.  The
# planned variant moves ALL index preparation to the host, amortized over a
# chunk of steps (the `make_planned_pairs` philosophy from the HGCN LP
# decoder applied to embedding batches):
#
# - batches + negatives are drawn on host (numpy, vectorized over the chunk);
# - each step's flat index multiset is argsorted ONCE on host, yielding:
#   uniq (sorted unique rows, sentinel-padded), inv_map (flat position →
#   slot), order (occurrences sorted by row), seg_sorted (their slots,
#   ascending);
# - on device the step is: one sorted gather of touched rows (+ their radam
#   moment rows), the batch loss through `_dedup_gather` — whose custom VJP
#   routes every cotangent through gathers and one SORTED segment-sum (no
#   unsorted scatter anywhere in autodiff) — the optimizer on the [U, d]
#   sub-table, and three sorted scatter-sets (table, mu, nu) with
#   ``mode="drop"`` for the sentinel rows.
#
# No device sort, no searchsorted, no unsorted scatter: update work is
# O(B·(2+K)·d) + the sorted-scatter latency, independent of N.


class SparsePlan(NamedTuple):
    """Device-resident plan for S planned-sparse steps (host-built).

    U = B·(2+K) flat index slots per step; all arrays static-shaped.
    """

    u_idx: jax.Array       # [S, B]
    v_idx: jax.Array       # [S, B]
    neg_idx: jax.Array     # [S, B, K]
    uniq: jax.Array        # [S, U] sorted unique rows, sentinel = num_nodes
    inv_map: jax.Array     # [S, U] flat position -> slot in uniq
    order: jax.Array       # [S, U] occurrences argsorted by row id
    seg_sorted: jax.Array  # [S, U] = inv_map[order] (ascending)


def plan_arrays_np(cfg: PoincareEmbedConfig, u_idx, v_idx, neg_idx):
    """The numpy planning pass behind :func:`plan_from_indices` —
    returns the seven plan arrays as HOST numpy (the host-resident
    trainer keeps them on host to union/remap before any transfer)."""
    import numpy as np

    steps = u_idx.shape[0]
    u_idx = np.asarray(u_idx, np.int32)
    v_idx = np.asarray(v_idx, np.int32)
    neg_idx = np.asarray(neg_idx, np.int32)
    flat = np.concatenate(
        [u_idx, v_idx, neg_idx.reshape(steps, -1)], axis=1)   # [S, U]
    order = np.argsort(flat, axis=1, kind="stable").astype(np.int32)
    sorted_ids = np.take_along_axis(flat, order, axis=1)
    # slot boundaries: a new unique row wherever the sorted id changes
    new_seg = np.ones_like(sorted_ids, bool)
    new_seg[:, 1:] = sorted_ids[:, 1:] != sorted_ids[:, :-1]
    seg_sorted = (np.cumsum(new_seg, axis=1) - 1).astype(np.int32)
    u_slots = flat.shape[1]
    uniq = np.full((steps, u_slots), cfg.num_nodes, np.int32)
    s_grid, _ = np.nonzero(new_seg)
    uniq[s_grid, seg_sorted[new_seg]] = sorted_ids[new_seg]
    inv_map = np.empty_like(seg_sorted)
    np.put_along_axis(inv_map, order, seg_sorted, axis=1)
    return u_idx, v_idx, neg_idx, uniq, inv_map, order, seg_sorted


def plan_from_indices(cfg: PoincareEmbedConfig, u_idx, v_idx,
                      neg_idx) -> SparsePlan:
    """Build the per-step index plans for explicit [S, B] / [S, B, K]
    batches — one vectorized numpy pass, ~milliseconds per epoch-chunk."""
    return SparsePlan(*(jnp.asarray(a) for a in
                        plan_arrays_np(cfg, u_idx, v_idx, neg_idx)))


def plan_sparse_steps(cfg: PoincareEmbedConfig, pairs, steps: int,
                      seed: int = 0) -> SparsePlan:
    """Draw ``steps`` batches + negatives on host and plan their indices."""
    import numpy as np

    _check_neg_mode(cfg, dense=False)
    rng = np.random.default_rng(seed)
    pairs = np.asarray(pairs)
    b, k = cfg.batch_size, cfg.neg_samples
    batch = pairs[rng.integers(0, len(pairs), (steps, b))]    # [S, B, 2]
    neg_idx = rng.integers(0, cfg.num_nodes, (steps, b, k))
    return plan_from_indices(cfg, batch[..., 0], batch[..., 1], neg_idx)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _dedup_gather(rows, inv_map, order, seg_sorted, num_slots: int):
    """rows[inv_map] whose VJP never scatters: the cotangent is permuted
    into row-sorted occurrence order (a gather) and combined per slot with
    a SORTED segment-sum."""
    return rows[inv_map]


def _dg_fwd(rows, inv_map, order, seg_sorted, num_slots):
    return rows[inv_map], (inv_map, order, seg_sorted)


def _dg_bwd(num_slots, res, g):
    inv_map, order, seg_sorted = res
    acc_dt = jnp.promote_types(g.dtype, jnp.float32)
    d_rows = jax.ops.segment_sum(
        g[order].astype(acc_dt), seg_sorted, num_slots,
        indices_are_sorted=True).astype(g.dtype)
    return d_rows, None, None, None


_dedup_gather.defvjp(_dg_fwd, _dg_bwd)


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_step_sparse_planned(
    cfg: PoincareEmbedConfig,
    opt,
    state: TrainState,
    plan: SparsePlan,
) -> tuple[TrainState, jax.Array]:
    """One planned-sparse step; consumes plan row ``state.step % S``.

    Mathematically identical to the dense step on the planned batch
    (duplicate cotangents are summed per row before the expmap), with the
    same lazy-moment radam semantics as `train_step_sparse`.
    """
    s = plan.u_idx.shape[0]
    i = state.step % s
    take = lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
    u_idx, v_idx, neg_idx, uniq, inv_map, order, seg_sorted = (
        take(a) for a in plan)
    b = cfg.batch_size
    n_slots = uniq.shape[0]
    safe_uniq = jnp.minimum(uniq, cfg.num_nodes - 1)
    rows = state.table[safe_uniq]  # [U, d] sorted gather

    def sub_loss(rows):
        flat = _dedup_gather(rows, inv_map, order, seg_sorted, n_slots)
        cv = jnp.concatenate(
            [flat[b : 2 * b, None],
             flat[2 * b :].reshape(b, -1, rows.shape[-1])], axis=1)
        return _ranking_loss(flat[:b], cv, u_idx, v_idx, neg_idx, cfg.c)

    loss, g_rows = jax.value_and_grad(sub_loss)(rows)

    opt_state = state.opt_state
    if isinstance(opt_state, RAdamState):
        row_state = RAdamState(
            count=opt_state.count,
            mu=opt_state.mu[safe_uniq],
            nu=opt_state.nu[safe_uniq],
        )
        updates, row_state = opt.update(g_rows, row_state, rows)
        new_opt_state = RAdamState(
            count=row_state.count,
            mu=opt_state.mu.at[uniq].set(
                row_state.mu.astype(opt_state.mu.dtype),
                mode="drop", indices_are_sorted=True),
            nu=opt_state.nu.at[uniq].set(
                row_state.nu.astype(opt_state.nu.dtype),
                mode="drop", indices_are_sorted=True),
        )
    else:
        updates, new_opt_state = opt.update(g_rows, opt_state, rows)
    new_rows = optax.apply_updates(rows, updates)
    table = state.table.at[uniq].set(
        new_rows.astype(state.table.dtype),
        mode="drop", indices_are_sorted=True)
    return TrainState(table, new_opt_state, key_after(state.key),
                      state.step + 1), loss


def key_after(key: jax.Array) -> jax.Array:
    """Advance the state PRNG key (planned steps draw nothing on device,
    but the key must still move so dense/sparse states stay interchangeable)."""
    return jax.random.split(key, 1)[0]


# --- packed planned state: one gather + ONE scatter per step ------------------
#
# On-chip breakdown at 598 k rows (docs/benchmarks.md sparse section): the
# planned radam step spent ~2.6 ms of its 4.7 ms in its three sorted
# scatter-sets (table, mu, nu) — each scatter pays the serialization
# latency once.  Packing the table and both moment tables side-by-side as
# one [N, 3d] array (a layout private to the planned path; `unpack_state`
# restores the standard TrainState) turns the update into ONE [U, 3d]
# gather and ONE sorted scatter-set, which is what lets the sparse path
# finally beat the dense step at arxiv-scale tables.


class PackedState(NamedTuple):
    packed: jax.Array  # [N, d] (rsgd) or [N, 2d+1] (radam: table|mu|nu-scalar)
    aux: Any           # non-row optimizer state (counts)
    key: jax.Array
    step: jax.Array


def pack_state(cfg: PoincareEmbedConfig, state: TrainState) -> PackedState:
    if isinstance(state.opt_state, RAdamState):
        packed = jnp.concatenate(
            [state.table, state.opt_state.mu, state.opt_state.nu], axis=1)
        aux = state.opt_state.count
    else:
        packed = state.table
        aux = state.opt_state
    return PackedState(packed, aux, state.key, state.step)


def unpack_state(cfg: PoincareEmbedConfig, p: PackedState) -> TrainState:
    d = cfg.dim
    if p.packed.shape[1] > d:  # radam rows: table | mu | nu (nu is [*, 1])
        table = p.packed[:, :d]
        opt_state = RAdamState(count=p.aux, mu=p.packed[:, d : 2 * d],
                               nu=p.packed[:, 2 * d :])
    else:
        table, opt_state = p.packed, p.aux
    return TrainState(table, opt_state, p.key, p.step)


def _packed_row_body(
    cfg: PoincareEmbedConfig,
    opt,
    state: PackedState,
    row: SparsePlan,  # single-step slices: [B], [B], [B, K], [U] ×4
    sorted_indices: bool = True,
) -> tuple[PackedState, jax.Array]:
    """Un-jitted packed-planned step body on one plan row; shared by
    :func:`train_step_planned_packed` and :func:`train_epoch_planned_packed`
    (``sorted_indices=True`` — per-step uniq rows are ascending) and by
    :func:`train_epoch_planned_hosted` (``False`` — the host-resident
    trainer remaps rows to device hot-cache SLOTS, which are arbitrary
    after the first eviction; same math, the scatter just loses its
    sortedness hint)."""
    u_idx, v_idx, neg_idx, uniq, inv_map, order, seg_sorted = row
    b, d = cfg.batch_size, cfg.dim
    n_slots = uniq.shape[0]
    safe_uniq = jnp.minimum(uniq, cfg.num_nodes - 1)
    all_rows = state.packed[safe_uniq]        # ONE gather, [U, d or 3d]
    rows = all_rows[:, :d]

    def sub_loss(rows):
        flat = _dedup_gather(rows, inv_map, order, seg_sorted, n_slots)
        cv = jnp.concatenate(
            [flat[b : 2 * b, None], flat[2 * b :].reshape(b, -1, d)], axis=1)
        return _ranking_loss(flat[:b], cv, u_idx, v_idx, neg_idx, cfg.c)

    loss, g_rows = jax.value_and_grad(sub_loss)(rows)

    if all_rows.shape[1] > d:  # radam: moments ride in the packed rows
        row_state = RAdamState(count=state.aux, mu=all_rows[:, d : 2 * d],
                               nu=all_rows[:, 2 * d :])
        updates, row_state = opt.update(g_rows, row_state, rows)
        new_all = jnp.concatenate(
            [optax.apply_updates(rows, updates),
             row_state.mu.astype(all_rows.dtype),
             row_state.nu.astype(all_rows.dtype)], axis=1)
        aux = row_state.count
    else:
        updates, aux = opt.update(g_rows, state.aux, rows)
        new_all = optax.apply_updates(rows, updates)
    packed = state.packed.at[uniq].set(
        new_all.astype(state.packed.dtype),
        mode="drop", indices_are_sorted=sorted_indices)  # ONE scatter
    return PackedState(packed, aux, key_after(state.key), state.step + 1), loss


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_step_planned_packed(
    cfg: PoincareEmbedConfig,
    opt,
    state: PackedState,
    plan: SparsePlan,
) -> tuple[PackedState, jax.Array]:
    """`train_step_sparse_planned` on a :class:`PackedState` — identical
    math, one row gather and one sorted scatter-set regardless of the
    optimizer's moment count.  Consumes plan row ``state.step % S``."""
    s = plan.u_idx.shape[0]
    i = state.step % s
    take = lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
    row = SparsePlan(*(take(a) for a in plan))
    return _packed_row_body(cfg, opt, state, row)


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_epoch_planned_packed(
    cfg: PoincareEmbedConfig,
    opt,
    state: PackedState,
    plan: SparsePlan,
) -> tuple[PackedState, jax.Array]:
    """All S planned steps as ONE XLA program: `lax.scan` over the plan
    rows in order.  Identical trajectory to S calls of
    :func:`train_step_planned_packed` when ``state.step % S == 0`` at
    entry (the single-step variant picks rows by ``step % S``, the scan
    consumes them front to back).  Returns the final state and the [S]
    per-step losses."""

    def body(st, row):
        return _packed_row_body(cfg, opt, st, row)

    return jax.lax.scan(body, state, plan)


@partial(jax.jit, static_argnames=("cfg", "opt"), donate_argnames=("state",))
def train_epoch_planned_hosted(
    cfg: PoincareEmbedConfig,
    opt,
    state: PackedState,
    plan: SparsePlan,
) -> tuple[PackedState, jax.Array]:
    """:func:`train_epoch_planned_packed` for the host-resident trainer
    (train/host_embed.py): ``state.packed`` is the device HOT-ROW CACHE
    (``[C, W]``, ``parallel/host_table.DeviceHotCache``) and the plan's
    ``uniq`` rows are remapped to cache slots — arbitrary order, so the
    scatter drops its sortedness hint; ``cfg.num_nodes`` must be the
    cache capacity C (the remapped sentinel).  Mathematically the same
    per-row computation as the in-HBM program — the host path is
    bitwise-identical to it on small tables (tested)."""

    def body(st, row):
        return _packed_row_body(cfg, opt, st, row, sorted_indices=False)

    return jax.lax.scan(body, state, plan)


def init_state(cfg: PoincareEmbedConfig, seed: int = 0) -> tuple[TrainState, optax.GradientTransformation]:
    """Build the initial state *and* its matching optimizer.

    Returned together so opt_state and the transformation can never be
    constructed from diverging configs.
    """
    from hyperspace_tpu import precision as precision_mod

    precision_mod.get_policy(cfg.precision)  # validate the name early
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    table = init_table(cfg, k_init)
    opt = make_optimizer(cfg)
    return TrainState(table, opt.init(table), key, jnp.zeros((), jnp.int32)), opt


# --- evaluation: MAP and mean rank over the closure (SURVEY.md §3.5) ----------


@jax.jit
def _rank_chunk(table: jax.Array, u_idx: jax.Array, v_idx: jax.Array, c):
    """For each pair (u, v): rank of v among all nodes by distance from u."""
    from hyperspace_tpu.kernels.distmat import pdist

    u = table[u_idx]  # [B, d]
    # fused [B, N] distance tile (kernels/distmat.py — one Gram matmul +
    # rank-1 broadcasts per tile, no [B, N, d] difference tensor); the
    # XLA twin == PoincareBall.dist pairwise, parity-tested
    d_all = pdist(u, table, c, manifold="poincare")  # [B, N]
    d_pos = jnp.take_along_axis(d_all, v_idx[:, None], axis=1)  # [B, 1]
    # rank = #nodes strictly closer than v (excluding u itself and v)
    closer = (d_all < d_pos).astype(jnp.int32)
    closer = closer.at[jnp.arange(u_idx.shape[0]), u_idx].set(0)
    closer = closer.at[jnp.arange(u_idx.shape[0]), v_idx].set(0)
    return jnp.sum(closer, axis=1) + 1  # 1-based rank


def evaluate(table: jax.Array, pairs, c, batch: int = 1024) -> dict:
    """Mean rank and MAP of ground-truth ancestors, ranking all N nodes.

    Chunked distance matrix (SURVEY.md §3.5) — N×B blocks stream through the
    device; nothing materializes N×N.
    """
    import numpy as np

    pairs = np.asarray(pairs)
    ranks = []
    for s in range(0, len(pairs), batch):
        chunk_pairs = pairs[s : s + batch]
        r = _rank_chunk(
            table, jnp.asarray(chunk_pairs[:, 0]), jnp.asarray(chunk_pairs[:, 1]), c
        )
        ranks.append(np.asarray(r))
    ranks = np.concatenate(ranks)

    # N&K protocol: rank each ancestor v against *non-ancestor* nodes only
    # ("filtered"): sorting u's unfiltered ranks, the i-th has exactly i other
    # positives above it, so its filtered rank is r_i - i and the precision at
    # its position is (i+1)/r_i.
    by_u: dict[int, list[int]] = {}
    for (u, v), r in zip(pairs, ranks):
        by_u.setdefault(int(u), []).append(int(r))
    aps, filtered_ranks = [], []
    for u, rs in by_u.items():
        rs = sorted(rs)
        aps.append(np.mean([(i + 1) / max(r, i + 1) for i, r in enumerate(rs)]))
        filtered_ranks.extend(max(r - i, 1) for i, r in enumerate(rs))
    return {
        "mean_rank": float(np.mean(filtered_ranks)),
        "map": float(np.mean(aps)),
    }
