"""HyboNet — fully-hyperbolic Lorentz transformer (reference workload 3).

BASELINE.json configs[2]: hyperbolic transformer for text classification,
semantics per Chen et al. ACL 2022 (SURVEY.md §2 "HyboNet model").

Architecture [PLAN], everything on the hyperboloid:

    tokens ──(tangent embed + positional tangent)── exp₀ ──► points
    × L blocks:   x ← midpoint(x, MHA(x))          (hyperbolic residual)
                  x ← midpoint(x, FFN(x))          (2 × LorentzLinear)
    pool: masked Lorentz centroid over the sequence
    head: Lorentz MLR → class logits

The hyperbolic residual is the Lorentz midpoint (centroid of the pair) —
the standard fully-hyperbolic replacement for ``x + f(x)``; LorentzLinear
and the attention aggregation keep every intermediate exactly on-manifold,
so no tangent round-trips appear anywhere in a block (the HyboNet design
point, and the reason this maps well onto the MXU: blocks are matmuls +
row-wise time-coordinate reconstructions).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu import precision as precision_mod
from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.nn.attention import HypMultiHeadAttention
from hyperspace_tpu.nn.gcn import from_tangent0_coords
from hyperspace_tpu.nn.layers import LorentzLinear
from hyperspace_tpu.nn.mlr import LorentzMLR


@dataclasses.dataclass(frozen=True)
class HyboNetConfig:
    vocab_size: int = 512
    num_classes: int = 4
    max_len: int = 32
    dim: int = 64  # manifold dim (ambient dim+1)
    num_heads: int = 4
    num_layers: int = 2
    ffn_mult: int = 2
    c: float = 1.0
    lr: float = 1e-3
    weight_decay: float = 1e-4
    dropout: float = 0.0
    batch_size: int = 64
    # "flash" (default) = the N7 Pallas flash-attention kernel on TPU
    # (kernels/attention.py; dense twin on CPU) — the default workload
    # executes the flagship kernel on chip.  "scan" = the XLA
    # online-softmax KV scan (the ring-attention per-device body).
    attention_impl: str = "flash"
    dtype: Any = jnp.float32
    # mixed-precision policy (hyperspace_tpu/precision.py): "bf16" runs
    # the LorentzLinear / attention-projection matmuls — the model's MXU
    # mass — in bf16 while params, every time-coordinate reconstruction,
    # centroids and the MLR head stay f32.  "f32" (default) is
    # bit-identical to the pre-policy model.
    precision: str = "f32"


class HyboNetBlock(nn.Module):
    cfg: HyboNetConfig
    manifold: Lorentz

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array, *, deterministic=True):
        cfg, m = self.cfg, self.manifold
        # matmuls run in the policy's compute dtype; centroids and every
        # time-coordinate reconstruction stay in the storage dtype
        cdt = precision_mod.get_policy(cfg.precision).module_dtype()
        # self-attention sublayer with padding mask
        att_mask = mask[..., None, :] & mask[..., :, None]  # [B, L, L]
        a = HypMultiHeadAttention(
            dim=cfg.dim, num_heads=cfg.num_heads, manifold=m,
            impl=cfg.attention_impl, compute_dtype=cdt, name="mha",
        )(x, mask=att_mask)
        x = m.centroid(jnp.stack([x, a], axis=-2))  # hyperbolic residual
        # FFN sublayer: expand (with tangent ReLU on ambient input) → project
        f = LorentzLinear(cfg.dim * cfg.ffn_mult, m, activation=nn.relu,
                          compute_dtype=cdt, name="ffn_in")(x)
        f = LorentzLinear(cfg.dim, m, compute_dtype=cdt, name="ffn_out")(f)
        x = m.centroid(jnp.stack([x, f], axis=-2))
        return x


class HyboNetClassifier(nn.Module):
    cfg: HyboNetConfig

    @nn.compact
    def __call__(self, tokens: jax.Array, mask: jax.Array, *, deterministic=True):
        cfg = self.cfg
        m = Lorentz(cfg.c)
        emb = self.param(
            "tok_embed", nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.dim), cfg.dtype)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (cfg.max_len, cfg.dim), cfg.dtype)
        v = emb[tokens] + pos[None, : tokens.shape[-1]]  # origin-tangent coords
        if cfg.dropout > 0:
            v = nn.Dropout(cfg.dropout)(v, deterministic=deterministic)
        x = from_tangent0_coords(m, v)  # [B, L, dim+1] on the hyperboloid
        for i in range(cfg.num_layers):
            x = HyboNetBlock(cfg, m, name=f"block{i}")(
                x, mask, deterministic=deterministic)
        # masked centroid pooling over the sequence
        pooled = m.centroid(x, mask.astype(x.dtype))  # [B, dim+1]
        return LorentzMLR(cfg.num_classes, m, name="head")(pooled)


# --- training ----------------------------------------------------------------


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    key: jax.Array
    step: jax.Array


def init_model(cfg: HyboNetConfig, seed: int = 0):
    model = HyboNetClassifier(cfg)
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    dummy_t = jnp.zeros((2, cfg.max_len), jnp.int32)
    dummy_m = jnp.ones((2, cfg.max_len), bool)
    params = model.init({"params": k_init}, dummy_t, dummy_m)["params"]
    opt = optax.adamw(cfg.lr, weight_decay=cfg.weight_decay)
    state = TrainState(params, opt.init(params), key, jnp.zeros((), jnp.int32))
    return model, opt, state


def _step_impl(model, opt, state, tokens, mask, labels, constrain=None):
    """Shared step body; ``constrain`` pins the batch's sharding (the
    only difference between the single-device and mesh-sharded steps)."""
    key, k_drop = jax.random.split(state.key)
    if constrain is not None:
        tokens, mask, labels = (constrain(t) for t in (tokens, mask, labels))

    def loss_fn(params):
        logits = model.apply(
            {"params": params}, tokens, mask,
            deterministic=False, rngs={"dropout": k_drop})
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels))

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, key, state.step + 1), loss


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step(model, opt, state: TrainState, tokens, mask, labels):
    """One step over a [B, L] batch — a single XLA program."""
    return _step_impl(model, opt, state, tokens, mask, labels)


@partial(jax.jit, static_argnames=("model",))
def eval_logits(model, params, tokens, mask):
    return model.apply({"params": params}, tokens, mask)


def _sampled_impl(model, opt, state, toks, mask, labels, constrain=None):
    key, k_next = jax.random.split(state.key)
    idx = jax.random.randint(k_next, (model.cfg.batch_size,), 0, toks.shape[0])
    return _step_impl(model, opt, state._replace(key=key),
                      toks[idx], mask[idx], labels[idx], constrain)


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step_sampled(model, opt, state: TrainState, toks, mask, labels):
    """Minibatch sampled on device from ``state.key``: the data-iterator
    state is the (checkpointed) PRNG key and the step stays one XLA
    program (SURVEY.md §5 "Checkpoint / resume": data-iterator state)."""
    return _sampled_impl(model, opt, state, toks, mask, labels)


def make_sharded_step(model, opt, mesh, state: TrainState, toks, mask, labels):
    """Data-parallel sampled train step over ``mesh``: the on-device
    minibatch shards over the data-like axes (XLA inserts the gradient
    all-reduce over ICI/DCN — SURVEY.md §2 N8), the dataset arrays are
    placed replicated ONCE (re-broadcasting them per step would swamp the
    step).  Returns ``(step, placed_state, (toks, mask, labels))``; call
    as ``state, loss = step(state, toks, mask, labels)``.  ``batch_size``
    must be divisible by the data-axis extent."""
    from hyperspace_tpu.parallel.mesh import data_extent, replicated, shard_batch
    from hyperspace_tpu.parallel.tp import state_shardings

    d = data_extent(mesh)
    if model.cfg.batch_size % d:
        raise ValueError(
            f"batch_size={model.cfg.batch_size} not divisible by the "
            f"mesh's data extent {d}")
    state_sh = state_shardings(state, state.params, mesh)
    repl = replicated(mesh)
    step = jax.jit(
        partial(_sampled_impl, model, opt,
                constrain=partial(shard_batch, mesh=mesh)),
        in_shardings=(state_sh, repl, repl, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )
    data = tuple(jax.device_put(t, repl) for t in (toks, mask, labels))
    return step, jax.device_put(state, state_sh), data


def train(cfg: HyboNetConfig, ds, steps: int = 200, seed: int = 0):
    """Minibatch training loop over a TextDataset; returns (model, params)."""
    model, opt, state = init_model(cfg, seed)
    toks = jnp.asarray(ds.tokens)
    mask = jnp.asarray(ds.mask)
    labels = jnp.asarray(ds.labels)
    loss = jnp.nan
    for _ in range(steps):
        state, loss = train_step_sampled(model, opt, state, toks, mask, labels)
    return model, state.params, float(loss)


def evaluate(model, params, ds, batch: int = 256) -> dict:
    from hyperspace_tpu.utils import metrics as metrics_lib

    outs = []
    for s in range(0, len(ds.labels), batch):
        outs.append(np.asarray(eval_logits(
            model, params,
            jnp.asarray(ds.tokens[s : s + batch]),
            jnp.asarray(ds.mask[s : s + batch]))))
    logits = np.concatenate(outs)
    return {"accuracy": metrics_lib.accuracy(logits, ds.labels)}
