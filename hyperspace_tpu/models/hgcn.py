"""HGCN — hyperbolic graph convolutional network (reference workload 2).

BASELINE.json configs[1]: HGCN on Cora / ogbn-arxiv, **Lorentz model**; the
north-star metric is samples/sec/chip and matching test ROC-AUC
(SURVEY.md §0, §3.2, §6).

Model shape (Chami et al. NeurIPS 2019):

    features --exp0--> manifold --[HGCConv × L]--> embeddings z
    LP head: FermiDirac(d²(z_u, z_v)) → BCE → ROC-AUC
    NC head: hyperbolic MLR → CE → accuracy/F1

The whole step — forward over the full padded graph, loss, grad, Adam
update — is one jitted XLA program.  Full-graph training is the natural
TPU formulation for graphs of Cora/arxiv scale: the [N, d] node tensor and
the padded edge list are static shapes resident in HBM, and every layer is
one MXU matmul plus masked segment ops (SURVEY.md §7 hard-part #3).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu import precision as precision_lib
from hyperspace_tpu.data import graphs as graph_data
from hyperspace_tpu.nn.decoders import FermiDiracDecoder
from hyperspace_tpu.nn.gcn import HGCConv, from_tangent0_coords, make_manifold
from hyperspace_tpu.nn.mlr import LorentzMLR, HypMLR
from hyperspace_tpu.utils import metrics as metrics_lib


@dataclasses.dataclass(frozen=True)
class HGCNConfig:
    feat_dim: int = 32
    hidden_dims: Sequence[int] = (64, 16)
    kind: str = "lorentz"  # BASELINE.json: Lorentz model for workload 2
    c: float = 1.0
    learn_c: bool = False
    use_att: bool = False
    dropout: float = 0.0
    num_classes: int = 0  # NC head only when > 0
    lr: float = 1e-2
    weight_decay: float = 5e-4
    # >0: clip the global gradient norm before adamw.  The attention
    # arm's measured failure mode (docs/benchmarks.md convergence §2) is
    # a collapse to the degenerate logits-0 solution driven by early
    # gradient spikes; clipping at ~1.0 removes the cliff (regression-
    # tested in tests/models/test_stability.py).  0 disables.
    clip_norm: float = 0.0
    neg_per_pos: int = 1  # LP negatives sampled per positive per step
    dtype: Any = jnp.float32
    # edge-message dtype for neighbor aggregation (None = dtype); bf16
    # halves the dominant HBM traffic while the kernel accumulates f32
    agg_dtype: Any = None
    # dtype of the LP decoder's pair-distance pass during TRAINING
    # (None = dtype); eval always scores in full precision.  bf16 halves
    # the 2.2 M-pair gather/scatter traffic; the planned scatters
    # (train_step_lp_pairs / _planned) get the full bandwidth win, the
    # unplanned step's XLA scatter much less — docs/benchmarks.md
    decoder_dtype: Any = None
    # rematerialize each conv layer in the backward pass (jax.checkpoint):
    # trades an extra forward per layer for not storing its residuals.
    # Measured at arxiv-like shapes the peak temp is a single pass's
    # [E, F] working set, not the residuals, so this only pays off for
    # DEEP stacks (many layers) or very wide features; off by default.
    remat: bool = False
    # mixed-precision policy (hyperspace_tpu/precision.py): "bf16" maps
    # onto this model's quality-validated bf16 lanes — agg_dtype (edge
    # messages) and decoder_dtype (training pair-distance pass) — while
    # the encoder compute, every manifold op and all reductions stay
    # f32 (the docs/benchmarks.md quality-anchor config).  Explicit
    # agg_dtype/decoder_dtype always win over the policy mapping.
    precision: str = "f32"

    def resolved_agg_dtype(self):
        """agg_dtype as executed: the explicit field, else the policy's
        compute dtype when mixed, else None (= dtype)."""
        pol = precision_lib.get_policy(self.precision)
        if self.agg_dtype is not None:
            return self.agg_dtype
        return pol.compute if pol.mixed else None

    def resolved_decoder_dtype(self):
        """decoder_dtype as executed (same resolution rule)."""
        pol = precision_lib.get_policy(self.precision)
        if self.decoder_dtype is not None:
            return self.decoder_dtype
        return pol.compute if pol.mixed else None


class HGCNEncoder(nn.Module):
    """Feature lift (exp0) + stacked HGCConv layers over a DeviceGraph."""

    cfg: HGCNConfig

    @nn.compact
    def __call__(self, g: graph_data.DeviceGraph, *, deterministic=True):
        cfg = self.cfg
        m0 = make_manifold(cfg.kind, cfg.c)
        # Euclidean features are origin-tangent coordinates; lift to the
        # manifold (SURVEY.md §3.2 "embed: expmap₀(features)").
        h = from_tangent0_coords(m0, g.x.astype(cfg.dtype))
        c_prev = cfg.c
        for i, d in enumerate(cfg.hidden_dims):
            is_last = i == len(cfg.hidden_dims) - 1
            conv = HGCConv(
                features=d,
                kind=cfg.kind,
                c_in=c_prev,
                c_out=cfg.c,
                learn_c=cfg.learn_c,
                use_att=cfg.use_att,
                dropout_rate=cfg.dropout,
                activation=(lambda v: v) if is_last else nn.relu,
                agg_dtype=cfg.resolved_agg_dtype(),
                name=f"conv{i}",
            )
            if cfg.remat:
                # re-run the layer's forward during the backward instead
                # of keeping its [N, F] / [E, F] intermediates live — the
                # HBM lever for beyond-arxiv graphs.  Static curvature
                # only: the remat'd callable must return arrays, so the
                # output manifold is reconstructed outside.
                if cfg.learn_c:
                    raise ValueError("remat=True requires learn_c=False "
                                     "(the remat boundary returns arrays)")

                def run_conv(mdl, hh):
                    out, _ = mdl(hh, g, deterministic=deterministic)
                    return out

                h = nn.remat(run_conv)(conv, h)
                m = make_manifold(cfg.kind, cfg.c)
            else:
                h, m = conv(h, g, deterministic=deterministic)
            c_prev = m.c
        return h, m  # points on the final layer's manifold


class HGCNLinkPred(nn.Module):
    """Encoder + Fermi–Dirac decoder; returns edge logits."""

    cfg: HGCNConfig

    @nn.compact
    def __call__(self, g: graph_data.DeviceGraph, pairs, *, deterministic=True):
        z, m = HGCNEncoder(self.cfg, name="encoder")(
            g, deterministic=deterministic
        )
        ddt = self.cfg.resolved_decoder_dtype()
        if ddt is not None and not deterministic:
            z = z.astype(ddt)  # train only; eval full-prec
        sq = m.sqdist(z[pairs[:, 0]], z[pairs[:, 1]])
        return FermiDiracDecoder(name="decoder")(sq.astype(self.cfg.dtype))

    @nn.compact
    def split_pair_logits(self, g: graph_data.DeviceGraph, pos, neg, *,
                          deterministic=True):
        """``(pos_logits, neg_logits)`` with ONE encoder pass and NO
        concatenation of the two pair batches — the dp×tp-safe form
        of :meth:`__call__`: this image's jax 0.4.37 GSPMD miscompiles
        ``concatenate`` when any operand or consumer carries a
        batch-sharding constraint over a subset of a multi-axis mesh's
        axes (see ``_lp_step_impl``), so the sharded LP step gathers
        the two batches separately and combines scalars only."""
        z, m = HGCNEncoder(self.cfg, name="encoder")(
            g, deterministic=deterministic
        )
        ddt = self.cfg.resolved_decoder_dtype()
        if ddt is not None and not deterministic:
            z = z.astype(ddt)  # train only; eval full-prec
        dec = FermiDiracDecoder(name="decoder")
        sq_p = m.sqdist(z[pos[:, 0]], z[pos[:, 1]])
        sq_n = m.sqdist(z[neg[:, 0]], z[neg[:, 1]])
        return (dec(sq_p.astype(self.cfg.dtype)),
                dec(sq_n.astype(self.cfg.dtype)))

    @nn.compact
    def pair_logits(self, g: graph_data.DeviceGraph, pos, neg_u, neg_v,
                    neg_plan, *, deterministic=True):
        """Logits for one LP step with every *static* scatter planned:
        positives are the run's train_pos pairs through
        `pair_sqdist_planned` (both endpoint scatters block-CSR), negatives
        corrupt only v (u-side planned).  ``pos`` is the bundle from
        :func:`make_planned_pairs`.  Returns (pos_logits [P], neg_logits [Q])."""
        from hyperspace_tpu.nn.edge_dist import (
            pair_sqdist_planned,
            pair_sqdist_semi_planned,
        )

        z, m = HGCNEncoder(self.cfg, name="encoder")(
            g, deterministic=deterministic
        )
        ddt = self.cfg.resolved_decoder_dtype()
        if ddt is not None:
            z = z.astype(ddt)
        sq_pos = pair_sqdist_planned(
            z, m.c, pos.u, pos.v, *pos.u_plan, pos.v_perm, pos.v_sorted,
            *pos.v_plan, self.cfg.kind)
        npb, npc, npf = neg_plan
        sq_neg = pair_sqdist_semi_planned(z, m.c, neg_u, neg_v,
                                          npb, npc, npf, self.cfg.kind)
        dec = FermiDiracDecoder(name="decoder")
        return (dec(sq_pos.astype(self.cfg.dtype)),
                dec(sq_neg.astype(self.cfg.dtype)))

    @nn.compact
    def edge_logits(self, g: graph_data.DeviceGraph, neg_u, neg_v, neg_plan,
                    *, deterministic=True):
        """Fast-path logits for one LP train step (same params as __call__):
        positives scored on the graph's own (sorted, planned) edge list and
        negatives on (static sorted u, fresh v) pairs, so every decoder
        gradient scatter is planned (nn/edge_dist.py).  Returns
        (pos_logits [E], pos_weight [E], neg_logits [P])."""
        from hyperspace_tpu.nn.edge_dist import (
            graph_edge_sqdist,
            pair_sqdist_semi_planned,
        )

        if g.rev_perm is None:
            raise ValueError(
                "edge_logits needs a symmetric edge layout — build the graph "
                "with graphs.prepare(..., symmetrize=True) (rev_perm is None)")
        z, m = HGCNEncoder(self.cfg, name="encoder")(
            g, deterministic=deterministic
        )
        ddt = self.cfg.resolved_decoder_dtype()
        if ddt is not None:
            z = z.astype(ddt)  # train-only method
        pb, pc, pf = g.plan if g.plan is not None else (None, None, None)
        sq_pos = graph_edge_sqdist(z, m.c, g.senders, g.receivers, g.rev_perm,
                                   pb, pc, pf, self.cfg.kind)
        sq_pos = sq_pos.astype(self.cfg.dtype)
        # self-loops are degenerate positives (d = 0); weight them out
        w_pos = (g.edge_mask & (g.senders != g.receivers)).astype(sq_pos.dtype)
        npb, npc, npf = neg_plan
        sq_neg = pair_sqdist_semi_planned(z, m.c, neg_u, neg_v,
                                          npb, npc, npf, self.cfg.kind)
        dec = FermiDiracDecoder(name="decoder")
        return dec(sq_pos), w_pos, dec(sq_neg.astype(self.cfg.dtype))


class HGCNNodeClf(nn.Module):
    """Encoder + hyperbolic MLR head; returns per-node class logits."""

    cfg: HGCNConfig

    @nn.compact
    def __call__(self, g: graph_data.DeviceGraph, *, deterministic=True):
        z, m = HGCNEncoder(self.cfg, name="encoder")(
            g, deterministic=deterministic
        )
        if self.cfg.kind == "euclidean":  # flat control: plain linear head
            return nn.Dense(self.cfg.num_classes, name="head")(z)
        head = LorentzMLR if self.cfg.kind == "lorentz" else HypMLR
        return head(self.cfg.num_classes, m, name="head")(z)


# --- training ----------------------------------------------------------------


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    key: jax.Array
    step: jax.Array


def make_optimizer(cfg: HGCNConfig) -> optax.GradientTransformation:
    # the clip stage is always present (inf = no-op) so the opt_state
    # pytree structure is identical across clip_norm settings — a
    # checkpoint written with clipping on restores with it off and
    # vice versa (orbax restore is structure-strict)
    max_norm = cfg.clip_norm if cfg.clip_norm > 0.0 else float("inf")
    return optax.chain(optax.clip_by_global_norm(max_norm),
                       optax.adamw(cfg.lr, weight_decay=cfg.weight_decay))


def _device_graph(g: graph_data.Graph) -> graph_data.DeviceGraph:
    return graph_data.to_device(g)


# ---- link prediction ----


def init_lp(cfg: HGCNConfig, g: graph_data.Graph, seed: int = 0):
    model = HGCNLinkPred(cfg)
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    dg = _device_graph(g)
    dummy_pairs = jnp.zeros((2, 2), jnp.int32)
    params = model.init({"params": k_init}, dg, dummy_pairs)["params"]
    opt = make_optimizer(cfg)
    state = TrainState(params, opt.init(params), key, jnp.zeros((), jnp.int32))
    return model, opt, state


def _lp_step_impl(model, opt, num_nodes, state, g, train_pos, constrain=None,
                  split_pairs=False):
    """Shared LP step body: sample negatives on device, BCE on pos+neg
    logits.  ``constrain`` (optional) pins the supervision batch's sharding
    (GSPMD hint) — the only difference between the single-device and the
    mesh-sharded step, so both jit wrappers compile this same program."""
    key, k_neg, k_drop = jax.random.split(state.key, 3)
    n_neg = train_pos.shape[0] * model.cfg.neg_per_pos
    neg = jax.random.randint(k_neg, (n_neg, 2), 0, num_nodes)

    def loss_fn(params):
        if constrain is not None and split_pairs:
            # multi-axis-mesh form: NO concatenate anywhere near the
            # constrained batch.  This image's jax 0.4.37 GSPMD
            # miscompiles `concatenate` when any operand — or any
            # downstream consumer, via backward sharding propagation —
            # carries a with_sharding_constraint over a proper subset
            # of a multi-axis mesh's axes (P(("data",), None) on a
            # dp×tp mesh): the output is assembled from the model-axis
            # sub-shard with full-width strides, garbling every row's
            # VALUES, not just their order (root-caused in PR 9;
            # reduced repro: tests/parallel/test_node_sharded.py::
            # test_gspmd_concat_constraint_miscompile).  So under such
            # a mesh the step gathers pos and neg separately (one
            # encoder pass, no pair concat) and combines scalar sums.
            # Single-axis (dp-only) meshes partition the concat
            # correctly and keep the historical form below, unchanged.
            pos_logit, neg_logit = model.apply(
                {"params": params}, g,
                constrain(train_pos), constrain(neg),
                deterministic=False, rngs={"dropout": k_drop},
                method=HGCNLinkPred.split_pair_logits,
            )
            bce_pos = optax.sigmoid_binary_cross_entropy(
                pos_logit, jnp.ones_like(pos_logit))
            bce_neg = optax.sigmoid_binary_cross_entropy(
                neg_logit, jnp.zeros_like(neg_logit))
            return ((jnp.sum(bce_pos) + jnp.sum(bce_neg))
                    / (pos_logit.shape[0] + neg_logit.shape[0]))
        tp, ng = train_pos, neg
        if constrain is not None:
            tp, ng = constrain(tp), constrain(ng)
        pairs = jnp.concatenate([tp, ng], axis=0)
        logits = model.apply(
            {"params": params}, g, pairs,
            deterministic=False, rngs={"dropout": k_drop},
        )
        labels = jnp.concatenate(
            [jnp.ones(train_pos.shape[0]), jnp.zeros(n_neg)]
        ).astype(logits.dtype)
        return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, key, state.step + 1), loss


@partial(jax.jit, static_argnames=("model", "opt", "num_nodes"), donate_argnames=("state",))
def train_step_lp(
    model: HGCNLinkPred,
    opt,
    num_nodes: int,
    state: TrainState,
    g: graph_data.DeviceGraph,
    train_pos: jax.Array,  # [P, 2]
):
    """One LP step: sample negatives on device, BCE on pos+neg logits."""
    return _lp_step_impl(model, opt, num_nodes, state, g, train_pos)


class PlannedPairs(NamedTuple):
    """Static supervision pairs with both-side CSR scatter plans
    (see nn/edge_dist.pair_sqdist_planned)."""

    u: jax.Array         # [P] sorted
    v: jax.Array         # [P] aligned with u
    u_plan: tuple
    v_perm: jax.Array    # [P] argsort of v
    v_sorted: jax.Array  # [P]
    v_plan: tuple


def make_planned_pairs(pairs: np.ndarray, num_nodes: int) -> PlannedPairs:
    """One-time host-side prep of a static pair set for the fully-planned
    decoder pass: sort by u and build its CSR plan; keep the static argsort
    of the aligned v column with its own plan for the backward."""
    from hyperspace_tpu.kernels.segment import build_csr_plan

    pairs = np.asarray(pairs)
    order = np.argsort(pairs[:, 0], kind="stable")
    u = np.ascontiguousarray(pairs[order, 0]).astype(np.int32)
    v = np.ascontiguousarray(pairs[order, 1]).astype(np.int32)
    v_perm = np.argsort(v, kind="stable").astype(np.int32)
    v_sorted = v[v_perm]
    to_dev = lambda plan: tuple(jnp.asarray(a) for a in plan)
    return PlannedPairs(
        u=jnp.asarray(u), v=jnp.asarray(v),
        u_plan=to_dev(build_csr_plan(u, num_nodes)),
        v_perm=jnp.asarray(v_perm), v_sorted=jnp.asarray(v_sorted),
        v_plan=to_dev(build_csr_plan(v_sorted, num_nodes)),
    )


@partial(jax.jit, static_argnames=("model", "opt", "num_nodes"), donate_argnames=("state",))
def train_step_lp_pairs(
    model: HGCNLinkPred,
    opt,
    num_nodes: int,
    state: TrainState,
    g: graph_data.DeviceGraph,
    pos: "PlannedPairs",
    neg_u: jax.Array,
    neg_plan: tuple,
):
    """One LP step scoring exactly the train positives with both decoder
    scatters planned, plus corrupt-one-side negatives (u planned).  Same
    pair count as `train_step_lp`; the only unsorted scatter left in the
    decoder backward is the negatives' fresh-random v side, which cannot
    be pre-planned (VERDICT r1 #6)."""
    assert neg_u.shape[0] == pos.u.shape[0] * model.cfg.neg_per_pos, (
        f"neg_u has {neg_u.shape[0]} rows; cfg.neg_per_pos="
        f"{model.cfg.neg_per_pos} needs {pos.u.shape[0]} * neg_per_pos "
        "(size the static negatives with make_static_negatives accordingly)")
    key, k_neg, k_drop = jax.random.split(state.key, 3)
    neg_v = jax.random.randint(k_neg, neg_u.shape, 0, num_nodes)

    def loss_fn(params):
        pos_logit, neg_logit = model.apply(
            {"params": params}, g, pos, neg_u, neg_v, neg_plan,
            deterministic=False, rngs={"dropout": k_drop},
            method=HGCNLinkPred.pair_logits,
        )
        bce_pos = optax.sigmoid_binary_cross_entropy(
            pos_logit, jnp.ones_like(pos_logit))
        bce_neg = optax.sigmoid_binary_cross_entropy(
            neg_logit, jnp.zeros_like(neg_logit))
        return ((jnp.sum(bce_pos) + jnp.sum(bce_neg))
                / (pos_logit.shape[0] + neg_logit.shape[0]))

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, key, state.step + 1), loss


def make_static_negatives(num_nodes: int, n_neg: int, seed: int = 0):
    """Host-side one-time negative scaffold for the planned LP step: a
    sorted static u column with its CSR plan; only v re-randomizes on
    device each step (corrupt-one-side sampling — the u marginal is fixed
    uniform, drawn once)."""
    from hyperspace_tpu.kernels.segment import build_csr_plan

    rng = np.random.default_rng(seed)
    u = np.sort(rng.integers(0, num_nodes, n_neg)).astype(np.int32)
    plan = tuple(jnp.asarray(a) for a in build_csr_plan(u, num_nodes))
    return jnp.asarray(u), plan


@partial(jax.jit, static_argnames=("model", "opt", "num_nodes"), donate_argnames=("state",))
def train_step_lp_planned(
    model: HGCNLinkPred,
    opt,
    num_nodes: int,
    state: TrainState,
    g: graph_data.DeviceGraph,
    neg_u: jax.Array,  # [P] sorted static (make_static_negatives)
    neg_plan: tuple,
):
    """One LP step with every decoder gradient scatter planned: positives
    are the graph's own edge list, negatives corrupt only the v side."""
    key, k_neg, k_drop = jax.random.split(state.key, 3)
    neg_v = jax.random.randint(k_neg, neg_u.shape, 0, num_nodes)

    def loss_fn(params):
        pos_logit, w_pos, neg_logit = model.apply(
            {"params": params}, g, neg_u, neg_v, neg_plan,
            deterministic=False, rngs={"dropout": k_drop},
            method=HGCNLinkPred.edge_logits,
        )
        bce_pos = optax.sigmoid_binary_cross_entropy(
            pos_logit, jnp.ones_like(pos_logit))
        bce_neg = optax.sigmoid_binary_cross_entropy(
            neg_logit, jnp.zeros_like(neg_logit))
        denom = jnp.sum(w_pos) + neg_logit.shape[0]
        return (jnp.sum(bce_pos * w_pos) + jnp.sum(bce_neg)) / denom

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, key, state.step + 1), loss


def _concat_hazard(mesh) -> bool:
    """True when ``mesh`` has a non-trivial axis outside the
    batch-sharding ("host"/"data") set — the mesh shape under which
    this image's jax 0.4.37 GSPMD miscompiles a constrained
    ``concatenate`` (``_lp_step_impl``'s split_pairs rationale)."""
    return any(int(mesh.shape[a]) > 1 for a in mesh.axis_names
               if a not in ("host", "data"))


def round_up_pairs(pairs: np.ndarray, mesh) -> np.ndarray:
    """Resize a [P, 2] supervision batch to a multiple of the mesh's
    data-axis extent (GSPMD needs the sharded axis divisible).  Repeats
    the leading edges cyclically — a negligible reweighting of a batch
    that already covers every positive edge each step."""
    from hyperspace_tpu.parallel.mesh import data_extent

    d = data_extent(mesh)
    n = -(-pairs.shape[0] // d) * d
    return np.resize(np.asarray(pairs), (n, 2))


def make_sharded_step_lp(
    model: HGCNLinkPred,
    opt,
    num_nodes: int,
    mesh,
    state: TrainState,
    g: graph_data.DeviceGraph,
):
    """Build a dp×tp LP train step jitted over ``mesh`` (SURVEY.md §2 N8).

    Compiles the *same* step body as `train_step_lp` with GSPMD shardings:
    the supervision batch (positives + sampled negatives) is sharded over
    the data-like mesh axes, so the gradient all-reduce XLA inserts is the
    NCCL all-reduce of the reference's trainer riding ICI; 2-D kernels are
    column-sharded over the ``model`` axis when present
    (`parallel/tp.tp_param_shardings`); optimizer moments are co-located
    with their parameter shards; the graph itself is replicated.

    Returns ``(step, placed_state, placed_graph)`` — call as
    ``state, loss = step(state, g, train_pos)``; ``state`` is donated.
    """
    from hyperspace_tpu.parallel.mesh import batch_sharding, replicated
    from hyperspace_tpu.parallel.tp import replicated_like, state_shardings

    state_sh = state_shardings(state, state.params, mesh)
    g_sh = replicated_like(g, mesh)
    bsh = batch_sharding(mesh, ndim=2)
    constrain = lambda x: jax.lax.with_sharding_constraint(x, bsh)

    # batch enters replicated and is constrained *in-program* (like
    # product_embed.make_sharded_step): a partitioned in_sharding would
    # reject process-local arrays on a multi-host mesh (and segfaults
    # XLA CPU on jax 0.4.37 when combined with restored+donated state on
    # a dp×tp mesh).  The per-host data plane feeds the node-sharded
    # builder below, which takes pairs batch-sharded.
    step = jax.jit(
        partial(_lp_step_impl, model, opt, num_nodes, constrain=constrain,
                split_pairs=_concat_hazard(mesh)),
        in_shardings=(state_sh, g_sh, replicated(mesh)),
        out_shardings=(state_sh, replicated(mesh)),
        donate_argnums=(0,),
    )
    return step, jax.device_put(state, state_sh), jax.device_put(g, g_sh)


def make_node_sharded_step_lp(
    model: HGCNLinkPred,
    opt,
    num_nodes: int,
    mesh,
    state: TrainState,
    split: graph_data.LinkSplit,
    halo="auto",  # forwarded to partition_graph ("a2a"/"ppermute" force
    # that exchange schedule, False forces the all-gather, "auto" picks
    # by estimated compiled bytes — parallel/node_shard.py doc)
):
    """LP train step whose ENCODER work divides across the mesh.

    `make_sharded_step_lp` shards only the supervision pairs — the
    full-graph encoder (~95% of step time) is replicated per device.
    This builder instead node-shards the graph (`parallel/node_shard`):
    the [N, F] activations, every matmul row, and each shard's slice of
    the edge aggregation live on one device; the only collective in the
    encoder is an [N, F] all-gather per layer per direction riding ICI.
    Per-device FLOPs and HBM bytes scale ~1/ndev (asserted by
    tests/parallel/test_node_sharded.py's compiled-cost check).

    Mean aggregation uses the involution backward (no cross-shard
    scatter); attention works too — the receiver partition keeps its
    segment softmax shard-local (`parallel.node_shard.
    node_sharded_att_aggregate`, autodiff collectives).  Returns
    ``(step, placed_state, placed_graph)``; call as
    ``state, loss = step(state, nsg, train_pos)``.
    """
    from hyperspace_tpu.parallel.mesh import batch_sharding, replicated
    from hyperspace_tpu.parallel.node_shard import graph_shardings, shard_graph
    from hyperspace_tpu.parallel.tp import state_shardings

    nsg = shard_graph(split.graph, mesh, halo=halo)
    state_sh = state_shardings(state, state.params, mesh)
    bsh = batch_sharding(mesh, ndim=2)
    constrain = lambda x: jax.lax.with_sharding_constraint(x, bsh)

    step = jax.jit(
        partial(_lp_step_impl, model, opt, num_nodes, constrain=constrain,
                split_pairs=_concat_hazard(mesh)),
        # pairs arrive BATCH-SHARDED (not replicated): the multi-process
        # data plane feeds a global array each host assembled from only
        # its own row range (multihost.distribute_batch); uncommitted
        # single-process arrays get placed the same way
        in_shardings=(state_sh, graph_shardings(nsg), bsh),
        out_shardings=(state_sh, replicated(mesh)),
        donate_argnums=(0,),
    )
    return step, jax.device_put(state, state_sh), nsg


def make_node_sharded_step_nc(
    model: HGCNNodeClf,
    opt,
    mesh,
    state: TrainState,
    g: graph_data.Graph,
    halo="auto",
):
    """NC twin of `make_node_sharded_step_lp`: node-sharded encoder, with
    labels/train-mask padded to the sharded node count and the per-node
    cross-entropy terms sharded over the same axes.  Returns
    ``(step, placed_state, placed_graph, labels, train_mask)``.
    """
    from hyperspace_tpu.parallel.mesh import replicated
    from hyperspace_tpu.parallel.node_shard import (
        graph_shardings,
        pad_node_array,
        shard_graph,
    )
    from hyperspace_tpu.parallel.tp import state_shardings

    nsg = shard_graph(g, mesh, halo=halo)
    n_pad = nsg.x.shape[0]
    labels = jnp.asarray(pad_node_array(g.labels, n_pad, 0))
    train_mask = jnp.asarray(pad_node_array(g.train_mask, n_pad, False))
    state_sh = state_shardings(state, state.params, mesh)
    nsh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(nsg.axes))
    constrain = lambda x: jax.lax.with_sharding_constraint(x, nsh)

    step = jax.jit(
        partial(_nc_step_impl, model, opt, constrain=constrain),
        in_shardings=(state_sh, graph_shardings(nsg),
                      replicated(mesh), replicated(mesh)),
        out_shardings=(state_sh, replicated(mesh)),
        donate_argnums=(0,),
    )
    return step, jax.device_put(state, state_sh), nsg, labels, train_mask


@partial(jax.jit, static_argnames=("model",))
def eval_scores_lp(model: HGCNLinkPred, params, g: graph_data.DeviceGraph, pairs):
    return model.apply({"params": params}, g, pairs)


def evaluate_lp(model, params, split: graph_data.LinkSplit, which: str = "test",
                ga: graph_data.DeviceGraph | None = None) -> dict:
    """LP ROC-AUC; pass ``ga`` to reuse an already-transferred DeviceGraph."""
    ga = _device_graph(split.graph) if ga is None else ga
    pos = jnp.asarray(getattr(split, f"{which}_pos"))
    neg = jnp.asarray(getattr(split, f"{which}_neg"))
    s_pos = np.asarray(eval_scores_lp(model, params, ga, pos))
    s_neg = np.asarray(eval_scores_lp(model, params, ga, neg))
    return {"roc_auc": metrics_lib.roc_auc(s_pos, s_neg)}


def train_lp(
    cfg: HGCNConfig,
    split: graph_data.LinkSplit,
    steps: int = 200,
    seed: int = 0,
    log_every: int = 0,
) -> tuple[Any, Any, list]:
    """Full LP training loop; returns (model, params, history)."""
    model, opt, state = init_lp(cfg, split.graph, seed)
    ga = _device_graph(split.graph)
    train_pos = jnp.asarray(split.train_pos)
    history = []
    for i in range(steps):
        state, loss = train_step_lp(model, opt, split.graph.num_nodes, state, ga, train_pos)
        if log_every and (i + 1) % log_every == 0:
            ev = evaluate_lp(model, state.params, split, "val", ga=ga)
            history.append({"step": i + 1, "loss": float(loss), **ev})
    return model, state.params, history


# ---- node classification ----


def init_nc(cfg: HGCNConfig, g: graph_data.Graph, seed: int = 0):
    model = HGCNNodeClf(cfg)
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    dg = _device_graph(g)
    params = model.init({"params": k_init}, dg)["params"]
    opt = make_optimizer(cfg)
    state = TrainState(params, opt.init(params), key, jnp.zeros((), jnp.int32))
    return model, opt, state


def _nc_step_impl(model, opt, state, g, labels, train_mask, constrain=None):
    """Shared NC step body; ``constrain`` optionally pins the per-node
    loss terms' sharding (data-parallel over the node axis)."""
    key, k_drop = jax.random.split(state.key)

    def loss_fn(params):
        logits = model.apply(
            {"params": params}, g,
            deterministic=False, rngs={"dropout": k_drop},
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        if constrain is not None:
            ce = constrain(ce)
        w = train_mask.astype(ce.dtype)
        return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, key, state.step + 1), loss


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step_nc(
    model: HGCNNodeClf,
    opt,
    state: TrainState,
    g: graph_data.DeviceGraph,
    labels: jax.Array,  # [N] int32
    train_mask: jax.Array,  # [N] bool
):
    return _nc_step_impl(model, opt, state, g, labels, train_mask)


def make_sharded_step_nc(
    model: HGCNNodeClf,
    opt,
    mesh,
    state: TrainState,
    g: graph_data.DeviceGraph,
):
    """dp×tp NC train step over ``mesh`` — the NC twin of
    `make_sharded_step_lp`: per-node cross-entropy terms shard over the
    data-like axes (GSPMD partitions the node-dim compute and inserts the
    gradient all-reduce), 2-D kernels column-shard over ``model``.
    Returns ``(step, placed_state, placed_graph)``; call as
    ``state, loss = step(state, g, labels, train_mask)``.
    """
    from hyperspace_tpu.parallel.mesh import batch_sharding, replicated
    from hyperspace_tpu.parallel.tp import replicated_like, state_shardings

    state_sh = state_shardings(state, state.params, mesh)
    g_sh = replicated_like(g, mesh)
    nsh = batch_sharding(mesh, ndim=1)
    constrain = lambda x: jax.lax.with_sharding_constraint(x, nsh)

    step = jax.jit(
        partial(_nc_step_impl, model, opt, constrain=constrain),
        in_shardings=(state_sh, g_sh, replicated(mesh), replicated(mesh)),
        out_shardings=(state_sh, replicated(mesh)),
        donate_argnums=(0,),
    )
    return step, jax.device_put(state, state_sh), jax.device_put(g, g_sh)


@partial(jax.jit, static_argnames=("model",))
def eval_logits_nc(model: HGCNNodeClf, params, g: graph_data.DeviceGraph):
    return model.apply({"params": params}, g)


def evaluate_nc(model: HGCNNodeClf, params, g: graph_data.Graph,
                ga: graph_data.DeviceGraph | None = None) -> dict:
    """NC metrics; pass ``ga`` to reuse an already-transferred DeviceGraph
    (the [N, F] feature tensor is ~90 MB at arxiv scale)."""
    logits = np.asarray(eval_logits_nc(
        model, params, _device_graph(g) if ga is None else ga))
    return {
        "val_acc": metrics_lib.accuracy(logits, g.labels, g.val_mask),
        "test_acc": metrics_lib.accuracy(logits, g.labels, g.test_mask),
        "test_f1": metrics_lib.f1_macro(
            logits, g.labels, model.cfg.num_classes, g.test_mask),
    }


def train_nc(
    cfg: HGCNConfig,
    g: graph_data.Graph,
    steps: int = 200,
    seed: int = 0,
) -> tuple[Any, Any, dict]:
    model, opt, state = init_nc(cfg, g, seed)
    ga = _device_graph(g)
    labels = jnp.asarray(g.labels)
    tr = jnp.asarray(g.train_mask)
    for _ in range(steps):
        state, loss = train_step_nc(model, opt, state, ga, labels, tr)
    res = {"loss": float(loss), **evaluate_nc(model, state.params, g, ga=ga)}
    return model, state.params, res
