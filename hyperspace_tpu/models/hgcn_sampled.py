"""Neighbor-sampled minibatch HGCN training (GraphSAGE-style fanouts).

Full-graph HGCN training (models/hgcn.py) holds every [N, F] layer
activation per step — the right trade at ogbn-arxiv scale, but the
per-step footprint grows with the graph, and its "samples/s" counts
every node each step.  This module is the complementary training mode
the reference family ships alongside full-graph trainers [INFERRED —
SURVEY.md §1a "models" layer]: fixed-fanout neighbor sampling with
**static block shapes**, where one step supervises exactly
``batch_size`` labeled seed nodes.

TPU-first design (what makes this NOT a translation of a CPU sampler
loop):

- **No scatter, no segment ops, no edge lists on device.**  A batch is a
  pyramid of dense index blocks — seeds ``[B]``, their sampled neighbors
  ``[B, f1]``, the neighbors' neighbors ``[B, f1, f2]`` — so every
  aggregation is a plain ``mean`` over a trailing axis of an MXU-shaped
  tensor.  The irregular work (adjacency walk, uniform draws) happens in
  the native C++ sampler (`data/_native/sampler.cc`) on the host, where
  it belongs.
- **Unbiased estimator of the full-graph operator.**  The full-graph
  layer aggregates with self-loop-inclusive mean weights
  ``(h_self + Σ_nbrs h) / (1 + n_nbrs)``; the sampled layer computes
  ``(h_self + (n_nbrs / f) · Σ_{f samples} h) / (1 + n_nbrs)`` whose
  expectation over the sampler's uniform draws is exactly the full sum.
  Nodes whose degree ≤ the fanout are reconstructed near-exactly;
  isolated nodes reduce to ``h_self``.
- **Parameter-tree compatibility.**  Layer/param names mirror
  ``HGCNEncoder``/``HGCNNodeClf`` (``encoder/conv{i}/kernel`` …,
  ``head``), so parameters trained with sampled minibatches evaluate
  with the exact full-graph model (`hgcn.evaluate_nc`) — tested in
  tests/models/test_hgcn_sampled.py.

Mean aggregation only: attention weights over a sampled multiset would
estimate a different (renormalized) operator than the full-graph
segment softmax, so ``use_att=True`` is rejected rather than silently
diverging.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu.models import hgcn
from hyperspace_tpu.nn.gcn import (
    from_tangent0_coords,
    make_manifold,
    tangent0_coords,
)
from hyperspace_tpu.nn.mlr import HypMLR, LorentzMLR


@dataclasses.dataclass(frozen=True)
class SampledConfig:
    base: hgcn.HGCNConfig
    # fanouts[l] = neighbors sampled per node at pyramid level l; length
    # must equal len(base.hidden_dims) (one sampling level per conv)
    fanouts: Sequence[int] = (10, 10)
    batch_size: int = 512

    def __post_init__(self):
        if len(self.fanouts) != len(self.base.hidden_dims):
            raise ValueError(
                f"need one fanout per conv layer: {self.fanouts} vs "
                f"hidden_dims {self.base.hidden_dims}")
        if self.base.use_att:
            raise ValueError(
                "sampled HGCN is mean-aggregation only (a sampled softmax "
                "estimates a different operator than the full-graph one)")


class SampledHGCConv(nn.Module):
    """One conv layer on a dense (self, sampled-neighbors) block.

    Same math as ``nn.gcn.HGCConv`` — tangent-0 matmul, mean
    aggregation, activation, expmap at the (optionally learned) output
    curvature — with identical param names/shapes, so trees transfer."""

    features: int
    kind: str = "lorentz"
    c_in: float = 1.0
    c_out: float = 1.0
    learn_c: bool = False
    use_bias: bool = True
    activation: Any = nn.relu
    dropout_rate: float = 0.0
    kernel_init: Any = nn.initializers.glorot_uniform()

    @nn.compact
    def __call__(self, x_self, x_nbr, n_nbrs, *, deterministic=True):
        # x_self [..., amb]; x_nbr [..., f, amb]; n_nbrs [...] true degree
        m_in = make_manifold(self.kind, self.c_in)
        if self.learn_c:
            init = float(np.log(np.expm1(self.c_out)))
            c_raw = self.param("c_raw", nn.initializers.constant(init), ())
            c_out = nn.softplus(c_raw)
        else:
            c_out = self.c_out
        m_out = make_manifold(self.kind, c_out)

        v_self = tangent0_coords(m_in, x_self)
        v_nbr = tangent0_coords(m_in, x_nbr)
        kernel = self.param("kernel", self.kernel_init,
                            (v_self.shape[-1], self.features), v_self.dtype)
        h_self = v_self @ kernel
        h_nbr = v_nbr @ kernel
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), v_self.dtype)
            h_self = h_self + bias
            h_nbr = h_nbr + bias
        if self.dropout_rate > 0.0:
            # h_self and h_nbr get INDEPENDENT masks, so a node that
            # appears both as itself and as a sampled neighbor (or is
            # drawn multiple times with replacement) sees different masks
            # than the full-graph layer's single per-node dropout: with
            # dropout>0 the sampled step is therefore not an unbiased
            # estimator of the full-graph training operator (standard
            # minibatch-GNN behavior; eval/deterministic paths agree).
            drop = nn.Dropout(self.dropout_rate)
            h_self = drop(h_self, deterministic=deterministic)
            h_nbr = drop(h_nbr, deterministic=deterministic)

        # E[agg] = the full-graph self-loop-inclusive mean (module doc)
        f = x_nbr.shape[-2]
        n = n_nbrs.astype(h_self.dtype)[..., None]
        agg = (h_self + (n / f) * jnp.sum(h_nbr, axis=-2)) / (1.0 + n)
        return from_tangent0_coords(m_out, self.activation(agg)), m_out


class SampledEncoder(nn.Module):
    """Feature lift + stacked SampledHGCConv over the index pyramid."""

    cfg: hgcn.HGCNConfig

    @nn.compact
    def __call__(self, levels, n_nbrs, *, deterministic=True):
        # levels[l]: [B, f1, .., fl, F0] raw features; n_nbrs[l] degrees
        cfg = self.cfg
        m0 = make_manifold(cfg.kind, cfg.c)
        pts = [from_tangent0_coords(m0, x.astype(cfg.dtype)) for x in levels]
        c_prev = cfg.c
        m = m0
        for i, d in enumerate(cfg.hidden_dims):
            is_last = i == len(cfg.hidden_dims) - 1
            conv = SampledHGCConv(
                features=d,
                kind=cfg.kind,
                c_in=c_prev,
                c_out=cfg.c,
                learn_c=cfg.learn_c,
                dropout_rate=cfg.dropout,
                activation=(lambda v: v) if is_last else nn.relu,
                name=f"conv{i}",
            )
            new_pts = []
            for l in range(len(pts) - 1):
                out, m = conv(pts[l], pts[l + 1], n_nbrs[l],
                              deterministic=deterministic)
                new_pts.append(out)
            pts = new_pts  # every call shares the layer's params, so the
            c_prev = m.c   # manifold from the last call is THE layer output
        return pts[0], m


class SampledHGCNNodeClf(nn.Module):
    """Sampled encoder + the same MLR head as ``HGCNNodeClf``."""

    cfg: hgcn.HGCNConfig

    @nn.compact
    def __call__(self, levels, n_nbrs, *, deterministic=True):
        z, m = SampledEncoder(self.cfg, name="encoder")(
            levels, n_nbrs, deterministic=deterministic)
        if self.cfg.kind == "euclidean":
            return nn.Dense(self.cfg.num_classes, name="head")(z)
        head = LorentzMLR if self.cfg.kind == "lorentz" else HypMLR
        return head(self.cfg.num_classes, m, name="head")(z)


class SampledHGCNLinkPred(nn.Module):
    """Sampled encoder + the same Fermi–Dirac decoder as ``HGCNLinkPred``.

    The seed vector is four aligned [P] chunks — (u_pos, v_pos, u_neg,
    v_neg) — so the pyramid encodes all endpoints in one pass; logits
    come from pairwise squared distances within chunks.  Param tree
    matches ``HGCNLinkPred`` (``encoder/...`` + ``decoder/{r, t_raw}``),
    so `hgcn.evaluate_lp` scores sampled-trained params directly."""

    cfg: hgcn.HGCNConfig

    @nn.compact
    def __call__(self, levels, n_nbrs, *, deterministic=True):
        from hyperspace_tpu.nn.decoders import FermiDiracDecoder

        z, m = SampledEncoder(self.cfg, name="encoder")(
            levels, n_nbrs, deterministic=deterministic)
        ddt = self.cfg.resolved_decoder_dtype()
        if ddt is not None and not deterministic:
            z = z.astype(ddt)
        p = z.shape[0] // 4
        sq_pos = m.sqdist(z[:p], z[p : 2 * p])
        sq_neg = m.sqdist(z[2 * p : 3 * p], z[3 * p :])
        dec = FermiDiracDecoder(name="decoder")
        return (dec(sq_pos.astype(self.cfg.dtype)),
                dec(sq_neg.astype(self.cfg.dtype)))


# --- host-side batch planning -------------------------------------------------


def build_adjacency(edges: np.ndarray, num_nodes: int):
    """Undirected CSR (indptr int64 [N+1], indices int32) for the sampler.

    Self-loops are NOT added — the sampled layer handles the self term
    explicitly (module doc), mirroring how ``data.graphs.prepare`` owns
    the self-loop for the full-graph path."""
    e = np.asarray(edges, np.int64)
    e = e[e[:, 0] != e[:, 1]] if len(e) else e.reshape(0, 2)
    both = np.concatenate([e, e[:, ::-1]]) if len(e) else e
    # dedupe like graphs.prepare does: duplicate rows or both orientations
    # in the input must not inflate degrees, or the sampled estimator
    # targets a different operator than the full-graph eval model
    key = both[:, 0] * num_nodes + both[:, 1] if len(both) else both[:, :0]
    s = both[np.unique(key, return_index=True)[1]] if len(both) else \
        np.zeros((0, 2), np.int64)
    indptr = np.searchsorted(s[:, 0], np.arange(num_nodes + 1)).astype(np.int64)
    return indptr, s[:, 1].astype(np.int32)


def _sample(indptr, indices, seeds, fanout, seed):
    try:
        from hyperspace_tpu.data import native

        return native.sample_neighbors(indptr, indices, seeds, fanout, seed)
    except (ImportError, OSError):
        from hyperspace_tpu.data.native import sample_neighbors_numpy

        return sample_neighbors_numpy(indptr, indices, seeds, fanout, seed)


class SampledBatches(NamedTuple):
    """S planned minibatches, device-resident (one pyramid per step)."""

    ids: tuple      # level l: [S, B, f1, .., fl] int32
    # [S, B] int32 seed labels (NC); None for LP batches, where
    # positives/negatives are positional in the seed chunks
    labels: Any


def _mix64(x: int) -> int:
    """Host-side splitmix64 finalizer (one round) over a python int."""
    m = (1 << 64) - 1
    x = (x + 0x9E3779B97F4A7C15) & m
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & m
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & m
    return x ^ (x >> 31)


def _build_pyramid(cfg: SampledConfig, indptr, indices, seeds, seed: int):
    """Fanout levels over per-step seed rows ([S, B] → [S, B, f1], ...).

    The ONE sampler-driving loop both planners share (same per-level
    seed derivation — NC and LP pyramids must never diverge).  ONE
    native-sampler call per level over all steps' seeds flattened — the
    per-(step, level) python loop was the planner's bottleneck, and the
    overlap pipeline (:class:`SampledBatchStream`) needs planning far
    cheaper than the device step.  The per-call seed is splitmix64-
    hashed first: the sampler computes ``splitmix64(seed ^ cell)``, so
    raw small-integer call seeds would correlate calls' RNG streams
    (ADVICE r3); within a call every (step, row, draw) is a distinct
    cell, so one call per level is at least as decorrelated as the old
    per-step calls."""
    levels = [seeds]
    for li, f in enumerate(cfg.fanouts):
        prev = levels[-1]
        nxt = _sample(indptr, indices, prev.ravel(), f,
                      seed=_mix64(seed * 1_000_003 + li))
        levels.append(nxt.reshape(prev.shape + (f,)))
    return levels


def _plan_nc_chunk(cfg: SampledConfig, indptr, indices, train_nodes,
                   labels, steps: int, chunk_seed: int):
    """Numpy core of one NC chunk: (levels, labels) for ``steps`` steps."""
    rng = np.random.default_rng(chunk_seed)
    seeds = rng.choice(train_nodes,
                       size=(steps, cfg.batch_size)).astype(np.int32)
    levels = _build_pyramid(cfg, indptr, indices, seeds, chunk_seed)
    return levels, np.asarray(labels, np.int32)[seeds]


def _plan_lp_chunk(cfg: SampledConfig, indptr, indices, train_pos,
                   num_nodes: int, steps: int, chunk_seed: int):
    """Numpy core of one LP chunk: (levels, None)."""
    rng = np.random.default_rng(chunk_seed)
    p = cfg.batch_size
    rows = rng.integers(0, len(train_pos), (steps, p))
    pos = train_pos[rows]                                    # [S, P, 2]
    neg = rng.integers(0, num_nodes, (steps, p, 2))
    seeds = np.concatenate(
        [pos[..., 0], pos[..., 1], neg[..., 0], neg[..., 1]],
        axis=1).astype(np.int32)                             # [S, 4P]
    return _build_pyramid(cfg, indptr, indices, seeds, chunk_seed), None


def plan_batches(cfg: SampledConfig, edges: np.ndarray, labels: np.ndarray,
                 train_mask: np.ndarray, num_nodes: int, steps: int,
                 seed: int = 0) -> tuple[SampledBatches, jax.Array]:
    """Draw ``steps`` seed batches + their fanout pyramids on the host.

    Returns the device-resident batches and the ``[N]`` true-degree
    array the steps gather their estimator weights from."""
    indptr, indices = build_adjacency(edges, num_nodes)
    train_nodes = np.flatnonzero(np.asarray(train_mask))
    levels, lab = _plan_nc_chunk(cfg, indptr, indices, train_nodes, labels,
                                 steps, seed)
    deg = (indptr[1:] - indptr[:-1]).astype(np.float32)
    return (SampledBatches(tuple(jnp.asarray(l) for l in levels),
                           jnp.asarray(lab)),
            jnp.asarray(deg))


def plan_lp_batches(cfg: SampledConfig, train_pos: np.ndarray,
                    num_nodes: int, steps: int,
                    seed: int = 0) -> tuple[SampledBatches, jax.Array]:
    """LP pyramids: per step, ``batch_size`` positive pairs drawn from
    ``train_pos`` and as many uniform-random negative pairs; the seed
    vector is the four aligned endpoint chunks (u⁺, v⁺, u⁻, v⁻).
    ``labels`` is None — positives/negatives are positional.

    Message passing samples over the TRAIN edges only (``train_pos`` is
    both the supervision set and the adjacency), matching the full-graph
    LP protocol where ``split_edges`` builds the encoder graph from
    train edges — held-out val/test edges must never leak into the
    neighborhood aggregation."""
    indptr, indices = build_adjacency(np.asarray(train_pos), num_nodes)
    levels, _ = _plan_lp_chunk(cfg, indptr, indices, np.asarray(train_pos),
                               num_nodes, steps, seed)
    deg = (indptr[1:] - indptr[:-1]).astype(np.float32)
    return (SampledBatches(tuple(jnp.asarray(l) for l in levels), None),
            jnp.asarray(deg))


class SampledBatchStream:
    """Background-planned, double-buffered minibatch pyramids.

    VERDICT r3 #5: the r03 trainer pre-planned ``plan_steps`` pyramids
    once and recycled them modulo on long runs.  This stream plans a
    FRESH chunk of ``chunk_steps`` pyramids in a background thread while
    the device trains on the current one, transfers it (``device_put``
    happens in the worker, so the host→device copy overlaps training
    too) and hands it over through a bounded queue (``depth`` chunks of
    look-ahead; the put blocks when full, bounding host memory).  Every
    chunk uses a splitmix64-derived seed, so a run of any length never
    sees a repeated batch.  ``plan_steps`` keeps its r03 meaning as the
    device-resident footprint cap — it is now the chunk size, not the
    total variety.

    The planner cores are the SAME functions the one-shot planners use
    (`_plan_nc_chunk` / `_plan_lp_chunk`); only the per-chunk seed
    derivation differs (splitmix64 of (seed, chunk index)).  The
    thread/queue machinery itself is the generic
    :class:`hyperspace_tpu.data.prefetch.HostPrefetcher` (this stream is
    the pipeline it was factored out of); this class owns only the
    planning and the chunk-seed sequence.
    """

    def __init__(self, cfg: SampledConfig, task: str, *, num_nodes: int,
                 edges=None, labels=None, train_mask=None, train_pos=None,
                 chunk_steps: int = 64, depth: int = 2, seed: int = 0,
                 start_chunk: int = 0):
        from hyperspace_tpu.data.prefetch import HostPrefetcher

        self.cfg = cfg
        self.task = task
        self.chunk_steps = int(chunk_steps)
        self._seed = int(seed)
        self._num_nodes = int(num_nodes)
        if task == "nc":
            self._indptr, self._indices = build_adjacency(edges, num_nodes)
            self._train_nodes = np.flatnonzero(np.asarray(train_mask))
            self._labels = np.asarray(labels, np.int32)
        elif task == "lp":
            self._train_pos = np.asarray(train_pos)
            self._indptr, self._indices = build_adjacency(self._train_pos,
                                                          num_nodes)
        else:
            raise ValueError(f"unknown task {task!r}")
        self.deg = jnp.asarray(
            (self._indptr[1:] - self._indptr[:-1]).astype(np.float32))
        # resume support (ADVICE r04): a run restored at step R passes
        # start_chunk = ceil(R / chunk_steps) — see train/loop.resume_chunk
        # (NOT floor: floor would re-serve the partially-consumed boundary
        # chunk's first R%cs rows, the batch-replay bug) — so the chunk
        # sequence CONTINUES instead of replaying consumed chunks; the
        # "never a repeated batch" guarantee holds across restarts
        self._prefetch = HostPrefetcher(self._make_chunk, depth=depth,
                                        start=int(start_chunk))

    def _plan(self, chunk: int):
        cs = _mix64((self._seed << 20) ^ chunk)
        if self.task == "nc":
            return _plan_nc_chunk(self.cfg, self._indptr, self._indices,
                                  self._train_nodes, self._labels,
                                  self.chunk_steps, cs)
        return _plan_lp_chunk(self.cfg, self._indptr, self._indices,
                              self._train_pos, self._num_nodes,
                              self.chunk_steps, cs)

    def _make_chunk(self, chunk: int) -> SampledBatches:
        # device_put in the prefetch worker: the host→device copy of
        # chunk i+1 overlaps the device's training on chunk i
        levels, lab = self._plan(chunk)
        return SampledBatches(
            tuple(jax.device_put(l) for l in levels),
            None if lab is None else jax.device_put(lab))

    def next(self) -> SampledBatches:
        """Block until the next fresh chunk of pyramids is ready.

        Re-raises any exception the planner thread hit (the run fails
        with the real traceback instead of hanging on an empty queue).
        """
        return self._prefetch.next()

    def close(self):
        self._prefetch.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --- training ----------------------------------------------------------------


def init_sampled_nc(cfg: SampledConfig, feat_dim: int, seed: int = 0):
    """Model + optimizer + TrainState (same tree as ``hgcn.init_nc``)."""
    model = SampledHGCNNodeClf(cfg.base)
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    b = cfg.batch_size
    dummy_levels, shape = [], (b,)
    for f in (None,) + tuple(cfg.fanouts):
        if f is not None:
            shape = shape + (f,)
        dummy_levels.append(jnp.zeros(shape + (feat_dim,), jnp.float32))
    dummy_nn = [jnp.ones(l.shape[:-1], jnp.float32)
                for l in dummy_levels[:-1]]
    params = model.init(k_init, dummy_levels, dummy_nn)["params"]
    opt = hgcn.make_optimizer(cfg.base)
    return model, opt, hgcn.TrainState(params, opt.init(params), key,
                                       jnp.zeros((), jnp.int32))


def init_sampled_lp(cfg: SampledConfig, feat_dim: int, seed: int = 0):
    """LP model + optimizer + TrainState (same tree as ``hgcn.init_lp``)."""
    model = SampledHGCNLinkPred(cfg.base)
    key = jax.random.PRNGKey(seed)
    k_init, key = jax.random.split(key)
    dummy_levels, shape = [], (4 * cfg.batch_size,)
    for f in (None,) + tuple(cfg.fanouts):
        if f is not None:
            shape = shape + (f,)
        dummy_levels.append(jnp.zeros(shape + (feat_dim,), jnp.float32))
    dummy_nn = [jnp.ones(l.shape[:-1], jnp.float32)
                for l in dummy_levels[:-1]]
    params = model.init(k_init, dummy_levels, dummy_nn)["params"]
    opt = hgcn.make_optimizer(cfg.base)
    return model, opt, hgcn.TrainState(params, opt.init(params), key,
                                       jnp.zeros((), jnp.int32))


def _lp_row_step(model, opt, state, x_table, deg, ids, constrain=None):
    """One LP minibatch step on a single pyramid row (un-jitted body)."""
    if constrain is not None:
        ids = [constrain(a) for a in ids]
    levels = [x_table[a] for a in ids]
    n_nbrs = [deg[a] for a in ids[:-1]]
    key, k_drop = jax.random.split(state.key)

    def loss_fn(params):
        pos_logit, neg_logit = model.apply(
            {"params": params}, levels, n_nbrs,
            deterministic=False, rngs={"dropout": k_drop})
        bce_pos = optax.sigmoid_binary_cross_entropy(
            pos_logit, jnp.ones_like(pos_logit))
        bce_neg = optax.sigmoid_binary_cross_entropy(
            neg_logit, jnp.zeros_like(neg_logit))
        return ((jnp.sum(bce_pos) + jnp.sum(bce_neg))
                / (pos_logit.shape[0] + neg_logit.shape[0]))

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return hgcn.TrainState(params, opt_state, key, state.step + 1), loss


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step_sampled_lp(
    model: SampledHGCNLinkPred,
    opt,
    state: hgcn.TrainState,
    x_table: jax.Array,
    deg: jax.Array,
    batches: SampledBatches,
):
    """One sampled LP step; consumes pyramid ``state.step % S``.

    Supervises ``batch_size`` positive pairs (+ as many negatives)."""
    return _sampled_lp_impl(model, opt, state, x_table, deg, batches)


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_epoch_sampled_lp(
    model: SampledHGCNLinkPred,
    opt,
    state: hgcn.TrainState,
    x_table: jax.Array,
    deg: jax.Array,
    batches: SampledBatches,
):
    """All S planned LP minibatches as one `lax.scan` program."""

    def body(st, ids):
        return _lp_row_step(model, opt, st, x_table, deg, list(ids))

    return jax.lax.scan(body, state, tuple(batches.ids))


def _row_step(model, opt, state, x_table, deg, ids, labels, constrain=None):
    """One minibatch step on a single pyramid row (un-jitted body)."""
    if constrain is not None:  # GSPMD hint: shard the batch axis
        ids = [constrain(a) for a in ids]
        labels = constrain(labels)
    levels = [x_table[a] for a in ids]
    n_nbrs = [deg[a] for a in ids[:-1]]
    key, k_drop = jax.random.split(state.key)

    def loss_fn(params):
        logits = model.apply({"params": params}, levels, n_nbrs,
                             deterministic=False, rngs={"dropout": k_drop})
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return hgcn.TrainState(params, opt_state, key, state.step + 1), loss


def _take_row(state, batches: SampledBatches):
    """Row ``state.step % S`` of the plan (the one modulo-indexed
    selection both the NC and LP steps use)."""
    s = batches.ids[0].shape[0]
    i = state.step % s
    take = lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
    labels = None if batches.labels is None else take(batches.labels)
    return [take(a) for a in batches.ids], labels


def _sampled_impl(model, opt, state, x_table, deg, batches, constrain=None):
    ids, labels = _take_row(state, batches)
    return _row_step(model, opt, state, x_table, deg, ids, labels, constrain)


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step_sampled_nc(
    model: SampledHGCNNodeClf,
    opt,
    state: hgcn.TrainState,
    x_table: jax.Array,   # [N, F0] raw features, device-resident
    deg: jax.Array,       # [N] true degrees
    batches: SampledBatches,
):
    """One minibatch step; consumes pyramid ``state.step % S``.

    Supervises exactly ``batch_size`` seed nodes — the honest
    "samples/step" unit of the sampled trainer."""
    return _sampled_impl(model, opt, state, x_table, deg, batches)


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_epoch_sampled_nc(
    model: SampledHGCNNodeClf,
    opt,
    state: hgcn.TrainState,
    x_table: jax.Array,
    deg: jax.Array,
    batches: SampledBatches,
):
    """All S planned minibatches as ONE XLA program (`lax.scan` over the
    pyramid rows, front to back — identical trajectory to S calls of
    :func:`train_step_sampled_nc` from ``state.step % S == 0``).  The
    per-step device work is a handful of small dense ops, so the scan's
    dispatch amortization is worth ~the same factor it buys the Poincaré
    workload (docs/benchmarks.md r03b)."""

    def body(st, row):
        ids, labels = row
        return _row_step(model, opt, st, x_table, deg, list(ids), labels)

    return jax.lax.scan(body, state, (tuple(batches.ids), batches.labels))


def _sampled_lp_impl(model, opt, state, x_table, deg, batches,
                     constrain=None):
    ids, _ = _take_row(state, batches)
    return _lp_row_step(model, opt, state, x_table, deg, ids, constrain)


def _make_sharded(impl, model, opt, mesh, state: hgcn.TrainState,
                  x_table, deg, batches: SampledBatches):
    """Shared DP builder: the pyramid's batch axis shards across the
    data-like axes (XLA inserts the gradient all-reduce — SURVEY.md §2
    N8); features/degrees/plan are placed replicated once.  Returns
    ``(step, placed_state, placed_data)``; call as ``state, loss =
    step(state, *placed_data)``.  The pyramid's leading batch axis (B
    for NC, 4·batch_size for LP) must divide by the mesh's data extent."""
    from hyperspace_tpu.parallel.mesh import (
        data_extent,
        replicated,
        shard_batch,
    )
    from hyperspace_tpu.parallel.tp import state_shardings

    d = data_extent(mesh)
    if batches.ids[0].shape[1] % d:
        raise ValueError(
            f"pyramid batch axis {batches.ids[0].shape[1]} not divisible "
            f"by the mesh's data extent {d}")
    state_sh = state_shardings(state, state.params, mesh)
    repl = replicated(mesh)
    step = jax.jit(
        partial(impl, model, opt, constrain=partial(shard_batch, mesh=mesh)),
        in_shardings=(state_sh, repl, repl, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )
    data = (jax.device_put(x_table, repl), jax.device_put(deg, repl),
            jax.tree_util.tree_map(lambda a: jax.device_put(a, repl),
                                   batches))
    return step, jax.device_put(state, state_sh), data


def make_sharded_step(model, opt, mesh, state: hgcn.TrainState,
                      x_table, deg, batches: SampledBatches):
    """Data-parallel sampled NC step over ``mesh`` (see _make_sharded)."""
    return _make_sharded(_sampled_impl, model, opt, mesh, state, x_table,
                         deg, batches)


def make_sharded_lp_step(model, opt, mesh, state: hgcn.TrainState,
                         x_table, deg, batches: SampledBatches):
    """Data-parallel sampled LP step over ``mesh`` (see _make_sharded)."""
    return _make_sharded(_sampled_lp_impl, model, opt, mesh, state,
                         x_table, deg, batches)
