"""Hyperbolic VAE on MNIST (reference workload 4).

BASELINE.json configs[3]: "Hyperbolic VAE on MNIST — wrapped-normal prior";
semantics per Mathieu et al. 2019 / Nagano et al. 2019 (SURVEY.md §2
"HVAE model", §3.3 call stack):

    encoder (Euclidean conv) ─► (μ ∈ manifold via exp₀, σ)
    posterior  q(z|x) = WrappedNormal(μ, σ)   — reparameterized rsample
    prior      p(z)   = WrappedNormal(origin, 1)
    decoder    log₀(z) ─► deconv ─► Bernoulli logits
    ELBO       E_q[log p(x|z)] − MC-KL,  KL ≈ log q(z|x) − log p(z)

Monte-Carlo KL (no closed form on the manifold) with the reparameterized
sample keeps the whole step differentiable; eval offers the K-sample IWAE
bound (SURVEY.md §3.5).  Works on the ball or the hyperboloid — the
latent geometry is a config choice, both [B] requirements.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from hyperspace_tpu import precision as precision_mod
from hyperspace_tpu.nn.gcn import make_manifold
from hyperspace_tpu.nn.wrapped_normal import WrappedNormal


@dataclasses.dataclass(frozen=True)
class HVAEConfig:
    image_size: int = 28
    latent_dim: int = 2  # manifold dim of the latent space
    hidden: int = 256
    conv_features: tuple = (32, 64)
    kind: str = "poincare"  # or "lorentz"
    c: float = 1.0
    lr: float = 1e-3
    batch_size: int = 128
    kl_weight: float = 1.0
    dtype: Any = jnp.float32
    # mixed-precision policy (hyperspace_tpu/precision.py): "bf16" runs
    # the Euclidean conv/dense stacks — the model's entire MXU mass — in
    # bf16 while params, the manifold latent (expmap0/logmap0, the
    # wrapped-normal densities) and the loss reductions stay f32.
    # "f32" (default) is bit-identical to the pre-policy model.
    precision: str = "f32"


class Encoder(nn.Module):
    cfg: HVAEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> WrappedNormal:
        cfg = self.cfg
        pol = precision_mod.get_policy(cfg.precision)
        cdt = pol.module_dtype()  # compute dtype when mixed, else None
        m = make_manifold(cfg.kind, cfg.c)
        h = pol.cast_compute(x[..., None])  # [B, H, W, 1]
        for f in cfg.conv_features:
            h = nn.relu(nn.Conv(f, (3, 3), strides=(2, 2), dtype=cdt)(h))
        h = h.reshape(h.shape[0], -1)
        h = nn.relu(nn.Dense(cfg.hidden, dtype=cdt)(h))
        # μ as origin-tangent coords → tangent chart → expmap0 — the
        # manifold side of the boundary: back to f32 BEFORE expmap0
        mu_t = pol.cast_boundary(nn.Dense(cfg.latent_dim, name="mu",
                                          dtype=cdt)(h))
        mu = m.expmap0(m.tangent_from_origin_coords(mu_t))
        log_sigma = pol.cast_boundary(
            nn.Dense(cfg.latent_dim, name="log_sigma", dtype=cdt)(h))
        sigma = jnp.exp(jnp.clip(log_sigma, -6.0, 2.0))
        return WrappedNormal(m, mu, sigma)


class Decoder(nn.Module):
    cfg: HVAEConfig

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.cfg
        pol = precision_mod.get_policy(cfg.precision)
        cdt = pol.module_dtype()
        m = make_manifold(cfg.kind, cfg.c)
        # leave the manifold once, at the decoder input (logmap0 in f32);
        # the Euclidean stack below runs in the compute dtype
        v = pol.cast_compute(m.origin_coords_from_tangent(m.logmap0(z)))
        s0 = cfg.image_size // (2 ** len(cfg.conv_features))
        f_top = cfg.conv_features[-1]
        h = nn.relu(nn.Dense(cfg.hidden, dtype=cdt)(v))
        h = nn.relu(nn.Dense(s0 * s0 * f_top, dtype=cdt)(h))
        h = h.reshape(h.shape[:-1] + (s0, s0, f_top))
        for f in reversed(cfg.conv_features[:-1]):
            h = nn.relu(nn.ConvTranspose(f, (3, 3), strides=(2, 2),
                                         dtype=cdt)(h))
        h = nn.ConvTranspose(1, (3, 3), strides=(2, 2), dtype=cdt)(h)
        h = h[..., 0]
        # crop in case strides overshoot the odd image size; logits leave
        # in the accumulation dtype — the BCE/ELBO sums never run in bf16
        return pol.cast_accum(h[..., : cfg.image_size, : cfg.image_size])


class HVAE(nn.Module):
    cfg: HVAEConfig

    def setup(self):
        self.encoder = Encoder(self.cfg)
        self.decoder = Decoder(self.cfg)

    def __call__(self, x: jax.Array, key: jax.Array):
        q = self.encoder(x)
        z = q.rsample(key)
        logits = self.decoder(z)
        return q, z, logits

    def prior(self, dtype=jnp.float32) -> WrappedNormal:
        cfg = self.cfg
        m = make_manifold(cfg.kind, cfg.c)
        loc = m.origin((m.ambient_dim(cfg.latent_dim),), dtype)
        return WrappedNormal(m, loc, jnp.ones((cfg.latent_dim,), dtype))


def elbo_terms(model_out, prior: WrappedNormal, x: jax.Array):
    q, z, logits = model_out
    recon = -jnp.sum(
        optax.sigmoid_binary_cross_entropy(logits, x), axis=(-2, -1))
    kl = q.log_prob(z) - prior.log_prob(z)
    return recon, kl


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    key: jax.Array
    step: jax.Array


def init_model(cfg: HVAEConfig, seed: int = 0):
    model = HVAE(cfg)
    key = jax.random.PRNGKey(seed)
    k_init, k_s, key = jax.random.split(key, 3)
    dummy = jnp.zeros((2, cfg.image_size, cfg.image_size), cfg.dtype)
    params = model.init({"params": k_init}, dummy, k_s)["params"]
    opt = optax.adam(cfg.lr)
    return model, opt, TrainState(params, opt.init(params), key, jnp.zeros((), jnp.int32))


def _step_impl(model, opt, state, x, constrain=None):
    """Shared step body; ``constrain`` pins the batch's sharding (the
    only difference between the single-device and mesh-sharded steps)."""
    key, k_sample = jax.random.split(state.key)
    prior = model.prior(x.dtype)
    if constrain is not None:
        x = constrain(x)

    def loss_fn(params):
        out = model.apply({"params": params}, x, k_sample)
        recon, kl = elbo_terms(out, prior, x)
        elbo = recon - model.cfg.kl_weight * kl
        return -jnp.mean(elbo), (jnp.mean(recon), jnp.mean(kl))

    (loss, (recon, kl)), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
    updates, opt_state = opt.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, key, state.step + 1), loss, recon, kl


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step(model: HVAE, opt, state: TrainState, x: jax.Array):
    return _step_impl(model, opt, state, x)


@partial(jax.jit, static_argnames=("model", "k"))
def iwae_bound(model: HVAE, params, x: jax.Array, key: jax.Array, k: int = 16):
    """K-sample importance-weighted bound (SURVEY.md §3.5 HVAE eval)."""
    prior = model.prior(x.dtype)

    def one(key):
        out = model.apply({"params": params}, x, key)
        recon, kl = elbo_terms(out, prior, x)
        return recon - kl  # log w (unnormalized)

    logw = jax.vmap(one)(jax.random.split(key, k))  # [K, B]
    return jnp.mean(jax.nn.logsumexp(logw, axis=0) - jnp.log(float(k)))


def _sampled_impl(model, opt, state, x_all, constrain=None):
    key, k_next = jax.random.split(state.key)
    idx = jax.random.randint(k_next, (model.cfg.batch_size,), 0, x_all.shape[0])
    return _step_impl(model, opt, state._replace(key=key), x_all[idx],
                      constrain)


@partial(jax.jit, static_argnames=("model", "opt"), donate_argnames=("state",))
def train_step_sampled(model: HVAE, opt, state: TrainState, x_all: jax.Array):
    """Like :func:`train_step` but samples the minibatch on device from
    ``state.key`` — the data-iterator state is then exactly the PRNG key
    inside the (checkpointed) TrainState, and the step remains one XLA
    program with no host-side indexing (SURVEY.md §5 "Checkpoint /
    resume": data-iterator state)."""
    return _sampled_impl(model, opt, state, x_all)


def make_sharded_step(model: HVAE, opt, mesh, state: TrainState, x_all):
    """Data-parallel sampled train step over ``mesh``: the on-device
    minibatch shards over the data-like axes (XLA inserts the gradient
    all-reduce over ICI/DCN — SURVEY.md §2 N8), the dataset array is
    placed replicated ONCE (re-broadcasting it per step would swamp the
    step).  Returns ``(step, placed_state, placed_x)``; call as
    ``state, loss, recon, kl = step(state, x_all)``."""
    from hyperspace_tpu.parallel.mesh import data_extent, replicated, shard_batch
    from hyperspace_tpu.parallel.tp import state_shardings

    d = data_extent(mesh)
    if model.cfg.batch_size % d:
        raise ValueError(
            f"batch_size={model.cfg.batch_size} not divisible by the "
            f"mesh's data extent {d}")
    state_sh = state_shardings(state, state.params, mesh)
    repl = replicated(mesh)
    step = jax.jit(
        partial(_sampled_impl, model, opt,
                constrain=partial(shard_batch, mesh=mesh)),
        in_shardings=(state_sh, repl),
        out_shardings=(state_sh, repl, repl, repl),
        donate_argnums=(0,),
    )
    return step, jax.device_put(state, state_sh), jax.device_put(x_all, repl)


def train(cfg: HVAEConfig, images: np.ndarray, steps: int = 200, seed: int = 0):
    """Minibatch loop; returns (model, state, last-metrics)."""
    model, opt, state = init_model(cfg, seed)
    x_all = jnp.asarray(images, cfg.dtype)
    metrics = {}
    for _ in range(steps):
        state, loss, recon, kl = train_step_sampled(model, opt, state, x_all)
        metrics = {"loss": float(loss), "recon": float(recon), "kl": float(kl)}
    return model, state, metrics
