"""Shared loopback multi-process worker: one process of an N-process
``jax.distributed`` group over 127.0.0.1, 2 virtual CPU devices each.

One worker, three consumers (so the pod story is drilled by ONE code
path, not three diverging copies):

- ``tests/parallel/test_multihost_smoke.py`` — the tier-1 FAST smoke
  (``--task pipeline`` at tiny sizes): group forms, the per-host data
  plane assembles a global batch from host-local shards, the
  per-host-owned table checkpoint commits behind the coordination
  barrier, and the process-0-gated export yields ONE artifact.
- ``scripts/check_multihost.py`` — the same pipeline plus the
  single-process half: restore-at-1-process, fingerprint cross-check,
  serve-query smoke.
- ``bench.py bench_multihost`` — ``--task bench``: timed chunked HGCN
  steps at 1 vs 2 processes for the scaling row.

What the CPU loopback can and cannot drill (jax 0.4.37's CPU backend
refuses cross-process device computations — "Multiprocess computations
aren't implemented"): the process group, the coordination-service
barriers, ``host_local_array_to_global_array`` assembly, and all
filesystem commit protocols are REAL across processes; the training
step itself runs on each process's LOCAL device mesh — the degenerate
data-parallel case where every replica sees the same batch and the
gradient all-reduce is the identity.  Determinism then pins the rest:
every process must produce bit-identical params/tables (checked by
digest exchange through the shared workdir behind a barrier), which is
exactly the invariant the cross-host all-reduce preserves on a real
pod.  On TPU the same code paths run with the collectives live.

Process 0 prints one ``RESULT {json}`` line; non-0 processes exit 0
silently (or non-0 on a cross-process consistency failure).  Runnable
by hand:

    python -m hyperspace_tpu.benchmarks.mh_worker --pid 0 --nprocs 2 \
        --port 9731 --workdir /tmp/mh --task pipeline &
    python -m hyperspace_tpu.benchmarks.mh_worker --pid 1 --nprocs 2 \
        --port 9731 --workdir /tmp/mh --task pipeline
"""

import argparse
import hashlib
import json
import os
import sys
import time


def _local_mesh():
    """Mesh over THIS process's devices only (the CPU loopback cannot
    run cross-process device programs; on a pod the trainers use
    ``multihost_mesh`` instead)."""
    import jax

    from hyperspace_tpu.parallel.mesh import make_mesh

    return make_mesh({"data": -1}, devices=jax.local_devices())


def _build_hgcn(nodes: int, feat: int, mesh, chunk: int):
    """(step_callable, state, num_pairs): the production trainer path —
    node-sharded HGCN LP (what ``cli/train.py`` runs on a mesh) with the
    supervision batch entering batch-sharded, as the data plane feeds
    it."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn
    from hyperspace_tpu.parallel.mesh import batch_sharding
    from hyperspace_tpu.train import loop as train_loop

    edges, x, labels, ncls = G.synthetic_hierarchy(
        num_nodes=nodes, feat_dim=feat, seed=0)
    split = G.split_edges(edges, nodes, x, seed=0, pad_multiple=128)
    cfg = hgcn.HGCNConfig(feat_dim=feat, hidden_dims=(16, 8))
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    pairs_host = hgcn.round_up_pairs(split.train_pos, mesh)
    train_pos = jax.device_put(jnp.asarray(pairs_host),
                               batch_sharding(mesh, ndim=2))
    step, state, nsg = hgcn.make_node_sharded_step_lp(
        model, opt, split.graph.num_nodes, mesh, state, split)
    fn = lambda st: step(st, nsg, train_pos)
    if chunk > 1:
        fn = train_loop.make_chunked_stepper(fn, chunk)
    return fn, state, pairs_host.shape[0]


def _check_data_plane(args, mh) -> dict:
    """The per-host data plane, REAL across processes: assemble a global
    batch over the host×data mesh from only this host's rows and verify
    this process's addressable shards hold exactly its owned slice."""
    import numpy as np

    from hyperspace_tpu.parallel.mesh import data_extent, multihost_mesh

    mesh = multihost_mesh({"data": 2})
    rows = 4 * data_extent(mesh)
    batch = np.arange(rows * 3, dtype=np.float32).reshape(rows, 3)
    g = mh.distribute_batch(batch, mesh)
    if tuple(g.shape) != (rows, 3):
        raise AssertionError(f"global batch shape {g.shape} != {(rows, 3)}")
    for s in g.addressable_shards:
        start = s.index[0].start or 0
        want = batch[start:start + s.data.shape[0]]
        if not np.array_equal(np.asarray(s.data), want):
            raise AssertionError(
                f"pid {args.pid}: shard at row {start} does not hold the "
                "host-local slice it owns")
    lo, hi = mh.local_batch_rows(np.arange(rows))[[0, -1]]
    return {"batch_rows": rows,
            "local_rows": [int(lo), int(hi) + 1],
            "local_shards": len(g.addressable_shards)}


def run_pipeline(args, mh) -> int:
    """Train (deterministic replicas) → per-host-owned checkpoint →
    process-0-gated export.  The single-process halves (elastic restore,
    serve query) live in scripts/check_multihost.py."""
    import jax
    import numpy as np

    from hyperspace_tpu.parallel import host_table as HT
    from hyperspace_tpu.serve.artifact import export_artifact, fingerprint_of

    plane = _check_data_plane(args, mh)

    fn, state, npairs = _build_hgcn(args.nodes, args.feat,
                                    _local_mesh(), chunk=1)
    losses = []
    for _ in range(args.steps):
        state, loss = fn(state)
        losses.append(float(jax.device_get(loss)))
    leaf = mh.fetch_replicated(jax.tree_util.tree_leaves(state.params)[0])
    params_sha = hashlib.sha256(
        np.ascontiguousarray(leaf).tobytes()).hexdigest()

    # a deterministic Poincaré table, trained a few steps for real —
    # host-identical by construction (the replicated-table DP contract)
    from hyperspace_tpu.data.wordnet import synthetic_tree
    from hyperspace_tpu.models import poincare_embed as pe

    ds = synthetic_tree(depth=4, branching=3)
    cfg = pe.PoincareEmbedConfig(num_nodes=ds.num_nodes, dim=8,
                                 batch_size=64, neg_samples=4,
                                 burnin_steps=0)
    pstate, popt = pe.init_state(cfg, seed=0)
    pstep = pe.make_train_step(cfg)
    import jax.numpy as jnp

    ppairs = jnp.asarray(ds.pairs)
    for _ in range(args.steps):
        pstate, _ = pstep(cfg, popt, pstate, ppairs)
    table = np.asarray(jax.device_get(pstate.table), np.float32)
    table_sha = hashlib.sha256(table.tobytes()).hexdigest()

    # the DP invariant, checked host-side: every replica bit-identical.
    # (assert_equal_across_hosts rides a device collective the CPU
    # loopback lacks; digests cross the shared filesystem instead.)
    digest = {"params_sha": params_sha, "table_sha": table_sha,
              "losses": losses}
    with open(os.path.join(args.workdir, f"digest.{args.pid}.json"),
              "w") as f:
        json.dump(digest, f)
    mh.sync("digests")
    if args.pid == 0:
        for p in range(1, args.nprocs):
            with open(os.path.join(args.workdir,
                                   f"digest.{p}.json")) as f:
                other = json.load(f)
            if other != digest:
                print(f"CONSISTENCY MISMATCH pid0 vs pid{p}: "
                      f"{digest} != {other}", flush=True)
                return 1

    # per-host-owned checkpoint: THIS process writes only its row range;
    # process 0 commits the manifest behind the barrier
    ckpt_dir = os.path.join(args.workdir, "host_table")
    master = HT.HostEmbedTable.from_array(table)
    HT.save_owned_rows(master, ckpt_dir,
                       barrier=lambda: mh.sync("host_table"))

    # process-0-gated export: every process calls, ONE artifact lands;
    # non-0 processes get the committed artifact back and must agree
    export_dir = os.path.join(args.workdir, "artifact")
    spec = ("poincare", float(cfg.c))
    art = export_artifact(export_dir, table, spec,
                          model_config={"dim": cfg.dim}, overwrite=True)
    want = fingerprint_of(table, spec)
    if art.fingerprint != want:
        print(f"FINGERPRINT MISMATCH pid={args.pid}: "
              f"{art.fingerprint} != {want}", flush=True)
        return 1

    if args.pid == 0:
        lo, hi = mh.process_row_range(master.num_rows)
        print("RESULT " + json.dumps({
            "losses": losses, "devices": jax.local_device_count(),
            "processes": jax.process_count(),
            "pairs": int(npairs), "num_rows": int(master.num_rows),
            "owned_rows_p0": [int(lo), int(hi)], "data_plane": plane,
            "fingerprint": art.fingerprint,
            "params_sha": params_sha, "table_sha": table_sha,
            "ckpt_dir": ckpt_dir, "export_dir": export_dir,
        }), flush=True)
    return 0


def run_bench(args, mh) -> int:
    """Timed chunked HGCN LP steps for the scaling row: warmup one
    chunk (compile), then time ``--steps`` steps in ``--chunk``-step
    dispatches.  Every process times its own replica and drops a
    timing file; process 0 aggregates behind the barrier, so the
    reported throughput is the fleet's, not one host's."""
    import jax

    fn, state, npairs = _build_hgcn(args.nodes, args.feat,
                                    _local_mesh(), chunk=args.chunk)
    state, loss = fn(state)  # warmup: compile + first chunk
    jax.block_until_ready(loss)
    nchunks = max(1, args.steps // max(args.chunk, 1))
    losses = []
    t0 = time.perf_counter()
    for _ in range(nchunks):
        state, loss = fn(state)
        lv = loss[-1] if getattr(loss, "ndim", 0) else loss
        losses.append(float(jax.device_get(lv)))  # per-chunk sync point
    elapsed = time.perf_counter() - t0
    steps = nchunks * max(args.chunk, 1)
    timing = {"elapsed_s": elapsed, "losses": losses}
    with open(os.path.join(args.workdir, f"timing.{args.pid}.json"),
              "w") as f:
        json.dump(timing, f)
    mh.sync("timings")
    if args.pid == 0:
        per_proc = [timing] + [
            json.load(open(os.path.join(args.workdir,
                                        f"timing.{p}.json")))
            for p in range(1, args.nprocs)]
        slowest = max(t["elapsed_s"] for t in per_proc)
        print("RESULT " + json.dumps({
            "losses": losses, "devices": jax.local_device_count(),
            "processes": jax.process_count(),
            "steps": steps, "chunk": args.chunk, "pairs": int(npairs),
            "elapsed_s": slowest, "step_time_s": slowest / steps,
            # fleet rate: nprocs replicas each advancing steps/slowest
            "steps_per_s": args.nprocs * steps / slowest,
            "per_process_elapsed_s": [t["elapsed_s"] for t in per_proc],
        }), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--task", choices=["pipeline", "bench"],
                    default="pipeline")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--nodes", type=int, default=128)
    ap.add_argument("--feat", type=int, default=8)
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    # persistent XLA compile cache, same resolution as the CLIs: every
    # group in a test/bench run compiles the SAME tiny programs, so
    # only the first-ever worker pays the cold compile — the rest
    # deserialize (the smoke/check/bench trio spawns 6+ processes)
    from hyperspace_tpu import compile_cache
    try:
        compile_cache.activate()
    except ValueError:
        pass  # unwritable cache dir: run cold rather than die

    from hyperspace_tpu.parallel import multihost as mh

    mh.initialize(f"127.0.0.1:{args.port}", args.nprocs, args.pid,
                  local_device_count=2)
    os.makedirs(args.workdir, exist_ok=True)
    if args.task == "bench":
        return run_bench(args, mh)
    return run_pipeline(args, mh)


if __name__ == "__main__":
    sys.exit(main())
