"""On-chip throughput for workloads 3–5 (VERDICT r3 weak #7 / next #8).

BASELINE.json's recorded metrics cover HGCN (workload 2) and the
Poincaré embeddings (workload 1); "COMPLETE" still wants a measured
number per workload, so this module times a standard-config train step
for HyboNet (3), HVAE (4) and product-space embeddings (5) on the live
backend, plus a ≥4k-token HyboNet fwd+bwd leg that exercises the N7
flash kernel in BOTH directions at long context (the r04 flash-backward
criterion).  Rides in bench.py's auto detail as one line per workload.
"""

from __future__ import annotations


def run_workloads_bench(repeats: int = 4, steps: int = 10) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.benchmarks.hgcn_bench import (
        roofline_fields,
        spread,
        step_cost,
        time_steps_all,
    )
    from hyperspace_tpu.data.mnist import synthetic_mnist
    from hyperspace_tpu.data.text import synthetic_text
    from hyperspace_tpu.data.wordnet import synthetic_tree
    from hyperspace_tpu.models import hvae, hybonet, product_embed as pe

    # default repeats=4: these legs are cheap (ms-scale steps) but the
    # r04 artifact showed ~50% session-to-session drift vs the docs
    # table — min over more repeats + the recorded spread make
    # contention visible (VERDICT r4 weak #8).  An explicit smaller
    # value is honored (quick smoke passes).
    out: dict = {"backend": jax.default_backend()}

    def timed_leg(stepper, state, n_steps):
        """(step_s, roofline dict, state): min-of-repeats + spread +
        the compiled bytes/flops bounds (VERDICT r4 #6)."""
        times, state, _ = time_steps_all(stepper, state, n_steps, repeats)
        step_s = min(times) / n_steps
        roof = roofline_fields(step_cost(stepper, state), step_s)
        return step_s, {"repeat_spread": spread(times), **roof}, state

    def scanned_leg(stepper, state, k=32):
        """Per-step ms of ONE dispatch running k chained steps — the fix
        for dispatch-floor-bound legs: the r05 rooflines showed
        HVAE/product steps pinned at ~7 ms while their HBM bound is
        0.3–0.6 ms, i.e. the remote-attach per-dispatch latency, not
        chip time.  Runs the SAME chunked stepper production training
        uses (train/loop.make_chunked_stepper, the CLI ``scan_chunk``
        path), so the ``scan_chunk_*`` fields measure the shipped code,
        not a bench-only twin."""
        from hyperspace_tpu.train.loop import make_chunked_stepper

        run = make_chunked_stepper(stepper, k)
        times, _, _ = time_steps_all(run, state, 1, repeats)
        return round(min(times) / k * 1e3, 3)

    def scan_fields(step_s, scan_ms, k=32):
        """The chunked-dispatch win, quantified per leg: K, per-step ms
        at K, and the per-step dispatch overhead the chunking removed
        (stepwise ms − scanned ms)."""
        return {
            "scan_chunk_k": k,
            "scan_chunk_step_ms": scan_ms,
            "scan_chunk_dispatch_overhead_ms": round(
                step_s * 1e3 - scan_ms, 3),
        }

    # --- HyboNet (workload 3): transformer classifier, flash attention
    cfg = hybonet.HyboNetConfig(vocab_size=8192, num_classes=8, max_len=128,
                                dim=128, num_heads=4, num_layers=2,
                                batch_size=256)
    ds = synthetic_text(num_samples=2048, vocab_size=cfg.vocab_size,
                        num_classes=cfg.num_classes, max_len=cfg.max_len,
                        min_len=cfg.max_len // 2, seed=0)
    model, opt, state = hybonet.init_model(cfg, seed=0)
    toks = jnp.asarray(ds.tokens)
    mask = jnp.asarray(ds.mask)
    labels = jnp.asarray(ds.labels)
    step_s, roof, state = timed_leg(
        lambda st: hybonet.train_step_sampled(model, opt, st, toks, mask,
                                              labels),
        state, steps)
    out["hybonet"] = {
        "step_ms": round(step_s * 1e3, 3),
        "tokens_per_s": round(cfg.batch_size * cfg.max_len / step_s, 1),
        "batch": [cfg.batch_size, cfg.max_len],
        "dim": cfg.dim, "layers": cfg.num_layers,
        "attention_impl": cfg.attention_impl,
        "precision": cfg.precision,
        **roof,
    }

    # --- HyboNet long context: 4k tokens fwd+bwd through the flash
    # kernel (forward online-softmax, recomputing backward — no [L, L]
    # score matrix in either direction)
    lcfg = hybonet.HyboNetConfig(vocab_size=8192, num_classes=8,
                                 max_len=4096, dim=64, num_heads=2,
                                 num_layers=1, batch_size=2)
    lds = synthetic_text(num_samples=4, vocab_size=lcfg.vocab_size,
                         num_classes=lcfg.num_classes, max_len=lcfg.max_len,
                         min_len=lcfg.max_len - 1, seed=0)
    lmodel, lopt, lstate = hybonet.init_model(lcfg, seed=0)
    lt, lm, ll = (jnp.asarray(lds.tokens[: lcfg.batch_size]),
                  jnp.asarray(lds.mask[: lcfg.batch_size]),
                  jnp.asarray(lds.labels[: lcfg.batch_size]))
    step_s, roof, lstate = timed_leg(
        lambda st: hybonet.train_step(lmodel, lopt, st, lt, lm, ll),
        lstate, max(steps // 2, 3))
    out["hybonet_long"] = {
        "step_ms": round(step_s * 1e3, 3),
        "tokens_per_s": round(lcfg.batch_size * lcfg.max_len / step_s, 1),
        "batch": [lcfg.batch_size, lcfg.max_len],
        "fwd_bwd": "flash both directions",
        **roof,
    }

    # --- HVAE (workload 4)
    hcfg = hvae.HVAEConfig(batch_size=256)
    hds = synthetic_mnist(num_samples=2048, seed=0)
    hmodel, hopt, hstate = hvae.init_model(hcfg, seed=0)
    x_all = jnp.asarray(hds.images, hcfg.dtype)

    def hvae_step(st):
        st, loss, recon, kl = hvae.train_step_sampled(hmodel, hopt, st,
                                                      x_all)
        return st, loss

    step_s, roof, hstate = timed_leg(hvae_step, hstate, steps)
    scan_ms = scanned_leg(hvae_step, hstate)
    out["hvae"] = {
        "step_ms": round(step_s * 1e3, 3),
        "images_per_s": round(hcfg.batch_size / step_s, 1),
        **scan_fields(step_s, scan_ms),
        "scan_chunk_images_per_s": round(
            hcfg.batch_size / (scan_ms / 1e3), 1),
        "batch": [hcfg.batch_size, hcfg.image_size, hcfg.image_size],
        "kind": hcfg.kind,
        "precision": hcfg.precision,
        **roof,
    }

    # --- product-space embeddings (workload 5): WordNet-noun-scale table
    tree = synthetic_tree(depth=5, branching=9)
    pcfg = pe.ProductEmbedConfig(num_nodes=tree.num_nodes, batch_size=1024)
    pstate, curv_opt = pe.init_state(pcfg, seed=0)
    pairs = jnp.asarray(tree.pairs)
    p_step = lambda st: pe.train_step(pcfg, curv_opt, st, pairs)
    step_s, roof, pstate = timed_leg(p_step, pstate, steps)
    scan_ms = scanned_leg(p_step, pstate)
    out["product_embed"] = {
        "step_ms": round(step_s * 1e3, 3),
        "pairs_per_s": round(pcfg.batch_size / step_s, 1),
        **scan_fields(step_s, scan_ms),
        "scan_chunk_pairs_per_s": round(
            pcfg.batch_size / (scan_ms / 1e3), 1),
        "num_nodes": tree.num_nodes,
        "factors": [list(f) for f in pcfg.factors],
        "precision": pcfg.precision,
        **roof,
    }
    return out
