"""HGCN throughput benchmark — the north-star metric (SURVEY.md §6).

BASELINE.json: "HGCN samples/sec/chip on ogbn-arxiv"; target ≥ 2× a single
A100 at matching ROC-AUC.  Samples/sec = nodes forward+backward per second
of full-graph training (the HGCN-codebase convention: one full-graph step
processes every node once).

Without the real ogbn-arxiv files on disk the graph is a synthetic
hierarchy at exactly arxiv scale (169 343 nodes / 1.166 M directed edges,
128 features, 40 classes); with ``data_root`` pointing at extracted OGB
csvs the real graph is used — shapes and therefore timings match either
way.
"""

from __future__ import annotations

import time

def time_steps_all(stepper, state, n_steps: int, repeats: int):
    """All repeat wall times for ``n_steps`` chained ``stepper`` calls.

    The ONE timing harness every benchmark here and in bench.py shares;
    returns ``(times_list, final_state, final_loss)``.  Completion
    barrier is a host fetch of the loss (``jax.device_get``), not
    ``block_until_ready``: remote-attached TPUs (axon tunnel) ack
    block_until_ready before execution finishes, and only a host fetch
    reliably waits — keep that rationale with this function, it is
    load-bearing for every number in docs/benchmarks.md.

    Chips here are remotely attached and sometimes contended, so the
    headline convention is MIN-of-repeats, and benches also record the
    repeat SPREAD (max/min) so a contended session is visible in the
    artifact instead of masquerading as a regression (VERDICT r4 weak
    #8: HVAE/product drifted ~50% between sessions with no marker).
    """
    import jax

    state, loss = stepper(state)  # compile + warmup
    jax.device_get(loss)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, loss = stepper(state)
        jax.device_get(loss)
        times.append(time.perf_counter() - t0)
    return times, state, loss


def time_steps(stepper, state, n_steps: int, repeats: int):
    """min-of-repeats wrapper over :func:`time_steps_all`."""
    times, state, loss = time_steps_all(stepper, state, n_steps, repeats)
    return min(times), state, loss


def spread(times) -> float:
    """max/min repeat ratio — ≫1 flags a contended chip session."""
    return round(max(times) / max(min(times), 1e-12), 3)


# single-chip peaks for the bench part (v5e): the honest MFU statement
# for the bandwidth-bound graph workloads is the HBM-roofline fraction
ROOFLINE_CHIP = "v5e"
V5E_HBM_BYTES_PER_S = 819e9
V5E_BF16_FLOPS = 197e12


def step_cost(stepper, state) -> dict:
    """flops/bytes of one compiled step + roofline bounds (VERDICT r4
    #6/#10).  Compiles the stepper once more for analysis (the remote
    compile cache makes this cheap after the timing run); returns {} on
    any failure so a cost-analysis quirk can never sink a bench leg.

    The hbm/mxu bounds assume the ``ROOFLINE_CHIP`` peaks regardless of
    where the step actually ran, so the artifact records BOTH the
    assumed chip and the detected device kind (ADVICE r5): a CPU or
    other-chip run's ``frac_*_roofline`` numbers are then readable as
    "fraction of a v5e" instead of silently passing for on-chip truth."""
    import jax

    from hyperspace_tpu.train.profiling import compiled_cost

    try:
        c = compiled_cost(stepper, state)  # ONE home of the list-shape fix
        flops = float(c["flops"])
        byts = float(c["bytes accessed"])
        return {
            "flops_per_step": flops,
            "bytes_per_step": byts,
            "hbm_bound_ms": round(byts / V5E_HBM_BYTES_PER_S * 1e3, 6),
            "mxu_bound_ms": round(flops / V5E_BF16_FLOPS * 1e3, 6),
            "roofline_chip": ROOFLINE_CHIP,
            "device_kind": jax.devices()[0].device_kind,
        }
    except Exception:  # noqa: BLE001 — diagnostic only, never fatal
        return {}


def roofline_fields(cost: dict, step_s: float) -> dict:
    """Achieved fraction of the binding resource for a measured step."""
    if not cost:
        return {}
    hbm = cost["hbm_bound_ms"] / (step_s * 1e3)
    mxu = cost["mxu_bound_ms"] / (step_s * 1e3)
    return {
        **cost,
        "frac_hbm_roofline": round(hbm, 4),
        "frac_mxu_roofline": round(mxu, 4),
        "bound": "hbm" if cost["hbm_bound_ms"] >= cost["mxu_bound_ms"]
                 else "mxu",
    }


ARXIV_NODES = 169_343
ARXIV_EDGES = 1_166_243
ARXIV_FEATS = 128
ARXIV_CLASSES = 40


def arxiv_scale_graph(num_nodes: int = ARXIV_NODES, seed: int = 0):
    """Synthetic hierarchy at ogbn-arxiv edge density.

    Edge count scales with ``num_nodes`` at arxiv's density so reduced-size
    runs stay proportionate.  The one construction every bench shares
    (full-graph LP, NC, sampled) — comparable numbers by construction.
    Returns (edges, x, labels, num_classes).
    """
    from hyperspace_tpu.data import graphs as G

    n_edges = ARXIV_EDGES * num_nodes / ARXIV_NODES
    extra = (n_edges - (num_nodes - 1) * 3) / num_nodes
    return G.synthetic_hierarchy(
        num_nodes=num_nodes, branching=3, feat_dim=ARXIV_FEATS,
        ancestor_hops=3, extra_edge_frac=max(extra, 0.0),
        num_classes=ARXIV_CLASSES, seed=seed)


def arxiv_scale_split(num_nodes: int = ARXIV_NODES, seed: int = 0,
                      reorder: str | None = "community",
                      cluster_min_pair: int = 256):
    """:func:`arxiv_scale_graph` + its LP split; returns (split, x).

    The graph is community-reordered by default: the LPA locality order
    lifts the synthetic hierarchy's clusterable edge fraction from 8%
    to ~39% (the tree+ancestor structure is there — the generation-order
    ids just hide it), which is the layout the cluster-pair kernels are
    built for.  A pure relabeling: quality metrics are unaffected.
    ``cluster_min_pair``: 256 for mean aggregation, 128 when attention
    will run (the r05 per-mode sweep, data.graphs.prepare doc).
    """
    from hyperspace_tpu.data import graphs as G

    edges, x, labels, ncls = arxiv_scale_graph(num_nodes, seed)
    if reorder:
        edges, x, labels, _ = G.apply_locality_order(edges, x, labels,
                                                     method=reorder)
    split = G.split_edges(edges, num_nodes, x, val_frac=0.02, test_frac=0.02,
                          seed=seed, pad_multiple=65536,
                          cluster_min_pair=cluster_min_pair)
    return split, x


def run_hgcn_bench(
    repeats: int = 3,
    steps_per_repeat: int = 10,
    backend: str = "",
    data_root: str | None = None,
    num_nodes: int = ARXIV_NODES,
    dtype: str = "float32",
    agg_dtype: str = "bfloat16",  # precision-policy: ok (CLI flag name)
    use_att: bool = False,
    step: str = "pairs",  # "lp" | "pairs" (fully-planned decoder scatters)
    decoder_dtype: str | None = "bfloat16",  # precision-policy: ok (flag)
) -> dict:
    """The default config — pairs step, f32 compute, bf16 edge messages
    and bf16 decoder pass (everything accumulates f32) — is the r02 bench
    default: measured quality-neutral at full 169 k-node scale over 3
    seeds (test AUC 0.6196 vs 0.6193 f32 control; docs/benchmarks.md) at
    987 k samples/s/chip vs 812 k for the r01 lp-step default."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    cmp_ = G.cluster_min_pair_for(use_att)
    if data_root is not None:
        edges, x, labels, ncls, source = G.load_graph("ogbn-arxiv", data_root)
        # real citation graphs arrive with arbitrary ids: the BFS locality
        # relabeling turns their community structure into the block
        # locality the cluster-pair kernel converts into VMEM-tile reuse
        edges, x, labels, _ = G.apply_locality_order(edges, x, labels)
        num_nodes = x.shape[0]
        split = G.split_edges(edges, num_nodes, x, val_frac=0.02,
                              test_frac=0.02, seed=0, pad_multiple=65536,
                              cluster_min_pair=cmp_)
    else:
        split, x = arxiv_scale_split(num_nodes, cluster_min_pair=cmp_)
        source = "synthetic"
    from hyperspace_tpu.precision import parse_dtype

    cfg = hgcn.HGCNConfig(
        feat_dim=x.shape[1], hidden_dims=(128, 32), kind="lorentz",
        use_att=use_att,
        dtype=parse_dtype(dtype),
        # explicit f32 (not None): "--agg-dtype float32" must force f32
        # messages even when the compute dtype is bf16
        agg_dtype=parse_dtype(agg_dtype),
        # like agg_dtype: explicit "float32" must force an f32 decoder
        # pass even when the compute dtype is bf16; None inherits dtype
        decoder_dtype=parse_dtype(decoder_dtype))
    if use_att:  # shipped attention-mode defaults (run_realistic_bench note)
        from hyperspace_tpu.cli.train import hgcn_mode_defaults

        cfg = hgcn_mode_defaults(cfg, {"use_att": "true"}, sampled=False)
    model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
    ga = hgcn._device_graph(split.graph)
    if step == "pairs":
        pos = hgcn.make_planned_pairs(split.train_pos, num_nodes)
        neg_u, neg_plan = hgcn.make_static_negatives(
            num_nodes, int(pos.u.shape[0]) * cfg.neg_per_pos, seed=0)
        step_fn = lambda st: hgcn.train_step_lp_pairs(
            model, opt, num_nodes, st, ga, pos, neg_u, neg_plan)
    else:
        train_pos = jnp.asarray(split.train_pos)
        step_fn = lambda st: hgcn.train_step_lp(
            model, opt, num_nodes, st, ga, train_pos)

    times, state, loss = time_steps_all(step_fn, state, steps_per_repeat,
                                        repeats)
    best = min(times)
    samples_per_sec = num_nodes * steps_per_repeat / best
    n_dev = jax.device_count()
    # roofline accounting for the headline step (VERDICT r4 #10): puts
    # the "~94% of HBM bandwidth" claim in the artifact each round
    roof = roofline_fields(step_cost(step_fn, state),
                           best / steps_per_repeat)
    return {
        "metric": "hgcn_samples_per_sec_per_chip",
        "value": round(samples_per_sec / n_dev, 1),
        "unit": "samples/s/chip",
        "vs_baseline": None,
        "detail": {
            "num_nodes": num_nodes,
            "reorder": "community",
            "frac_clustered": (
                None if split.graph.cluster_split is None
                else round(split.graph.cluster_split.frac_clustered, 4)),
            "num_edges_padded": int(split.graph.senders.shape[0]),
            "steps": steps_per_repeat,
            "step_time_s": round(best / steps_per_repeat, 5),
            "repeat_spread": spread(times),
            **roof,
            "loss": float(loss),
            "devices": n_dev,
            "backend": backend,
            "source": source,
            "dtype": dtype,
            "agg_dtype": agg_dtype,
            "use_att": use_att,
            # the config as EXECUTED: attention runs rewrite lr/clip to
            # the shipped mode defaults, and the clip stage is part of
            # the timed step — the artifact must say so
            "lr": cfg.lr,
            "clip_norm": cfg.clip_norm,
            "step": step,
            # both steps run the training decoder pass through
            # cfg.decoder_dtype (HGCNLinkPred casts z whenever
            # deterministic=False), so the record is the flag as executed
            "decoder_dtype": decoder_dtype,
            # precision mode as executed, so BENCH_r* trajectories stay
            # comparable across precision configs (docs/precision.md)
            "precision": cfg.precision,
        },
    }


def ensure_disk_dataset(root: str | None = None, seed: int = 0) -> str:
    """Materialize the community-structured power-law dataset on disk in
    the OGB extracted-csv layout (generate once, ~180 MB, cached).

    The uniform-random synthetic bench graph is adversarial to the
    locality/cluster levers (8% clusterable by construction); this
    dataset carries the hierarchical community structure real citation
    graphs have, AND exercises the full disk → ``load_ogbn_arxiv`` →
    ``prepare`` pipeline (VERDICT r3 #3: ``source: "disk"``).
    """
    import os

    from hyperspace_tpu.data import graphs as G

    if root is None:
        root = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                            ".cache", "arxiv-synth")
    root = os.path.abspath(root)
    if not os.path.exists(os.path.join(root, "raw", "edge.csv")):
        # write into a temp sibling and rename whole: an interrupted
        # generation must not leave a half-written tree that the
        # edge.csv existence sentinel would treat as complete
        tmp = root + ".tmp"
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        edges, x, labels, _ = G.community_power_law_graph(seed=seed)
        G.write_ogb_csv_layout(tmp, edges, x, labels)
        os.makedirs(os.path.dirname(root), exist_ok=True)
        shutil.rmtree(root, ignore_errors=True)
        os.replace(tmp, root)
    return root


def run_realistic_bench(repeats: int = 2, steps_per_repeat: int = 10,
                        data_root: str | None = None) -> dict:
    """Realistic-locality variant: disk csvs → loader → community reorder
    → cluster split → timed mean AND attention steps on the live backend.

    Reports the clusterable edge fraction the reorder achieves and both
    step times — the honest test of the r03/r04 cluster levers (the
    uniform synthetic caps clusterable edges at ~8%; this graph reaches
    ~31% under the community order).  Rides in bench.py's auto detail.
    """
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn

    root = ensure_disk_dataset(data_root)
    edges, x, labels, ncls, source = G.load_graph("ogbn-arxiv", root)
    edges, x, labels, _ = G.apply_locality_order(edges, x, labels,
                                                 method="community")
    num_nodes = x.shape[0]
    split = G.split_edges(edges, num_nodes, x, val_frac=0.02,
                          test_frac=0.02, seed=0, pad_multiple=65536)
    from hyperspace_tpu.data import prep_cache

    out = {
        "source": source,
        "num_nodes": num_nodes,
        "num_edges_padded": int(split.graph.senders.shape[0]),
        "reorder": "community",
        "backend": jax.default_backend(),
        # persistent graph-prep cache accounting (data/prep_cache.py):
        # from the second bench round on, the reorder/split/cluster prep
        # above is served from disk — hits > 0 is the observable
        "graph_cache": prep_cache.stats(),
    }
    for use_att in (False, True):
        # per-mode cluster threshold (r05 sweep): only the cluster
        # split differs between the legs, so rebuild just that piece
        # instead of re-running the whole host split pipeline
        from hyperspace_tpu.kernels.cluster import build_cluster_split

        g_ = split.graph
        g_.cluster_split = build_cluster_split(
            g_.senders, g_.receivers, g_.edge_mask, g_.deg, num_nodes,
            min_pair_edges=G.cluster_min_pair_for(use_att),
            rev_perm=g_.rev_perm)
        key = "att" if use_att else "mean"
        out[f"{key}_frac_clustered"] = round(
            g_.cluster_split.frac_clustered, 4)
        # precision="bf16" maps to the same bf16 agg/decoder lanes via
        # the policy (HGCNConfig.resolved_*_dtype) — no ad-hoc literals
        cfg = hgcn.HGCNConfig(
            feat_dim=x.shape[1], hidden_dims=(128, 32), kind="lorentz",
            use_att=use_att, precision="bf16")
        if use_att:
            # the shipped attention-mode defaults (ONE source of truth —
            # cli.hgcn_mode_defaults): at the full-graph lr=1e-2 the
            # attention arm diverges to NaN within 10 steps on this
            # hub-heavy graph; benching an unshippable config is
            # meaningless
            from hyperspace_tpu.cli.train import hgcn_mode_defaults

            cfg = hgcn_mode_defaults(cfg, {"use_att": "true"},
                                     sampled=False)
        model, opt, state = hgcn.init_lp(cfg, split.graph, seed=0)
        ga = hgcn._device_graph(split.graph)
        pos = hgcn.make_planned_pairs(split.train_pos, num_nodes)
        neg_u, neg_plan = hgcn.make_static_negatives(
            num_nodes, int(pos.u.shape[0]) * cfg.neg_per_pos, seed=0)
        step_fn = lambda st: hgcn.train_step_lp_pairs(
            model, opt, num_nodes, st, ga, pos, neg_u, neg_plan)
        best, state, loss = time_steps(step_fn, state, steps_per_repeat,
                                       repeats)
        out[f"{key}_lr"] = cfg.lr            # the config as EXECUTED
        out[f"{key}_clip_norm"] = cfg.clip_norm
        out[f"{key}_step_s"] = round(best / steps_per_repeat, 5)
        out[f"{key}_samples_per_s"] = round(
            num_nodes * steps_per_repeat / best, 1)
        out[f"{key}_loss"] = float(loss)
    return out


def run_sampled_bench(repeats: int = 3, steps: int = 64,
                      num_nodes: int = ARXIV_NODES) -> dict:
    """Neighbor-sampled minibatch trainer throughput (models/hgcn_sampled).

    Reports *supervised* samples/s — labeled seed nodes receiving a loss
    term per step (the minibatch-GNN paper unit; contrast with the
    full-graph metric's nodes-per-step convention, both defined in
    docs/benchmarks.md).  Rides in bench.py's auto detail.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.data import graphs as G
    from hyperspace_tpu.models import hgcn, hgcn_sampled as HS

    edges, x, labels, ncls = arxiv_scale_graph(num_nodes, seed=0)
    tr, _, _ = G.node_split_masks(num_nodes, seed=0)
    cfg = HS.SampledConfig(
        base=hgcn.HGCNConfig(feat_dim=ARXIV_FEATS, hidden_dims=(128, 32),
                             num_classes=ncls),
        fanouts=(10, 10), batch_size=512)
    batches, deg = HS.plan_batches(cfg, edges, labels, tr, num_nodes,
                                   steps=steps, seed=0)
    model, opt, state = HS.init_sampled_nc(cfg, feat_dim=ARXIV_FEATS, seed=0)
    xt = jnp.asarray(np.asarray(x, np.float32))

    times, state, _ = time_steps_all(
        lambda st: HS.train_step_sampled_nc(model, opt, st, xt, deg,
                                            batches),
        state, steps, repeats)
    step_s = min(times) / steps

    # sampling-INCLUSIVE wall clock (VERDICT r3 weak #4): fresh batches
    # flow from the background SampledBatchStream while the device
    # trains; the honest samples/s includes planning + transfer
    import time as _time

    tr_mask, _, _ = G.node_split_masks(num_nodes, seed=0)
    with HS.SampledBatchStream(
            cfg, "nc", num_nodes=num_nodes, edges=edges, labels=labels,
            train_mask=tr_mask, chunk_steps=steps, seed=1) as stream:
        batches1 = stream.next()          # warm the pipeline
        state, loss = HS.train_step_sampled_nc(model, opt, state, xt, deg,
                                               batches1)
        jax.device_get(loss)
        n_chunks = max(2, repeats)
        t0 = _time.perf_counter()
        for _ in range(n_chunks):
            b = stream.next()
            for _ in range(steps):
                state, loss = HS.train_step_sampled_nc(model, opt, state,
                                                       xt, deg, b)
            jax.device_get(loss)
        incl = (_time.perf_counter() - t0) / (n_chunks * steps)

    return {
        "step_ms": round(step_s * 1e3, 3),
        "supervised_samples_per_s": round(cfg.batch_size / step_s, 1),
        "repeat_spread": spread(times),
        "sampling_inclusive_step_ms": round(incl * 1e3, 3),
        "sampling_inclusive_samples_per_s": round(cfg.batch_size / incl, 1),
        "batch_size": cfg.batch_size,
        "fanouts": list(cfg.fanouts),
        "num_nodes": num_nodes,
    }
