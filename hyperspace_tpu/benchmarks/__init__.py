"""Benchmark harness (SURVEY.md §4.8): emits the BASELINE.json metrics."""
