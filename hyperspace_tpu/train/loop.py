"""Chunked-dispatch training loop — the production home of the r05 fix.

The r05 roofline study (docs/benchmarks.md, BENCH_r05.json) diagnosed the
small-step workloads (HVAE, product-embed) as pinned at the ~7 ms
per-dispatch latency floor — 10-20x above their HBM-roofline bounds — and
proved the fix (K steps per dispatch under ``lax.scan``) inside
``benchmarks/workloads_bench.py`` only.  This module promotes that bench
trick to a first-class training-loop feature shared by every CLI runner:

- :func:`make_chunked_stepper` compiles K calls of a single-step function
  into ONE XLA program (``lax.scan`` over the step body) with the carried
  train state donated, so a run pays one dispatch per K steps instead of
  one per step.  With the same step body and the same PRNG stream the
  chunked trajectory is bitwise the single-step trajectory (the
  ``train_epoch_scan`` guarantee, now generic).
- :func:`run_loop` is the ONE step loop every workload runner goes
  through (moved here from ``cli/train.py``): checkpoint/resume, JSONL
  logging with boundary-crossing cadence (a chunk that crosses a log or
  save interval fires it), and per-chunk loss accumulation
  (:class:`hyperspace_tpu.optim.metrics.ChunkMetrics` — one host fetch
  per log boundary, never one per step).
- :func:`resume_chunk` derives the batch-stream resume offset (ceil —
  see the function doc; floor would replay already-consumed rows).

Chunk size policy: ``K`` trades dispatch amortization against reaction
latency — checkpoints/logs can only land on chunk boundaries, so keep
``K`` ≲ the checkpoint cadence.  K=32 recovers the dispatch floor on the
ms-scale steps (docs/benchmarks.md "chunked dispatch"); K=1 is exactly
the old loop (steppers are called directly, no scan wrapper).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def make_chunked_stepper(step_fn: Callable, chunk_steps: int):
    """Compile ``chunk_steps`` calls of ``step_fn`` into one XLA program.

    ``step_fn(state, *args) -> (state, out...)`` must be a traceable
    single-step body (the jitted per-step train functions qualify: jit
    inlines under trace).  Returns ``chunk(state, *args)`` — one jitted
    dispatch running ``chunk_steps`` steps with ``state`` donated —
    whose outputs are the per-step ``out`` values stacked on a leading
    ``[chunk_steps]`` axis (a single extra output comes back as one
    stacked array, several as a tuple of stacked arrays).  ``*args`` are
    scan-invariant (the same batch/graph arrays feed every step in the
    chunk; steps that walk a plan index by ``state.step`` advance
    through it as usual).

    ``chunk_steps <= 1`` returns ``step_fn`` unchanged — the K=1 path is
    the caller's original stepper, bit-identical by construction.
    """
    k = int(chunk_steps)
    if k <= 1:
        return step_fn

    def body(state, *args):
        def one(st, _):
            res = step_fn(st, *args)
            out = res[1] if len(res) == 2 else tuple(res[1:])
            return res[0], out

        return jax.lax.scan(one, state, None, length=k)

    return jax.jit(body, donate_argnums=(0,))


def round_steps_to_chunk(steps: int, chunk_steps: int) -> int:
    """Step budget rounded UP to a chunk multiple: every dispatch runs
    exactly ``chunk_steps`` steps (the scan length is baked into the
    program), so checkpoint/log step numbers always equal the steps
    actually taken — never a clamped lie."""
    k = max(int(chunk_steps), 1)
    return -(-int(steps) // k) * k


def resume_chunk(ckpt_dir: Optional[str], resume: bool,
                 chunk_steps: int) -> int:
    """Starting chunk index for a resuming batch stream (e.g.
    ``hgcn_sampled.SampledBatchStream``): a run resuming from step R has
    consumed batches from chunks 0..ceil(R/cs)-1 (the last possibly
    partially), so the stream skips to the NEXT chunk boundary —
    restarting at 0 would replay the consumed chunks, and floor division
    would re-serve the already-started boundary chunk's first R%cs rows
    (ADVICE r04).  The skipped tail rows of a partial boundary chunk are
    iid draws that simply never get used; no batch is ever repeated."""
    if not (ckpt_dir and resume):
        return 0
    from hyperspace_tpu.train.checkpoint import peek_latest_step

    cs = max(int(chunk_steps), 1)
    return -(-peek_latest_step(ckpt_dir) // cs)


def _logger(run):
    from hyperspace_tpu.train.logging import MetricsLogger

    return MetricsLogger(run.log, stdout=False,
                         tensorboard_dir=run.tensorboard_dir)


def run_loop(run, state, stepper, project=None, steps_per_call=1):
    """Shared step loop: optional checkpoint/resume + JSONL logging.

    ``run`` is duck-typed (``cli.train.RunConfig`` shape): ``steps``,
    ``eval_every``, ``log``, ``tensorboard_dir``, ``ckpt_dir``,
    ``ckpt_every``, ``resume``.  Every workload runner goes through
    here, so --ckpt-dir / resume work uniformly.  The checkpoint manager
    is context-managed (its __exit__ waits for in-flight async saves and
    closes background threads, also on the exception path).  Orbax async
    saves copy device→host synchronously before returning, so saving a
    state whose buffers the next step's donation invalidates is safe.
    ``project`` re-projects restored states onto their manifolds
    (train/checkpoint.py's restore contract — guards dtype/float drift
    off the constraint surface).  ``steps_per_call`` is the chunk size:
    the stepper always executes exactly that many steps per call (see
    :func:`make_chunked_stepper`); chunked steppers return the stacked
    ``[steps_per_call]`` per-step losses, of which the LAST is the
    logged/returned loss and the chunk mean rides along as
    ``loss_mean``.  Returns ``(final_state, final_loss)``; loss is nan
    when no step ran.
    """
    ck = None
    start = 0
    loss = jnp.nan
    if run.ckpt_dir:
        from hyperspace_tpu.train.checkpoint import CheckpointManager

        ck = CheckpointManager(run.ckpt_dir,
                               save_interval_steps=run.ckpt_every)
    acc = None
    if steps_per_call > 1:
        from hyperspace_tpu.optim.metrics import ChunkMetrics

        acc = ChunkMetrics()
    # restore inside the with-block: a corrupt checkpoint raising in
    # restore() still closes the manager's async machinery on the way out
    with (ck if ck is not None else contextlib.nullcontext()), \
            _logger(run) as log:
        if (ck is not None and run.resume
                and ck.latest_committed_step() is not None):
            state, start = ck.restore(state, project=project)
            # re-materialize the restored pytree before stepping: the
            # first dispatch DONATES these buffers, and donating arrays
            # that came out of orbax's restore machinery (rather than out
            # of a jitted program) has been observed to corrupt resumed
            # trajectories under a persistent compilation cache; one
            # device-side copy per resume buys unconditionally safe
            # donation
            state = jax.tree_util.tree_map(
                lambda a: jnp.asarray(a).copy(), state)
        last_saved = None
        every = run.eval_every or 50
        done = start
        while done < run.steps:
            state, loss = stepper(state)
            if acc is not None:
                acc.add(loss)
            if jnp.ndim(loss):  # scanned chunk: [steps_per_call] losses
                loss = loss[-1]
            # the stepper always executes exactly steps_per_call steps
            # (the scan length is baked into the program), so the
            # recorded step count is the TRUE count — never clamped
            prev, done = done, done + steps_per_call
            # boundary-crossing gates: with chunked stepping, `done` only
            # takes chunk multiples, so exact-equality cadence would
            # degrade to lcm(chunk, interval); fire whenever the chunk
            # crossed an interval boundary (identical to the old
            # `done % every == 0` when steps_per_call == 1)
            if (done // every) > (prev // every):
                kw = {"loss": float(loss)}
                if acc is not None:
                    mean = acc.flush()
                    if mean is not None:
                        kw["loss_mean"] = mean
                log.log(done, **kw)
            # ckpt_every <= 0 = final save only (mirrors eval_every's
            # "0 = eval only at the end"; orbax's interval gate divides
            # by the interval, so it never sees a 0)
            if ck is not None and run.ckpt_every > 0:
                iv = run.ckpt_every
                crossed = (done // iv) > (prev // iv)
                if ck.save(done, state,
                           force=crossed and steps_per_call > 1):
                    last_saved = done
        if acc is not None and done > start:
            # chunks past the last crossed log boundary would otherwise
            # vanish: close the run with a final record so every step's
            # loss lands in some interval's loss_mean
            mean = acc.flush()
            if mean is not None:
                log.log(done, loss=float(loss), loss_mean=mean)
        if ck is not None and start < run.steps and last_saved != done:
            # the final state must land even when it misses the save
            # cadence — otherwise resume silently replays a partial chunk
            ck.save(done, state, force=True)
    return state, loss
