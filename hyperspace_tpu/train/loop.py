"""Chunked-dispatch training loop — the production home of the r05 fix.

The r05 roofline study (docs/benchmarks.md, BENCH_r05.json) diagnosed the
small-step workloads (HVAE, product-embed) as pinned at the ~7 ms
per-dispatch latency floor — 10-20x above their HBM-roofline bounds — and
proved the fix (K steps per dispatch under ``lax.scan``) inside
``benchmarks/workloads_bench.py`` only.  This module promotes that bench
trick to a first-class training-loop feature shared by every CLI runner:

- :func:`make_chunked_stepper` compiles K calls of a single-step function
  into ONE XLA program (``lax.scan`` over the step body) with the carried
  train state donated, so a run pays one dispatch per K steps instead of
  one per step.  With the same step body and the same PRNG stream the
  chunked trajectory is bitwise the single-step trajectory (the
  ``train_epoch_scan`` guarantee, now generic).
- :func:`run_loop` is the ONE step loop every workload runner goes
  through (moved here from ``cli/train.py``): checkpoint/resume, JSONL
  logging with boundary-crossing cadence (a chunk that crosses a log or
  save interval fires it), and per-chunk loss accumulation
  (:class:`hyperspace_tpu.optim.metrics.ChunkMetrics` — one host fetch
  per log boundary, never one per step).
- :func:`resume_chunk` derives the batch-stream resume offset (ceil —
  see the function doc; floor would replay already-consumed rows).
- ``run_loop`` is also the telemetry spine (``telemetry=`` on the CLI;
  docs/observability.md): it writes the run manifest as the FIRST JSONL
  record, wraps each dispatch/flush/save in trace spans, snapshots the
  counter registry (``ctr/*``) and span aggregates (``span/*``) into
  every log record, samples the numerical-health monitor every
  ``health_every`` chunks, and closes the stream with one
  ``telemetry_summary`` record.  Disabled (the default) none of that
  runs: the per-dispatch additions are one registry dict-op and a
  no-op span check — no host sync, no extra dispatches (tested).

Chunk size policy: ``K`` trades dispatch amortization against reaction
latency — checkpoints/logs can only land on chunk boundaries, so keep
``K`` ≲ the checkpoint cadence.  K=32 recovers the dispatch floor on the
ms-scale steps (docs/benchmarks.md "chunked dispatch"); K=1 is exactly
the old loop (steppers are called directly, no scan wrapper).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def make_chunked_stepper(step_fn: Callable, chunk_steps: int, policy=None):
    """Compile ``chunk_steps`` calls of ``step_fn`` into one XLA program.

    ``step_fn(state, *args) -> (state, out...)`` must be a traceable
    single-step body (the jitted per-step train functions qualify: jit
    inlines under trace).  Returns ``chunk(state, *args)`` — one jitted
    dispatch running ``chunk_steps`` steps with ``state`` donated —
    whose outputs are the per-step ``out`` values stacked on a leading
    ``[chunk_steps]`` axis (a single extra output comes back as one
    stacked array, several as a tuple of stacked arrays).  ``*args`` are
    scan-invariant (the same batch/graph arrays feed every step in the
    chunk; steps that walk a plan index by ``state.step`` advance
    through it as usual).

    ``policy`` is an optional mixed-precision policy (a
    ``hyperspace_tpu.precision`` Policy or preset name).  With a mixed
    policy the chunk program casts the floating leaves of ``*args`` —
    the batch data every step in the chunk reads — to the policy's
    compute dtype ONCE, outside the scan, so a bf16 run pays one host
    batch downcast per dispatch instead of one per step (integer/bool
    leaves — ids, masks — pass through untouched; the carried ``state``
    is never cast: master params stay in the param dtype).  The per-step
    losses are cast to the accumulation dtype on the way out.  ``None``
    or the f32 preset changes nothing — bit-identical by construction.

    ``chunk_steps <= 1`` returns ``step_fn`` unchanged (the K=1 path is
    the caller's original stepper, bit-identical by construction) except
    under a mixed policy, where a thin wrapper applies the same arg cast
    per call.
    """
    from hyperspace_tpu.precision import get_policy

    pol = get_policy(policy)
    k = int(chunk_steps)
    if k <= 1:
        if not pol.mixed:
            return step_fn

        def one_step(state, *args):
            # same arg-cast AND accum-cast contract as the scanned path,
            # so loss dtype never flips with the scan_chunk setting
            res = step_fn(state, *pol.cast_compute_tree(args))
            return (res[0],) + tuple(pol.cast_accum(o) for o in res[1:])

        return one_step

    def body(state, *args):
        args = pol.cast_compute_tree(args)  # once per chunk, not per step

        def one(st, _):
            res = step_fn(st, *args)
            if len(res) == 2:
                return res[0], pol.cast_accum(res[1])
            return res[0], tuple(pol.cast_accum(o) for o in res[1:])

        return jax.lax.scan(one, state, None, length=k)

    return jax.jit(body, donate_argnums=(0,))


def round_steps_to_chunk(steps: int, chunk_steps: int) -> int:
    """Step budget rounded UP to a chunk multiple: every dispatch runs
    exactly ``chunk_steps`` steps (the scan length is baked into the
    program), so checkpoint/log step numbers always equal the steps
    actually taken — never a clamped lie."""
    k = max(int(chunk_steps), 1)
    return -(-int(steps) // k) * k


def resume_chunk(ckpt_dir: Optional[str], resume: bool,
                 chunk_steps: int) -> int:
    """Starting chunk index for a resuming batch stream (e.g.
    ``hgcn_sampled.SampledBatchStream``): a run resuming from step R has
    consumed batches from chunks 0..ceil(R/cs)-1 (the last possibly
    partially), so the stream skips to the NEXT chunk boundary —
    restarting at 0 would replay the consumed chunks, and floor division
    would re-serve the already-started boundary chunk's first R%cs rows
    (ADVICE r04).  The skipped tail rows of a partial boundary chunk are
    iid draws that simply never get used; no batch is ever repeated."""
    if not (ckpt_dir and resume):
        return 0
    from hyperspace_tpu.train.checkpoint import peek_latest_step

    cs = max(int(chunk_steps), 1)
    return -(-peek_latest_step(ckpt_dir) // cs)


def _logger(run):
    from hyperspace_tpu.train.logging import MetricsLogger

    if jax.process_index() != 0:
        # multi-process runs: every process computes IDENTICAL losses
        # (DP steps end in an all-reduce), so N processes writing the
        # same JSONL/TB path would race each other for no information —
        # the run log is a process-0 artifact (docs/multihost.md)
        return MetricsLogger(None, stdout=False, tensorboard_dir=None)
    return MetricsLogger(run.log, stdout=False,
                         tensorboard_dir=run.tensorboard_dir)


def run_manifest(run) -> dict:
    """The run-identity record logged FIRST in every telemetry-enabled
    JSONL (the acceptance anchor for "which run produced this file"):
    full run config, device/backend identity, process topology, and the
    package version."""
    import dataclasses

    import jax

    import hyperspace_tpu

    try:
        config = dataclasses.asdict(run)
    except TypeError:  # duck-typed run object (tests)
        config = {k: v for k, v in vars(run).items()
                  if not k.startswith("_")}
    dev = jax.devices()[0]
    return {
        "config": config,
        "backend": jax.default_backend(),
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "version": hyperspace_tpu.__version__,
    }


def _telemetry_setup(run):
    """(tracer, registry, freshly_enabled) per the run's flags — all
    None/disabled by default.  Duck-typed getattr so non-CLI callers
    (tests, benches) opt in by simply having the attributes.
    ``freshly_enabled`` marks that THIS call turned the process-global
    tracer on (library use; the CLI enables it earlier, in ``main``, so
    host prep spans record too) — the loop then turns it back off on
    exit instead of leaking span recording into later runs."""
    telemetry_on = bool(getattr(run, "telemetry", False))
    trace_out = getattr(run, "trace_out", None)
    tracer = reg = None
    fresh = False
    if telemetry_on or trace_out:
        from hyperspace_tpu.telemetry import registry, trace

        fresh = not trace.default_tracer().enabled
        tracer = trace.enable(keep_events=bool(trace_out))
        if fresh:
            # library use: the tracer was off, so anything it holds is a
            # PRIOR run's aggregates/events — this run starts clean
            tracer.reset()
        registry.install_jax_monitoring_hook()
        reg = registry.default_registry() if telemetry_on else None
    return tracer, reg, fresh


@contextlib.contextmanager
def _tracer_guard(tracer, fresh, trace_out=None):
    """Return the process-global tracer to its pre-run state when this
    run_loop enabled it: dump the requested trace file (the CLI flow
    dumps later, in ``main``, so the eval span makes the timeline — a
    library caller's only dump point is here), drop unflushed boundary
    aggregates (they would bleed into a later run's first record), and
    disable recording."""
    try:
        yield
    finally:
        if tracer is not None and fresh:
            if trace_out:
                try:
                    tracer.dump_chrome_trace(trace_out)
                except OSError:
                    pass  # diagnostics never sink (or mask) the run
            tracer.flush_fields()
            tracer.enabled = False


def _health_monitor(run, health_fn):
    if health_fn is None or int(getattr(run, "health_every", 0) or 0) <= 0:
        return None, 0
    from hyperspace_tpu.telemetry.health import (
        DEFAULT_BOUNDARY_EPS, DEFAULT_VIOLATION_TOL, HealthMonitor)

    hm = HealthMonitor(
        health_fn,
        boundary_eps=float(getattr(run, "health_eps",
                                   DEFAULT_BOUNDARY_EPS)),
        violation_tol=float(getattr(run, "health_tol",
                                    DEFAULT_VIOLATION_TOL)),
        abort=bool(getattr(run, "health_abort", False)))
    return hm, int(run.health_every)


def _poison(state, loss):
    """Apply the ``train.step_nan`` fault: NaN every inexact leaf of
    the state and the loss — the device-side shape a poisoned batch
    leaves behind after one step has propagated it."""
    def p(a):
        a = jnp.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return a * jnp.asarray(jnp.nan, a.dtype)
        return a

    return jax.tree_util.tree_map(p, state), p(loss)


def _rollback_ctrl(run, ck, project, on_rollback):
    """RollbackController per the run's ``rollback``/``rollback_lr_backoff``
    flags (None when off).  ``rollback=N`` needs a checkpoint dir — the
    rollback target IS the last committed checkpoint."""
    max_rb = int(getattr(run, "rollback", 0) or 0)
    if max_rb <= 0:
        return None
    if ck is None:
        raise ValueError(
            "rollback=N needs ckpt_dir= — the divergence guard rewinds "
            "to the last COMMITTED checkpoint (docs/resilience.md)")
    from hyperspace_tpu.resilience.guard import RollbackController

    return RollbackController(
        ck, max_rollbacks=max_rb,
        lr_backoff=float(getattr(run, "rollback_lr_backoff", 0.5) or 0.5),
        project=project, on_rollback=on_rollback)


def run_loop(run, state, stepper, project=None, steps_per_call=1,
             health_fn=None, on_rollback=None):
    """Shared step loop: optional checkpoint/resume + JSONL logging.

    ``run`` is duck-typed (``cli.train.RunConfig`` shape): ``steps``,
    ``eval_every``, ``log``, ``tensorboard_dir``, ``ckpt_dir``,
    ``ckpt_every``, ``resume``; plus the optional telemetry knobs
    ``telemetry``, ``trace_out``, ``health_every``/``health_eps``/
    ``health_abort`` (absent = off) and the divergence-guard knobs
    ``rollback`` (max rollbacks; 0 = off) / ``rollback_lr_backoff``
    (docs/resilience.md).  With the guard on, a non-finite loss at a
    metrics/save boundary — or a health-threshold violation at the
    health cadence — rewinds to the last committed checkpoint instead
    of poisoning the rest of the run; ``on_rollback(restored_step,
    attempt, lr_scale)`` lets stream-fed callers re-seed past the
    poisoned chunk and apply the LR backoff.  Every workload runner goes through
    here, so --ckpt-dir / resume work uniformly.  The checkpoint manager
    is context-managed (its __exit__ waits for in-flight async saves and
    closes background threads, also on the exception path).  Orbax async
    saves copy device→host synchronously before returning, so saving a
    state whose buffers the next step's donation invalidates is safe.
    ``project`` re-projects restored states onto their manifolds
    (train/checkpoint.py's restore contract — guards dtype/float drift
    off the constraint surface).  ``steps_per_call`` is the chunk size:
    the stepper always executes exactly that many steps per call (see
    :func:`make_chunked_stepper`); chunked steppers return the stacked
    ``[steps_per_call]`` per-step losses, of which the LAST is the
    logged/returned loss and the chunk mean/last/min/max ride along as
    ``loss_*`` fields.  ``health_fn`` is a jitted ``state -> {name:
    device scalar}`` (``telemetry.health.make_health_fn``), sampled
    every ``run.health_every`` chunks — reading the state between
    dispatches is safe w.r.t. donation (the read is enqueued before the
    next dispatch consumes the buffers).  Returns ``(final_state,
    final_loss)``; loss is nan when no step ran.
    """
    from hyperspace_tpu.resilience import faults
    from hyperspace_tpu.telemetry import registry as telem
    from hyperspace_tpu.telemetry.trace import span, tracing

    tracer, reg, fresh_tracer = _telemetry_setup(run)
    # profile_steps=N: for the first N steps, block on each chunk's
    # result inside the dispatch window (the phase reads execution, not
    # async enqueue) and observe it as the device_step phase — the
    # train-plane mirror of the serve stage histograms; compile events
    # are armed too, so the profiled window attributes compile time.
    # N steps only: a permanent block would re-serialize host and
    # device, the exact overlap the chunked loop exists to buy.
    profile_steps = int(getattr(run, "profile_steps", 0) or 0)
    if profile_steps > 0:
        from hyperspace_tpu.train.telemetry import install_hooks

        install_hooks()
    monitor, health_every = _health_monitor(run, health_fn)
    mwriter = None
    metrics_out = (getattr(run, "metrics_out", None)
                   if jax.process_index() == 0 else None)
    if metrics_out:
        # Prometheus-text file snapshotter (telemetry/exposition.py):
        # a training job becomes scrapeable-by-file; checked at chunk
        # boundaries (one clock read each), final write on exit
        from hyperspace_tpu.telemetry.exposition import MetricsFileWriter

        mwriter = MetricsFileWriter(
            metrics_out, float(getattr(run, "metrics_every", 30.0)))
    ck = None
    start = 0
    loss = jnp.nan
    if run.ckpt_dir:
        from hyperspace_tpu.train.checkpoint import CheckpointManager

        ck = CheckpointManager(run.ckpt_dir,
                               save_interval_steps=run.ckpt_every)
    ctrl = _rollback_ctrl(run, ck, project, on_rollback)
    acc = None
    if steps_per_call > 1:
        from hyperspace_tpu.optim.metrics import ChunkMetrics

        acc = ChunkMetrics()

    # per-run counter baseline, mirroring the tracer's fresh/guard
    # semantics: when THIS run_loop freshly enabled telemetry (library
    # use — several runs share the process-cumulative registry), report
    # counters as deltas from loop entry so run 2 never claims run 1's
    # dispatches.  In the CLI flow telemetry comes up in main() before
    # graph prep, so no baseline is taken and pre-loop prep/prefetch
    # counts rightly belong to this run's records.
    counter_base = (reg.mark()
                    if (reg is not None and fresh_tracer) else None)

    def do_rollback(st, dn, log, reason):
        """The ONE rollback sequence every trigger funnels through:
        discard the poisoned interval's chunk-metric accumulation, then
        rewind — callers rebind (state, done), set loss = nan and
        continue."""
        if acc is not None:
            acc.flush()  # poisoned interval: discard
        return ctrl.rollback(st, dn, log, reason=reason)

    def record_fields():
        """Telemetry fields for one JSONL record: span aggregates since
        the last record + a consistent counter/gauge snapshot."""
        if reg is None:
            return {}
        out = tracer.flush_fields() if tracer is not None else {}
        out.update(reg.snapshot("ctr/", baseline=counter_base))
        return out

    # restore inside the with-block: a corrupt checkpoint raising in
    # restore() still closes the manager's async machinery on the way out
    # (tracer guard FIRST so it unwinds last, after the logger closed)
    with _tracer_guard(tracer, fresh_tracer,
                       getattr(run, "trace_out", None)), \
            (ck if ck is not None else contextlib.nullcontext()), \
            _logger(run) as log:
        if reg is not None:
            log.event("run_manifest", **run_manifest(run))
        if (ck is not None and run.resume
                and ck.latest_committed_step() is not None):
            state, start = ck.restore(state, project=project)
            # re-materialize the restored pytree before stepping: the
            # first dispatch DONATES these buffers, and donating arrays
            # that came out of orbax's restore machinery (rather than out
            # of a jitted program) has been observed to corrupt resumed
            # trajectories under a persistent compilation cache; one
            # device-side copy per resume buys unconditionally safe
            # donation
            state = jax.tree_util.tree_map(
                lambda a: jnp.asarray(a).copy(), state)
        if ctrl is not None and ck.latest_committed_step() is None:
            # the guard needs a rollback target from chunk one: without
            # a committed checkpoint the first divergence would be fatal
            ck.save(start, state, force=True)
        last_saved = None
        every = run.eval_every or 50
        done = start
        chunk_i = 0
        prof_until = start + profile_steps
        while True:
            while done < run.steps:
                t_disp = time.perf_counter()
                # span args: step-at-dispatch + chunk size, so a slow
                # span in the Perfetto timeline is attributable to its
                # position (built only while tracing — the disabled hot
                # path stays allocation-free)
                args = ({"step": done, "chunk": steps_per_call}
                        if tracing() else None)
                prof = profile_steps > 0 and done < prof_until
                with span("dispatch", args=args):
                    state, loss = stepper(state)
                    if prof:
                        # profiled window: the dispatch time must read
                        # execution, not enqueue (block_until_ready is
                        # not a host fetch — no value crosses the link)
                        jax.block_until_ready(loss)
                disp_ms = (time.perf_counter() - t_disp) * 1e3
                telem.observe("train/dispatch_ms", disp_ms)
                if prof:
                    telem.observe("train/phase/device_step_ms", disp_ms)
                telem.inc("train/dispatches")
                if mwriter is not None:
                    try:
                        mwriter.maybe_write()
                    except OSError:
                        pass  # scrape-file loss never sinks the run
                if faults.active() and faults.poison("train.step_nan"):
                    # chaos: the device-side shape one poisoned batch
                    # leaves after its step (docs/resilience.md)
                    state, loss = _poison(state, loss)
                chunk_i += 1
                if acc is not None:
                    acc.add(loss)
                if jnp.ndim(loss):  # scanned chunk: [spc] losses
                    loss = loss[-1]
                # the stepper always executes exactly steps_per_call
                # steps (the scan length is baked into the program), so
                # the recorded step count is the TRUE count — never
                # clamped
                prev, done = done, done + steps_per_call
                # boundary-crossing gates: with chunked stepping, `done`
                # only takes chunk multiples, so exact-equality cadence
                # would degrade to lcm(chunk, interval); fire whenever
                # the chunk crossed an interval boundary (identical to
                # the old `done % every == 0` when steps_per_call == 1)
                if (done // every) > (prev // every):
                    # the float(loss) fetch is the interval's real
                    # block-until-device-done (dispatch is async
                    # enqueue), so it must sit INSIDE the span or the
                    # wait would show up nowhere in the span breakdown
                    t_flush = time.perf_counter()
                    with span("metrics_flush"):
                        kw = {"loss": float(loss)}  # hyperlint: disable=host-sync-in-hot-path — the documented per-boundary fetch
                        if acc is not None:
                            stats = acc.flush()
                            if stats is not None:
                                kw.update(stats)
                    telem.observe("train/metrics_flush_ms",
                                  (time.perf_counter() - t_flush) * 1e3)
                    if ctrl is not None and ctrl.divergent(kw["loss"]):
                        # the poisoned interval's record is the incident
                        # event, not a loss row
                        state, done = do_rollback(
                            state, done, log,
                            f"non-finite loss at step {done}")
                        loss = jnp.nan
                        continue
                    log.log(done, **kw, **record_fields())
                # health sampling rides the chunk cadence, not the log
                # one: a diverging run should flag BEFORE the next log
                # boundary
                if monitor is not None and chunk_i % health_every == 0:
                    if ctrl is None:
                        monitor.check(state, done, log)
                    else:
                        # guard mode: a threshold violation (or the
                        # monitor's own abort) is a rollback trigger,
                        # not a warning/abort — until the budget runs out
                        try:
                            bad = monitor.problems(
                                monitor.check(state, done, log))
                        except FloatingPointError as e:
                            bad = [str(e)]
                        if bad:
                            state, done = do_rollback(
                                state, done, log,
                                "health: " + "; ".join(bad))
                            loss = jnp.nan
                            continue
                # ckpt_every <= 0 = final save only (mirrors
                # eval_every's "0 = eval only at the end"; orbax's
                # interval gate divides by the interval, so it never
                # sees a 0)
                if ck is not None and run.ckpt_every > 0:
                    iv = run.ckpt_every
                    crossed = (done // iv) > (prev // iv)
                    if ctrl is not None and crossed:
                        # guard-only fetch: a poisoned state must never
                        # be saved — it would become the rollback target
                        lv = float(loss)
                        if ctrl.divergent(lv):
                            state, done = do_rollback(
                                state, done, log,
                                f"non-finite loss at save boundary, "
                                f"step {done}")
                            loss = jnp.nan
                            continue
                    if ck.save(done, state,
                               force=crossed and steps_per_call > 1):
                        last_saved = done
            # end-of-run divergence check: a chunk past the last crossed
            # boundary can still be poisoned — never close (or final-
            # save) a diverged run while the guard has budget left
            if ctrl is not None and done > start:
                lv = float(loss)
                if ctrl.divergent(lv):
                    state, done = do_rollback(
                        state, done, log,
                        f"non-finite loss at run end, step {done}")
                    loss = jnp.nan
                    continue
            break
        if acc is not None and done > start:
            # chunks past the last crossed log boundary would otherwise
            # vanish: close the run with a final record so every step's
            # loss lands in some interval's loss_mean
            t_flush = time.perf_counter()
            with span("metrics_flush"):
                stats = acc.flush()
                final_loss = float(loss)  # hyperlint: disable=host-sync-in-hot-path — the run-closing boundary fetch
            telem.observe("train/metrics_flush_ms",
                          (time.perf_counter() - t_flush) * 1e3)
            if stats is not None:
                log.log(done, loss=final_loss, **stats, **record_fields())
        if ck is not None and start < run.steps and last_saved != done:
            # the final state must land even when it misses the save
            # cadence — otherwise resume silently replays a partial chunk
            ck.save(done, state, force=True)
        if reg is not None:
            if ck is not None:
                ck.wait()  # async saves landed → ckpt/bytes gauge is real
            summary = reg.snapshot("ctr/", baseline=counter_base)
            if tracer is not None:
                summary.update(tracer.total_fields())
            if jax.process_count() > 1:
                # fleet view (docs/observability.md "Multihost metric
                # aggregation", exercised by real training since this
                # loop went multi-process): every process contributes
                # its raw export over ONE allgather; counters sum,
                # gauges max — logged process-0-side as fleet/* fields
                from hyperspace_tpu.parallel.multihost import (
                    gather_metric_exports)
                from hyperspace_tpu.telemetry.aggregate import merge_exports

                fc, fg, _ = merge_exports(gather_metric_exports(reg))
                summary["fleet_processes"] = jax.process_count()
                summary.update({f"fleet/{k}": v for k, v in fc.items()})
                summary.update({f"fleet/{k}": v for k, v in fg.items()})
            log.event("telemetry_summary", steps=int(done), **summary)
        if mwriter is not None:
            try:
                # the run's final counters must land whatever the
                # cadence — the last scrape a collector sees is the
                # run's closing state
                mwriter.write()
            except OSError:
                pass
    return state, loss
