"""Host-resident planned-sparse training for beyond-HBM embedding
tables (ROADMAP item 3 — the training half; ``parallel/host_table.py``
holds the table/cache machinery, ``serve/engine.py`` the int8 serve
lane).

The in-HBM planned-packed trainer (models/poincare_embed.py) keeps the
whole ``[N, W]`` packed table (embeddings | optimizer moments) device-
resident; this runner keeps it in HOST memory and visits the device
with only each chunk's working set:

1. **Plan on host** (prefetched): draw ``chunk_steps`` batches +
   negatives, build the per-step sparse plans
   (``poincare_embed.plan_arrays_np``), and union the steps' unique
   rows into the chunk's touched-id set — all numpy, overlapped with
   the previous chunk's device work via ``data/prefetch.HostPrefetcher``.
2. **Hot-row gather**: ``DeviceHotCache.ensure`` uploads only the
   rows not already device-resident (one bucketed transfer + scatter);
   rows hot across chunks never cross the link again.
3. **Run the chunk** as ONE dispatch:
   ``train_epoch_planned_hosted`` — the packed-planned scan program
   with every plan ``uniq`` remapped to CACHE SLOTS (sentinel → C),
   updating the cache in place (donated).
4. **Write back at the chunk boundary**: fetch the touched rows and
   scatter them into the host master, so the master is current before
   the next chunk's gather.

**Equivalence contract.**  The default (synchronous gather) path is
**bitwise-identical** to the in-HBM planned-packed trainer fed the same
per-chunk plans (:func:`run_planned_inhbm`; tested): remapping rows to
slots changes gather/scatter indices, never values, and the per-row
optimizer math has no cross-row coupling.  ``gather_ahead=True``
overlaps upcoming chunks' row gathers with the current chunk's
compute; a row evicted from the cache and re-touched can then be read
STALE, bounded by the prefetch look-ahead: the worker runs up to
``prefetch_depth + 1`` chunks ahead of the consumer's write-back
(depth queued + one in flight), so the staleness bound is
``prefetch_depth + 1`` chunks (default 3) — a bounded-staleness trade
(the classic async parameter-server relaxation), documented and
opt-in.  Rows that stay CACHED are always current (the cache is
updated in place), so at ``hot_rows >= N`` the overlap mode is exact
again.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.data.prefetch import HostPrefetcher
from hyperspace_tpu.models import poincare_embed as pe
from hyperspace_tpu.parallel.host_table import DeviceHotCache, HostEmbedTable
from hyperspace_tpu.telemetry import registry as _telem
from hyperspace_tpu.telemetry.trace import span as _span
from hyperspace_tpu.train.telemetry import StepPhases

DEFAULT_CHUNK_STEPS = 8

# largest table the CLI will materialize back onto the device for the
# closing eval (`HostPlannedTrainer.to_state`) — past this the whole
# point of the host-resident path is that the table does NOT fit, so
# eval is skipped and the sharded master save is the run's product
EVAL_MAX_ROWS = 1 << 21


def auto_hot_rows(cfg: pe.PoincareEmbedConfig, chunk_steps: int) -> int:
    """Default cache capacity: the chunk's worst-case working set
    (every id distinct), capped at the table — small tables fit whole."""
    worst = int(chunk_steps) * cfg.batch_size * (2 + cfg.neg_samples)
    return min(cfg.num_nodes, worst)


def chunk_plan_np(cfg: pe.PoincareEmbedConfig, pairs: np.ndarray,
                  steps: int, seed: int, chunk_index: int):
    """Host-drawn batches + sparse plans for chunk ``chunk_index`` —
    deterministic in ``(cfg, pairs, steps, seed, chunk_index)``, so the
    host-resident and in-HBM trainers consume IDENTICAL plans (the
    bitwise contract's precondition)."""
    rng = np.random.default_rng((int(seed), int(chunk_index)))
    b, k = cfg.batch_size, cfg.neg_samples
    batch = pairs[rng.integers(0, len(pairs), (steps, b))]    # [S, B, 2]
    neg = rng.integers(0, cfg.num_nodes, (steps, b, k))
    return pe.plan_arrays_np(cfg, batch[..., 0], batch[..., 1], neg)


def _chunk_sizes(steps: int, chunk_steps: int) -> list[int]:
    sizes = [chunk_steps] * (steps // chunk_steps)
    if steps % chunk_steps:
        sizes.append(steps % chunk_steps)  # one ragged tail chunk
    return sizes


class HostPlannedTrainer:
    """Drives the per-chunk protocol above over one host master table.

    ``master`` holds PACKED rows (``pack_state`` layout: table alone
    for rsgd, table | mu | nu for radam); ``aux``/``key``/``step`` are
    the packed state's non-row leaves.  Build from a live
    :class:`~hyperspace_tpu.models.poincare_embed.TrainState` with
    :meth:`from_state` (small/medium tables), or hand a pre-built
    sharded master directly (the 10M-row bench path).
    """

    def __init__(self, cfg: pe.PoincareEmbedConfig, opt,
                 master: HostEmbedTable, aux, key, step=0, *,
                 chunk_steps: int = DEFAULT_CHUNK_STEPS,
                 hot_rows: int = 0, seed: int = 0,
                 gather_ahead: bool = False, prefetch_depth: int = 2,
                 profile: bool = False, phases: StepPhases = None):
        if master.num_rows != cfg.num_nodes:
            raise ValueError(
                f"master has {master.num_rows} rows; cfg.num_nodes is "
                f"{cfg.num_nodes}")
        pe._check_neg_mode(cfg, dense=False)
        self.cfg, self.opt, self.master = cfg, opt, master
        self.aux, self.key = aux, jnp.asarray(key)
        self.step = jnp.asarray(step, jnp.int32)
        self.chunk_steps = int(chunk_steps)
        if self.chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1; got {chunk_steps}")
        self.hot_rows = int(hot_rows) or auto_hot_rows(cfg, self.chunk_steps)
        self.seed = int(seed)
        self.gather_ahead = bool(gather_ahead)
        self.prefetch_depth = int(prefetch_depth)
        # per-chunk phase timers (train/telemetry.py): data_wait /
        # host_gather / device_step / write_back histograms; profile=
        # makes device_step block on the chunk's output (honest
        # execution window — the CLI's profile_steps= flag)
        self.phases = phases or StepPhases(profile=profile,
                                           annotate=profile)
        self.cache = DeviceHotCache(master, self.hot_rows)
        # ONE local config per capacity: the chunk program's static
        # num_nodes is the cache size C (remapped sentinel = C), so
        # every chunk shares one executable per plan shape
        self._cfg_local = dataclasses.replace(
            cfg, num_nodes=self.cache.capacity)

    @classmethod
    def from_state(cls, cfg: pe.PoincareEmbedConfig, opt,
                   state: pe.TrainState, *, shards: int = 1,
                   **kw) -> "HostPlannedTrainer":
        """Pack a live TrainState's rows into a host master (row-
        sharded ``shards`` ways) — the entry for tables that still fit
        on one device; big tables build the master directly."""
        p = pe.pack_state(cfg, state)
        master = HostEmbedTable.from_array(np.asarray(p.packed), shards)
        return cls(cfg, opt, master, p.aux, p.key, p.step, **kw)

    # --- the per-chunk protocol ----------------------------------------------

    def _make_chunk(self, chunk_index: int, steps: int):
        """Prefetcher body: plan + union on host; under ``gather_ahead``
        also the (possibly stale, bounded by the look-ahead) row gather."""
        plan = chunk_plan_np(self.cfg, self._pairs, steps, self.seed,
                             chunk_index)
        uniq = plan[3]
        chunk_ids = np.unique(uniq)
        chunk_ids = chunk_ids[chunk_ids < self.cfg.num_nodes]
        rows = self.master.gather(chunk_ids) if self.gather_ahead else None
        return plan, chunk_ids, rows

    def _run_chunk(self, item) -> np.ndarray:
        plan, chunk_ids, pre_rows = item
        cap = self.cache.capacity
        with self.phases.phase("host_gather"):
            if pre_rows is None:
                slots = self.cache.ensure(chunk_ids)
            else:
                slots = self.cache.ensure_with_rows(
                    chunk_ids, pre_rows, np.ones(len(chunk_ids), bool))
        u_idx, v_idx, neg_idx, uniq, inv_map, order, seg = plan
        # remap global rows -> cache slots; the sentinel (num_nodes)
        # becomes the local sentinel C (gather clamps, scatter drops)
        pos = np.minimum(np.searchsorted(chunk_ids, uniq),
                         max(len(chunk_ids) - 1, 0))
        local_uniq = np.where(uniq >= self.cfg.num_nodes, cap,
                              slots[pos]).astype(np.int32)
        dev_plan = pe.SparsePlan(*(jnp.asarray(a) for a in (
            u_idx, v_idx, neg_idx, local_uniq, inv_map, order, seg)))
        pstate = pe.PackedState(self.cache.array, self.aux, self.key,
                                self.step)
        # device_step: in profile mode the phase blocks on the updated
        # cache (the chunk's donated output) before closing, so the
        # window is execution, not enqueue; default mode adds no sync
        with self.phases.phase("device_step", lambda: out.packed):
            with _span("host_chunk_dispatch"):
                out, losses = pe.train_epoch_planned_hosted(
                    self._cfg_local, self.opt, pstate, dev_plan)
        self.cache.array = out.packed
        self.aux, self.key, self.step = out.aux, out.key, out.step
        # chunk-boundary write-back: the master is current before the
        # next chunk's gather (and before any eviction could drop the
        # only fresh copy)
        with self.phases.phase("write_back"):
            self.master.write_back(chunk_ids, self.cache.fetch(slots))
        _telem.inc("host_table/chunks")
        return np.asarray(losses)

    def run(self, pairs, steps: int) -> np.ndarray:
        """Train ``steps`` steps in chunks; returns the [steps] losses.

        Plans are built (and under ``gather_ahead`` rows gathered) in a
        background :class:`HostPrefetcher` thread, ``prefetch_depth``
        chunks ahead of the device."""
        self._pairs = np.asarray(pairs)
        sizes = _chunk_sizes(int(steps), self.chunk_steps)
        if not sizes:
            return np.zeros((0,), np.float32)
        losses = []
        with HostPrefetcher(
                lambda i: self._make_chunk(i, sizes[i]),
                depth=self.prefetch_depth) as pf:
            for _ in sizes:
                # data_wait: blocking on the prefetcher — near zero
                # while the planner keeps ahead of the device
                with self.phases.phase("data_wait"):
                    item = pf.next()
                losses.append(self._run_chunk(item))
        return np.concatenate(losses)

    def to_state(self) -> pe.TrainState:
        """Materialize the master back into a device TrainState — the
        small-table eval/export path only (a beyond-HBM table must stay
        on host; use the master directly)."""
        host = self.master.to_array()
        packed = jnp.asarray(host)  # hyperlint: disable=full-table-materialization — documented small-table eval/export exit; beyond-HBM callers keep the master host-resident
        return pe.unpack_state(self.cfg, pe.PackedState(
            packed, self.aux, self.key, self.step))


def run_planned_inhbm(cfg: pe.PoincareEmbedConfig, opt,
                      state: pe.TrainState, pairs, steps: int, *,
                      chunk_steps: int = DEFAULT_CHUNK_STEPS,
                      seed: int = 0) -> tuple[pe.TrainState, np.ndarray]:
    """The in-HBM reference: the SAME per-chunk plans
    (:func:`chunk_plan_np`) through the packed-planned device program
    over the full resident table — the bitwise baseline the host-
    resident path is tested against, and the bench's in-HBM step-time
    leg."""
    pairs = np.asarray(pairs)
    p = pe.pack_state(cfg, state)
    losses = []
    for ci, s in enumerate(_chunk_sizes(int(steps), int(chunk_steps))):
        plan = pe.SparsePlan(*(jnp.asarray(a) for a in chunk_plan_np(
            cfg, pairs, s, seed, ci)))
        p, chunk_losses = pe.train_epoch_planned_packed(cfg, opt, p, plan)
        losses.append(np.asarray(chunk_losses))
    return pe.unpack_state(cfg, p), np.concatenate(losses)
