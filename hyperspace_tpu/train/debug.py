"""Debug/correctness modes — the TPU analogue of the reference's CUDA
sanitizer story (SURVEY.md §5 "Race detection / sanitizers": not
applicable on TPU; instead NaN trapping, determinism assertions, and the
kernel parity suite).

- :func:`nan_checks` — context manager enabling ``jax_debug_nans``:
  any NaN produced inside jitted code raises at the producing op
  (re-runs the failing computation op-by-op), instead of surfacing
  steps later as a corrupted loss.
- :func:`assert_replicas_match` — asserts a value is identical across
  hosts/replicas (gradient sync / determinism guard); alias of
  :func:`hyperspace_tpu.parallel.multihost.assert_equal_across_hosts`.
- Determinism across device counts is asserted by
  ``tests/parallel/test_dp_equivalence.py``: the same DP train step on an
  8-device mesh must match the single-device run to float tolerance.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax


@contextmanager
def nan_checks(enabled: bool = True):
    """Enable jax_debug_nans within the block (compile caches are per-config,
    so expect recompiles inside).  Defers to JAX's own config context
    manager — same thread-local handling as ``with jax.debug_nans(...)``."""
    with jax.debug_nans(enabled):
        yield


def assert_replicas_match(x, message: str = "replica values diverged"):
    """Raise if ``x`` differs across processes (multi-host determinism)."""
    from hyperspace_tpu.parallel.multihost import assert_equal_across_hosts

    assert_equal_across_hosts(x, msg=message)
