"""JSONL metrics/event logging + optional TensorBoard sink
(SURVEY.md §5 "Metrics/logging": "JSONL event log ... + optional
TensorBoard writer").

One JSON object per line: {"step": ..., "ts": ..., "host": ..., **metrics}.
Cheap enough to call every step; file handle is line-buffered so a crashed
run keeps everything up to the last step.  Multi-host: each process writes
its own file (suffix = process index); step metrics are device-reduced
*before* logging by the caller, so host 0's file is the canonical one.
TensorBoard (``tensorboard_dir=``) is best-effort: only process 0 writes,
and a missing writer library degrades to JSONL-only with a warning.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str], *, stdout: bool = False,
                 tensorboard_dir: Optional[str] = None):
        """``path`` None → stdout-only when ``stdout`` else no-op."""
        self._stdout = stdout
        self._f = None
        self._tb = None
        try:
            import jax

            idx = jax.process_index()
        except Exception:
            idx = 0
        if path is not None:
            if idx != 0:
                root, ext = os.path.splitext(path)
                path = f"{root}.{idx}{ext or '.jsonl'}"
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a", buffering=1)
        if tensorboard_dir is not None and idx == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # best-effort sink; JSONL stays canonical
                import warnings

                warnings.warn(f"TensorBoard writer unavailable ({e!r}); "
                              "logging JSONL only")
        self._host = os.environ.get("HOSTNAME", "")

    def log(self, step: int, **metrics: Any):
        rec = {"step": int(step), "ts": time.time(), "host": self._host}
        for k, v in metrics.items():
            if isinstance(v, bool):  # flags (health/ok) stay JSON bools,
                rec[k] = v           # not 0.0/1.0
                continue
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = v
        line = json.dumps(rec)
        if self._f is not None:
            self._f.write(line + "\n")
        if self._tb is not None:
            for k, v in rec.items():
                if k not in ("step", "ts", "host") and isinstance(v, float):
                    self._tb.add_scalar(k, v, int(step))
        if self._stdout:
            print(line, flush=True)

    def event(self, name: str, **fields: Any):
        """Write one non-step record ``{"event": name, ...}`` — run
        manifests, telemetry summaries.  Values pass through as-is
        (nested dicts like a config allowed; caller keeps them
        JSON-serializable); non-serializable values degrade to repr
        rather than killing the run.  JSONL/stdout only — TensorBoard
        is a scalar sink."""
        rec = {"event": name, "ts": time.time(), "host": self._host,
               **fields}
        try:
            line = json.dumps(rec)
        except (TypeError, ValueError):
            # repr ONLY the offending fields: one bad value must not
            # flatten the whole record's structured payload to strings
            safe = {}
            for k, v in rec.items():
                try:
                    json.dumps(v)
                    safe[k] = v
                except (TypeError, ValueError):
                    safe[k] = repr(v)
            line = json.dumps(safe)
        if self._f is not None:
            self._f.write(line + "\n")
        if self._stdout:
            print(line, flush=True)

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_jsonl(path: str) -> list[dict]:
    """Parse a JSONL log, tolerating a truncated FINAL line.

    A crashed run's last write can be cut mid-record (line-buffering
    flushes whole lines, but a hard kill or full disk can still leave a
    partial tail); the readable prefix is the artifact, so return it
    instead of raising.  A malformed line with more records AFTER it is
    real corruption and still raises.
    """
    out = []
    pending_error = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if pending_error is not None:
                raise pending_error  # bad line was NOT the final one
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                pending_error = e
    return out
