"""JSONL metrics/event logging + optional TensorBoard sink
(SURVEY.md §5 "Metrics/logging": "JSONL event log ... + optional
TensorBoard writer").

One JSON object per line: {"step": ..., "ts": ..., "host": ..., **metrics}.
Cheap enough to call every step; file handle is line-buffered so a crashed
run keeps everything up to the last step.  Multi-host: each process writes
its own file (suffix = process index); step metrics are device-reduced
*before* logging by the caller, so host 0's file is the canonical one.
TensorBoard (``tensorboard_dir=``) is best-effort: only process 0 writes,
and a missing writer library degrades to JSONL-only with a warning.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str], *, stdout: bool = False,
                 tensorboard_dir: Optional[str] = None):
        """``path`` None → stdout-only when ``stdout`` else no-op."""
        self._stdout = stdout
        self._f = None
        self._tb = None
        try:
            import jax

            idx = jax.process_index()
        except Exception:
            idx = 0
        if path is not None:
            if idx != 0:
                root, ext = os.path.splitext(path)
                path = f"{root}.{idx}{ext or '.jsonl'}"
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a", buffering=1)
        if tensorboard_dir is not None and idx == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # best-effort sink; JSONL stays canonical
                import warnings

                warnings.warn(f"TensorBoard writer unavailable ({e!r}); "
                              "logging JSONL only")
        self._host = os.environ.get("HOSTNAME", "")

    def log(self, step: int, **metrics: Any):
        rec = {"step": int(step), "ts": time.time(), "host": self._host}
        for k, v in metrics.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = v
        line = json.dumps(rec)
        if self._f is not None:
            self._f.write(line + "\n")
        if self._tb is not None:
            for k, v in rec.items():
                if k not in ("step", "ts", "host") and isinstance(v, float):
                    self._tb.add_scalar(k, v, int(step))
        if self._stdout:
            print(line, flush=True)

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_jsonl(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
