"""L4 train runtime (SURVEY.md §1b): chunked-dispatch loop,
checkpointing, metrics, profiling."""

from hyperspace_tpu.train.checkpoint import CheckpointManager  # noqa: F401
from hyperspace_tpu.train.logging import MetricsLogger  # noqa: F401
from hyperspace_tpu.train.loop import (  # noqa: F401
    make_chunked_stepper,
    run_loop,
)
from hyperspace_tpu.train.profiling import benchmark_step  # noqa: F401
