"""L4 train runtime (SURVEY.md §1b): checkpointing, metrics, profiling."""

from hyperspace_tpu.train.checkpoint import CheckpointManager  # noqa: F401
from hyperspace_tpu.train.logging import MetricsLogger  # noqa: F401
from hyperspace_tpu.train.profiling import benchmark_step  # noqa: F401
