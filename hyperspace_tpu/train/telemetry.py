"""Train-plane step-phase telemetry: where a train step's time goes.

The serve plane decomposes a request into stages
(``telemetry/spans.py``); this module is the train-plane mirror for
the host-resident chunk protocol (``train/host_embed.py``) and the
chunked loop (``train/loop.py``).  Each chunk decomposes into the
:data:`PHASES`:

- ``data_wait`` — blocking on the :class:`~hyperspace_tpu.data.
  prefetch.HostPrefetcher` for the next chunk's plans (near zero when
  the prefetcher keeps ahead; the planner is the bottleneck when not),
- ``host_gather`` — ``DeviceHotCache.ensure``: the host→device
  transfer of the chunk's cold rows,
- ``device_step`` — the chunk's one fused dispatch.  Dispatch is async
  enqueue; in ``profile`` mode the phase blocks on the chunk's output
  (``jax.block_until_ready``) before closing, so the window times
  EXECUTION.  Off (the default), it times enqueue only and the wait
  surfaces in the next write_back/fetch — the production loop never
  pays an extra sync for telemetry,
- ``write_back`` — fetching the touched cache rows and scattering them
  into the host master.

Each phase observes a ``train/phase/<name>_ms`` registry histogram
(docs/observability.md "Train-plane phases"), so a training job with
``metrics_out=`` exposes its phase decomposition in the same
Prometheus families the serve plane does — and the multihost
aggregation hook (``parallel/multihost.gather_metric_exports``) merges
them across processes unchanged.

``annotate=True`` additionally wraps each phase in a
``jax.profiler.TraceAnnotation`` so the phases appear as named ranges
in a captured device profile; the import is lazy and degrades to a
no-op where the profiler is unavailable.

Host-table cache effectiveness (hit/miss/evict counters and the
``host_table/cache_hit_rate`` gauge) ticks inside
``parallel/host_table.py`` itself; compile events come from
``telemetry.registry.install_jax_monitoring_hook`` (``jax/recompiles``,
``jax/compile_s``) — :func:`install_hooks` arms it idempotently.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

from hyperspace_tpu.telemetry import registry as telem

# chunk-phase order: consecutive phases of one chunk never overlap, so
# their bounds are monotone in this order (tested)
PHASES = ("data_wait", "host_gather", "device_step", "write_back")


def install_hooks() -> None:
    """Arm the compile-event counters (idempotent): ``jax/recompiles``
    and ``jax/compile_s`` tick for every fresh XLA compile."""
    telem.install_jax_monitoring_hook()


def _annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for ``name``, or a no-op
    where the profiler API is unavailable (stripped builds)."""
    try:
        from jax.profiler import TraceAnnotation
    except (ImportError, AttributeError):
        return contextlib.nullcontext()
    return TraceAnnotation(name)


class StepPhases:
    """Per-chunk phase timers (module docstring).

    ``profile=True`` makes the ``device_step`` phase block on its
    output before closing (honest execution window — the bench/debug
    mode the CLI's ``profile_steps=`` flag arms); ``annotate=True``
    adds profiler trace annotations.  The last chunk's readings stay
    on :attr:`last` (ms) and :attr:`last_bounds` (raw perf_counter
    pairs) for assertions and log records."""

    def __init__(self, profile: bool = False,
                 annotate: bool = False):
        self.profile = bool(profile)
        self.annotate = bool(annotate)
        self.last: dict[str, float] = {}
        self.last_bounds: dict[str, tuple] = {}

    @contextlib.contextmanager
    def phase(self, name: str, block: Optional[Callable] = None):
        """Time one phase.  ``block`` is a thunk returning the device
        value(s) the phase produced — called (and blocked on) only in
        ``profile`` mode, AFTER the body, so late-bound locals are
        fine: ``with phases.phase("device_step", lambda: out.packed):``
        """
        ann = _annotation(name) if self.annotate else None
        t0 = time.perf_counter()
        try:
            if ann is not None:
                with ann:
                    yield
            else:
                yield
            if self.profile and block is not None:
                import jax

                jax.block_until_ready(block())
        finally:
            t1 = time.perf_counter()
            self.last[name] = (t1 - t0) * 1e3
            self.last_bounds[name] = (t0, t1)
            # the phase histogram family (one per PHASES member):
            # telemetry-catalog: train/phase/data_wait_ms
            # telemetry-catalog: train/phase/host_gather_ms
            # telemetry-catalog: train/phase/device_step_ms
            # telemetry-catalog: train/phase/write_back_ms
            telem.observe(f"train/phase/{name}_ms", (t1 - t0) * 1e3)
