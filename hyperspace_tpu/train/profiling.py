"""Profiling & timing harness (SURVEY.md §5 "Tracing/profiling").

- ``benchmark_step``: wall-clock a jitted step with warmup +
  ``block_until_ready`` — the number the benchmark suite reports.
- ``trace``: context manager around ``jax.profiler`` producing an XPlane/
  Perfetto trace directory for TPU runs.
- ``compiled_cost``: XLA's own FLOP/bytes estimate for a jitted function —
  per-kernel cost visibility without hardware counters.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable

import jax


def benchmark_step(
    fn: Callable[[], Any],
    *,
    warmup: int = 3,
    iters: int = 20,
) -> dict:
    """Time ``fn()`` (must return jax arrays); returns seconds statistics.

    ``warmup=0`` is legal (an intentionally-cold first iteration —
    compile time lands in ``max_s``): the warmup barrier only runs when
    a warmup call produced something to wait on.
    """
    out = None
    for _ in range(warmup):
        out = fn()
    if out is not None:
        jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    n = len(times)
    return {
        "mean_s": sum(times) / n,
        "p50_s": times[n // 2],
        "min_s": times[0],
        "max_s": times[-1],
        "iters": n,
    }


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler trace (view with TensorBoard/Perfetto/xprof)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_analysis_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to ONE flat dict.

    The raw call is backend- and version-shaped: older jax returns a
    one-element ``[dict]`` per program, some backends raise, some
    return None.  Every consumer (``compiled_cost``, the bench's
    ``step_cost``, the profiling scripts) goes through here so the
    list-shape handling lives in exactly one place; returns {} whenever
    no analysis is available.
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend without cost analysis
        return {}
    if isinstance(cost, (list, tuple)):  # older jax: one dict per program
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


def compiled_cost(fn: Callable, *args, **kwargs) -> dict:
    """flops / bytes-accessed of the XLA executable for fn(*args) —
    the two keys every roofline consumer wants, {} when the backend
    offers no analysis."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    cost = cost_analysis_dict(compiled)
    return {k: cost[k] for k in ("flops", "bytes accessed") if k in cost}
