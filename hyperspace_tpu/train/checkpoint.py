"""Checkpoint / resume on orbax (SURVEY.md §5 "Checkpoint / resume").

Saves the full training state pytree — params, optimizer state (for
Riemannian Adam that includes the tangent moments *and* the step count
whose base points are the saved params themselves), PRNG key, step, and
any learned curvatures, since they all live inside the state pytree.

Restore applies an optional ``project`` function (manifold re-projection):
checkpoints written in one dtype and restored in another can drift off the
constraint surface, and re-projection is idempotent for clean restores
(SURVEY.md §5: "restore re-projects params onto their manifolds").

Async by default: `keep_period`-style retention is delegated to orbax's
CheckpointManager options.  The recovery model is restart-from-checkpoint
(XLA programs are fixed-topology; SURVEY.md §5 "Failure detection").
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin orbax wrapper pinned to this framework's conventions."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Maybe-save (interval-gated); returns True if a save started.

        ``force=True`` bypasses the interval gate — used for the final
        step of a run, which must always land on disk regardless of
        where it falls in the save cadence."""
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def restore(
        self,
        state_like: Any,
        *,
        step: Optional[int] = None,
        project: Optional[Callable[[Any], Any]] = None,
    ) -> tuple[Any, int]:
        """Restore (state, step); ``state_like`` supplies structure/shapes."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_like))
        if project is not None:
            restored = project(restored)
        return restored, step

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self):
        """Block until async saves land (call before process exit)."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


def peek_latest_step(directory: str) -> int:
    """Latest checkpointed step under ``directory``, 0 if none — WITHOUT
    opening a full manager (no async machinery, nothing created on
    disk).  Used by the CLI to derive resume offsets (e.g. the sampled
    stream's starting chunk) before the training loop restores."""
    d = os.path.abspath(directory)
    if not os.path.isdir(d):
        return 0
    steps = [int(name) for name in os.listdir(d) if name.isdigit()]
    return max(steps, default=0)


def reproject_params(tags, params):
    """Build a ``project`` fn argument from a manifold tag tree: re-projects
    every manifold-tagged leaf, passes Euclidean leaves through."""
    from hyperspace_tpu.optim.tags import map_tagged

    def apply(tree):
        return map_tagged(
            lambda t, p: p if t is None else t.proj(p), tags, tree)

    return apply
