"""Checkpoint / resume on orbax (SURVEY.md §5 "Checkpoint / resume").

Saves the full training state pytree — params, optimizer state (for
Riemannian Adam that includes the tangent moments *and* the step count
whose base points are the saved params themselves), PRNG key, step, and
any learned curvatures, since they all live inside the state pytree.

Restore applies an optional ``project`` function (manifold re-projection):
checkpoints written in one dtype and restored in another can drift off the
constraint surface, and re-projection is idempotent for clean restores
(SURVEY.md §5: "restore re-projects params onto their manifolds").

Async by default: `keep_period`-style retention is delegated to orbax's
CheckpointManager options.  The recovery model is restart-from-checkpoint
(XLA programs are fixed-topology; SURVEY.md §5 "Failure detection").
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin orbax wrapper pinned to this framework's conventions."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
        save_retries: int = 2,
        retry_backoff_s: float = 0.05,
    ):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        # transient-IO retry policy around save (docs/resilience.md):
        # save_retries EXTRA attempts, exponential backoff from
        # retry_backoff_s — always a bounded loop, never sleep-forever
        self._save_retries = max(int(save_retries), 0)
        self._retry_backoff_s = float(retry_backoff_s)
        self._clean_orphans()
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def _clean_orphans(self) -> None:
        """Remove save debris a crashed process left behind: orbax
        staging dirs (``…orbax-checkpoint-tmp…``) and all-digit step
        dirs that fail the commit test.  A crash between staging write
        and the commit rename leaks exactly these shapes FOREVER (the
        retention policy only rotates committed steps), and an
        uncommitted dir shadows the resume scan's candidate list every
        restart.  Runs at init — before this manager has any save in
        flight; the single-writer assumption (one manager owns a
        checkpoint dir, as everywhere in this module) makes that safe.
        Cleanups are counted (``ckpt/orphans_cleaned``) and logged."""
        import shutil

        try:
            entries = os.listdir(self._dir)
        except OSError:
            return
        orphans = []
        for name in entries:
            path = os.path.join(self._dir, name)
            if "orbax-checkpoint-tmp" in name:
                orphans.append(path)
            elif (name.isdigit() and os.path.isdir(path)
                    and not _step_dir_committed(path)):
                orphans.append(path)
        if not orphans:
            return
        from hyperspace_tpu.telemetry import registry as telem

        cleaned = 0
        for path in orphans:
            try:
                if os.path.isdir(path):
                    shutil.rmtree(path)
                else:
                    os.remove(path)
                cleaned += 1
            except OSError as e:
                print(f"[ckpt] failed to clean orphan {path}: {e}",
                      flush=True)
        if cleaned:
            telem.inc("ckpt/orphans_cleaned", cleaned)
            print(f"[ckpt] cleaned {cleaned} orphaned staging "
                  f"dir(s) under {self._dir} (crash between staging "
                  "write and commit rename)", flush=True)

    def _fault_point(self, step: int) -> None:
        """The ``ckpt.save`` fault site (resilience/faults.py): chaos
        tests inject a transient IOError (absorbed by the retry loop),
        latency, or ``crash_staged`` — which materializes the exact
        on-disk debris a process killed between staging write and
        commit rename leaves (an uncommitted step dir + a staging dir),
        then raises InjectedCrash (NOT retried: a kill is not a
        transient)."""
        from hyperspace_tpu.resilience import faults

        spec = faults.due("ckpt.save")
        if spec is None:
            return
        if spec.kind == "latency":
            import time

            time.sleep(spec.ms / 1e3)
        elif spec.kind == "ioerror":
            raise faults.InjectedIOError("injected IOError at ckpt.save")
        elif spec.kind == "crash_staged":
            partial = os.path.join(self._dir, str(int(step)))
            os.makedirs(os.path.join(
                partial, "tmp.orbax-checkpoint-tmp-0"), exist_ok=True)
            os.makedirs(os.path.join(
                self._dir, f"{int(step)}.orbax-checkpoint-tmp-0"),
                exist_ok=True)
            raise faults.InjectedCrash(
                "injected crash between staging write and commit rename")

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Maybe-save (interval-gated); returns True if a save started.

        ``force=True`` bypasses the interval gate — used for the final
        step of a run, which must always land on disk regardless of
        where it falls in the save cadence.  Started saves bump
        ``ckpt/saves`` and accumulate the BLOCKING portion (orbax's
        synchronous device→host copy; the disk write is async) into
        ``ckpt/save_s`` — the number that says how much step time
        checkpointing steals (docs/observability.md).

        Transient ``OSError`` s (a flaky filesystem; the injected
        ``ckpt.save`` ioerror fault) are retried up to ``save_retries``
        extra attempts with exponential backoff (``ckpt/save_retries``
        counts them); past the budget the last error propagates —
        bounded by construction, per the ``unbounded-retry`` lint."""
        import time

        from hyperspace_tpu.resilience import faults
        from hyperspace_tpu.telemetry import registry as telem
        from hyperspace_tpu.telemetry.trace import default_tracer

        t0 = time.perf_counter()
        if not (force or self._mgr.should_save(step)):
            return False  # interval-gated skip: no copy, no fault point
        # snapshot the pytree BEFORE handing it to orbax: the async
        # machinery's device→host copy is NOT reliably complete when
        # save() returns (observed on this image's orbax 0.7.0 / CPU:
        # a donated stepper's next dispatch reuses the buffers and a
        # MID-RUN checkpoint silently holds a LATER step's content —
        # exactly the corruption a rollback target must never have).
        # One device copy per STARTED save; interval-gated skips above
        # pay nothing.
        state = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).copy(), state)
        for attempt in range(self._save_retries + 1):
            try:
                if faults.active():
                    self._fault_point(step)
                started = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force)
                break
            except OSError as e:
                if attempt >= self._save_retries:
                    raise
                telem.inc("ckpt/save_retries")
                delay = self._retry_backoff_s * (2 ** attempt)
                print(f"[ckpt] save step {step} attempt {attempt + 1} "
                      f"failed ({e}); retrying in {delay:.3g}s",
                      flush=True)
                time.sleep(delay)
        t1 = time.perf_counter()
        if started:
            # counter and span recorded together, and ONLY for saves
            # that actually started — an interval-gated skip is a no-op
            # in both metrics, so ckpt/saves and span/ckpt_save_n agree
            telem.inc("ckpt/saves")
            telem.inc("ckpt/save_s", t1 - t0)
            # the distribution behind the sum: a single slow save (a
            # cold filesystem, a huge state) shows in ckpt/save_ms p99
            # where the counter only shows a bigger total
            telem.observe("ckpt/save_ms", (t1 - t0) * 1e3)
            tracer = default_tracer()
            if tracer.enabled:
                tracer.record_span("ckpt_save", t0, t1,
                                   args={"step": int(step)})
        return started

    def restore(
        self,
        state_like: Any,
        *,
        step: Optional[int] = None,
        project: Optional[Callable[[Any], Any]] = None,
    ) -> tuple[Any, int]:
        """Restore (state, step); ``state_like`` supplies structure/shapes.

        With ``step=None`` the target is :meth:`latest_committed_step`,
        NOT orbax's ``latest_step()`` — orbax trusts any all-digit dir,
        including an interrupted save's empty one, and restoring that
        would crash (or worse, desync from the resume-offset accounting
        ``peek_latest_step`` derived from the committed step)."""
        step = self.latest_committed_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_like))
        if project is not None:
            restored = project(restored)
        return restored, step

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def latest_committed_step(self) -> Optional[int]:
        """Newest step dir that passes the commit test (the SAME scan
        ``peek_latest_step`` runs) — the restore target and the CLI's
        resume-offset source must agree on which step is real, or an
        interrupted save desyncs stream accounting from the restored
        step (ADVICE r5)."""
        return _latest_committed_step(self._dir)

    def wait(self):
        """Block until async saves land (call before process exit).

        Once everything is on disk, the ``ckpt/bytes`` gauge is set to
        the directory's total size — bytes are only meaningful after
        the async writes commit, so this is the one place to count.
        The recursive size walk only runs while a telemetry run has the
        tracer enabled; the default (telemetry off) pays nothing."""
        self._mgr.wait_until_finished()
        from hyperspace_tpu.telemetry.trace import default_tracer

        if default_tracer().enabled:
            from hyperspace_tpu.telemetry import registry as telem

            telem.set_gauge("ckpt/bytes", dir_bytes(self._dir))

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()


def restore_params_only(directory: str, *, step: Optional[int] = None
                        ) -> tuple[Any, int]:
    """Restore a checkpoint's raw state pytree WITHOUT constructing
    optimizer state — the serving-export path (``serve/artifact.py``).

    With ``step=None`` the target is the newest COMMITTED step (the same
    scan :meth:`CheckpointManager.latest_committed_step` runs, so an
    interrupted save's uncommitted dir is never trusted).  The restore
    goes through orbax's template-free ``StandardRestore``: the caller
    needs NO ``state_like`` pytree, hence no optimizer/model objects —
    NamedTuple states come back as plain dicts keyed by field name
    (``tree["table"]``, ``tree["params"]["c_raw"]``, ...).  Returns
    ``(tree, step)``.  Raises ``FileNotFoundError`` when no committed
    checkpoint exists under ``directory``.
    """
    directory = os.path.abspath(directory)
    if step is None:
        step = _latest_committed_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {directory}")
    elif not _step_dir_committed(os.path.join(directory, str(int(step)))):
        # the never-trust-an-uncommitted-dir rule holds for pinned steps
        # too — an interrupted save must not become a serving artifact
        raise FileNotFoundError(
            f"step {step} under {directory} is missing or uncommitted")
    mgr = ocp.CheckpointManager(directory)
    try:
        tree = mgr.restore(step, args=ocp.args.StandardRestore())
    finally:
        mgr.close()
    return tree, step


def dir_bytes(directory: str) -> int:
    """Total bytes on disk under ``directory`` (0 on any OS error).

    The per-file try/except is load-bearing, not defensive boilerplate:
    this walks the checkpoint dir WHILE the async save thread is
    renaming staging dirs and the retention policy is deleting old
    steps, so a file listed by ``os.walk`` can be gone (or mid-rename)
    by the time ``getsize`` stats it — ``FileNotFoundError`` (and any
    other ``OSError``) skips that file instead of sinking the gauge.
    """
    total = 0
    try:
        for root, _dirs, files in os.walk(directory):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(root, name))
                except OSError:  # incl. FileNotFoundError: deleted mid-scan
                    pass
    except OSError:
        pass
    return total


def _step_dir_committed(path: str) -> bool:
    """Whether a candidate step dir holds a COMMITTED save, judged the
    way orbax's ``latest_step()`` would: orbax writes into a
    ``…orbax-checkpoint-tmp…`` staging name and renames on commit, so an
    interrupted save leaves either no all-digit dir at all or an
    empty/partial one.  Structural test first (non-empty, no staging
    markers inside — orbax's own ``is_checkpoint_finalized`` passes an
    EMPTY dir, which is exactly the interrupted-save shape to reject),
    then orbax's finalization check on top when the installed version
    exposes it."""
    try:
        entries = os.listdir(path)
    except OSError:
        return False
    if not entries or any("orbax-checkpoint-tmp" in e for e in entries):
        return False
    try:
        from orbax.checkpoint import utils as ocp_utils

        return bool(ocp_utils.is_checkpoint_finalized(path))
    except Exception:  # noqa: BLE001 — version drift: structural verdict
        return True


def _latest_committed_step(directory: str) -> Optional[int]:
    """Newest all-digit step dir under ``directory`` that passes
    :func:`_step_dir_committed` — the ONE scan behind both
    ``peek_latest_step`` (resume-offset accounting) and
    ``CheckpointManager.latest_committed_step`` (restore target), so the
    two can never disagree on which step is real."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for s in sorted((int(n) for n in names if n.isdigit()), reverse=True):
        if _step_dir_committed(os.path.join(directory, str(s))):
            return s
    return None


def peek_latest_step(directory: str) -> int:
    """Latest COMMITTED checkpoint step under ``directory``, 0 if none —
    WITHOUT opening a full manager (no async machinery, nothing created
    on disk).  Used by the CLI to derive resume offsets (e.g. the
    sampled stream's starting chunk) before the training loop restores.

    Candidate all-digit dirs are validated with the same commit test
    orbax's ``latest_step()`` applies (ADVICE r5): after an interrupted
    save the newest dir can be uncommitted, and trusting it would derive
    ``start_chunk`` from a newer step than the one the loop actually
    restores — chunks skipped, consumed-batch accounting drifting from
    the restored step.  Uncommitted candidates are skipped in favor of
    the next older committed one."""
    step = _latest_committed_step(os.path.abspath(directory))
    return 0 if step is None else step


def reproject_params(tags, params):
    """Build a ``project`` fn argument from a manifold tag tree: re-projects
    every manifold-tagged leaf, passes Euclidean leaves through."""
    from hyperspace_tpu.optim.tags import map_tagged

    def apply(tree):
        return map_tagged(
            lambda t, p: p if t is None else t.proj(p), tags, tree)

    return apply
