"""Riemannian SGD as an optax-compatible gradient transformation.

Semantics per Bonnabel 2013 / Nickel & Kiela 2017 (SURVEY.md §2): the
Euclidean gradient is rescaled by the inverse metric (``egrad2rgrad``), the
step is taken with the exponential map (or a cheap first-order retraction),
and the point is re-projected.  This runs entirely inside one jitted train
step — the BASELINE.json requirement "Riemannian SGD ... runnable as a
single XLA-compiled train step".

optax compatibility trick: the transform computes the *new point on the
manifold* internally and emits ``new_point - old_point`` as the update, so
``optax.apply_updates`` (a plain add) reconstructs it exactly.  Chaining with
schedules works via the ``learning_rate`` schedule argument.

Sparse embedding batches (SURVEY.md §7 hard-part #2): JAX autodiff of a
gather produces a scatter-add cotangent — rows outside the batch carry a zero
Euclidean gradient, get a zero tangent, and ``expmap(x, 0) = x`` leaves them
bit-identical.  Duplicate rows in a batch sum their cotangents *before* the
metric rescale, i.e. tangents combine at the same base point, which is the
correct Riemannian accumulation.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from hyperspace_tpu.optim.common import ScalarOrSchedule, lr_at
from hyperspace_tpu.optim.tags import map_tagged


class RSGDState(NamedTuple):
    count: jax.Array


def riemannian_sgd(
    learning_rate: ScalarOrSchedule,
    tags: Any,
    *,
    use_expmap: bool = True,
    burnin_steps: int = 0,
    burnin_factor: float = 0.1,
) -> optax.GradientTransformation:
    """Riemannian SGD.

    Args:
      learning_rate: scalar or optax schedule.
      tags: pytree matching the params; leaves are Manifold or None.
      use_expmap: exact exponential-map update if True, else retraction.
      burnin_steps / burnin_factor: Nickel & Kiela 2017 burn-in — the first
        ``burnin_steps`` use ``lr * burnin_factor`` (angular layout settles
        before radii grow).
    """

    def init_fn(params):
        del params
        return RSGDState(count=jnp.zeros((), jnp.int32))

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("riemannian_sgd requires params")
        lr = lr_at(learning_rate, state.count)
        if burnin_steps > 0:
            lr = jnp.where(state.count < burnin_steps, lr * burnin_factor, lr)

        def one(tag, g, p):
            if tag is None:
                return -lr * g
            rg = tag.egrad2rgrad(p, g)
            step = -lr * rg
            # expmap/retr already end in proj() on every manifold — one
            # projection site, no re-projection here.
            new_p = tag.expmap(p, step) if use_expmap else tag.retr(p, step)
            return new_p - p

        updates = map_tagged(one, tags, grads, params)
        return updates, RSGDState(count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)
