"""Manifold tagging for parameter pytrees.

The reference framework marks manifold-valued tensors so one optimizer can
handle mixed Euclidean/manifold parameter sets (geoopt's ManifoldParameter
pattern; SURVEY.md §2 "ManifoldParam tagging").  Here a *tag tree* is a
pytree with the same structure as the params whose leaves are either a
``Manifold`` instance or ``None`` (= Euclidean).  Tag trees ride through
``jax.jit`` because manifolds are pytrees themselves.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

from hyperspace_tpu.manifolds.base import Manifold


def is_tag(x: Any) -> bool:
    return x is None or isinstance(x, Manifold)


def map_tagged(fn: Callable, tags, *trees):
    """tree_map over (tag, *leaves) treating each manifold tag as one leaf.

    ``fn(tag, *leaves)`` is called per parameter leaf; ``tag`` is a Manifold
    or None.
    """
    return jax.tree_util.tree_map(fn, tags, *trees, is_leaf=is_tag)


def tags_from_paths(params, rule: Callable[[tuple], Any]):
    """Build a tag tree from a path-based rule.

    ``rule`` receives the jax key-path tuple of each leaf and returns a
    Manifold or None.  This is how flax models declare which of their params
    live on a manifold (path/name-based, no special parameter class needed).
    """
    return jax.tree_util.tree_map_with_path(lambda p, _: rule(p), params)


def path_contains(path, name: str) -> bool:
    """True if any path entry (DictKey/GetAttrKey/...) matches ``name``."""
    for entry in path:
        key = getattr(entry, "key", getattr(entry, "name", None))
        if key == name:
            return True
    return False
