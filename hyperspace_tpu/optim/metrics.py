"""Per-chunk metric accumulation for the chunked-dispatch loop.

A chunked stepper (train/loop.make_chunked_stepper) returns the stacked
``[K]`` per-step losses of one dispatch.  Fetching each to host per step
would reintroduce exactly the per-step host round-trip the chunking
removed, so the loop accumulates the DEVICE arrays and reduces them with
ONE host fetch per log boundary.  Holding the references is safe: only
the carried train state is donated; loss outputs are fresh buffers the
next dispatch never aliases.
"""

from __future__ import annotations


class ChunkMetrics:
    """Accumulate chunk loss arrays; ``flush()`` = stats since last flush.

    ``add`` takes whatever the stepper returned as its loss — a scalar
    (K=1) or a stacked ``[K]`` device array — and does NOT synchronize;
    the one device→host transfer happens in ``flush``.
    """

    def __init__(self):
        self._chunks = []

    def add(self, losses) -> None:
        self._chunks.append(losses)

    def flush(self):
        """Reduce every step added since the previous flush with ONE
        host fetch: ``{"loss_mean", "loss_last", "loss_min",
        "loss_max"}`` over the interval (the JSONL field names), or
        None when nothing was added.  mean smooths the noisy per-step
        loss; last is the value a single-step loop would have logged;
        min/max bound the interval — a spiking max with a flat mean is
        the early divergence signature the mean alone hides."""
        if not self._chunks:
            return None
        import numpy as np

        vals = np.concatenate(
            [np.atleast_1d(np.asarray(c)) for c in self._chunks])
        self._chunks.clear()
        return {"loss_mean": float(vals.mean()),
                "loss_last": float(vals[-1]),
                "loss_min": float(vals.min()),
                "loss_max": float(vals.max())}
