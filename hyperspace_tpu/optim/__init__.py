from hyperspace_tpu.optim.metrics import ChunkMetrics
from hyperspace_tpu.optim.radam import riemannian_adam
from hyperspace_tpu.optim.rsgd import riemannian_sgd
from hyperspace_tpu.optim.tags import map_tagged, path_contains, tags_from_paths

__all__ = [
    "ChunkMetrics",
    "riemannian_adam",
    "riemannian_sgd",
    "map_tagged",
    "path_contains",
    "tags_from_paths",
]
