"""Gradient accumulation for the minibatch trainers.

The reference family's DDP trainer grows its effective batch past device
memory by accumulating microbatch gradients between optimizer updates
[INFERRED — SURVEY.md §1a "Distributed trainer"]; the optax-native
equivalent is ``optax.MultiSteps``: every k-th ``update`` applies the
inner transform to the mean of the last k gradients, the others emit
zero updates.  This wrapper exists so every workload wires it the same
way (CLI ``accum=N``) and so the optimizer state is rebuilt consistently
— a wrapped transform has a different state pytree, so the old state
must be discarded, never reused.
"""

from __future__ import annotations

import optax


def with_grad_accumulation(opt: optax.GradientTransformation, params,
                           every_k: int):
    """Return ``(wrapped_opt, fresh_opt_state)`` accumulating ``every_k``
    microbatch gradients per optimizer update (k <= 1: unchanged opt,
    fresh state)."""
    if every_k <= 1:
        return opt, opt.init(params)
    wrapped = optax.MultiSteps(opt, every_k_schedule=every_k)
    return wrapped, wrapped.init(params)
