"""Riemannian Adam (Bécigneul & Ganea 2019) as an optax transformation.

BASELINE.json north star: "Riemannian SGD/Adam with its tangent-space
retraction runs as a single XLA-compiled train step".  Semantics
(SURVEY.md §2 "Riemannian Adam"):

- the Euclidean gradient is converted to a Riemannian gradient;
- the first moment is a *tangent vector* at the current point and is
  **parallel-transported** to the new point after every update, so it stays
  a valid tangent vector as the parameter moves (SURVEY.md §7 hard-part #4:
  moments live in tangent spaces of moving points);
- the second moment is the scalar Riemannian squared norm per parameter row
  (geoopt's default for manifolds without component structure), kept
  elementwise for Euclidean leaves so they reduce to standard Adam;
- the update point is ``expmap`` (or the cheap retraction), which already
  re-projects.

Like :mod:`hyperspace_tpu.optim.rsgd`, the transform emits
``new_point - old_point`` so ``optax.apply_updates`` reconstructs the
on-manifold point exactly, and the whole thing jits into one XLA program.

GSPMD note: all state tensors are elementwise-shaped like their parameter
(or a last-axis reduction of it), so any sharding rule that shards a param
shards its moments identically — moment shards stay co-located with their
parameter shards by construction.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from hyperspace_tpu.optim.common import ScalarOrSchedule, lr_at
from hyperspace_tpu.optim.tags import map_tagged


class RAdamState(NamedTuple):
    count: jax.Array
    mu: Any  # first moment: tangent vectors (manifold) / elementwise (None)
    nu: Any  # second moment: [..., 1] row-scalars (manifold) / elementwise


def riemannian_adam(
    learning_rate: ScalarOrSchedule,
    tags: Any,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    use_expmap: bool = True,
    stabilize_every: int = 0,
) -> optax.GradientTransformation:
    """Riemannian Adam.

    Args:
      learning_rate: scalar or optax schedule.
      tags: pytree matching the params; leaves are Manifold or None.
      b1, b2, eps: Adam constants.
      use_expmap: exact exponential-map update if True, else retraction
        (``proj(x + v)``) — the reference's "tangent-space retraction" mode.
      stabilize_every: if > 0, every that-many steps the new point is
        re-projected onto the manifold and the transported first moment
        onto its tangent space (geoopt's ``stabilize`` cadence,
        SURVEY.md §2 "Riemannian Adam") — counters float drift off the
        constraint surface over long runs without paying the projection
        on every step.
    """

    def init_fn(params):
        mu = map_tagged(lambda t, p: jnp.zeros_like(p), tags, params)
        nu = map_tagged(
            lambda t, p: jnp.zeros_like(p) if t is None
            else jnp.zeros(p.shape[:-1] + (1,), p.dtype),
            tags, params,
        )
        return RAdamState(count=jnp.zeros((), jnp.int32), mu=mu, nu=nu)

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("riemannian_adam requires params")
        count = state.count + 1
        lr = lr_at(learning_rate, state.count)
        ftype = jnp.result_type(float)  # f64 under x64, f32 on TPU
        c1 = 1.0 - b1 ** count.astype(ftype)
        c2 = 1.0 - b2 ** count.astype(ftype)
        do_stab = (
            (count % stabilize_every == 0) if stabilize_every > 0 else None
        )

        def one(tag, g, p, mu, nu):
            if tag is None:
                mu_n = b1 * mu + (1.0 - b1) * g
                nu_n = b2 * nu + (1.0 - b2) * g * g
                step = -lr * (mu_n / c1) / (jnp.sqrt(nu_n / c2) + eps)
                return step, mu_n, nu_n
            rg = tag.egrad2rgrad(p, g)
            mu_n = b1 * mu + (1.0 - b1) * rg
            nu_n = b2 * nu + (1.0 - b2) * tag.inner(p, rg, rg, keepdims=True)
            nu_n = jnp.maximum(nu_n, 0.0)  # Lorentz inner can go −0.0-ish
            direction = (mu_n / c1) / (jnp.sqrt(nu_n / c2) + eps)
            step = -lr * direction
            new_p = tag.expmap(p, step) if use_expmap else tag.retr(p, step)
            # transport the first moment to the new point's tangent space
            mu_t = tag.ptransp(p, new_p, mu_n)
            if do_stab is not None:
                # lax.cond (not where): projection work is actually skipped
                # on the non-stabilize steps
                def _stab(args):
                    q, v = args
                    q = tag.proj(q)
                    return q, tag.proju(q, v)

                new_p, mu_t = jax.lax.cond(
                    do_stab, _stab, lambda a: a, (new_p, mu_t))
            return new_p - p, mu_t, nu_n

        out = map_tagged(one, tags, grads, params, state.mu, state.nu)
        updates = map_tagged(lambda t, x: x[0], tags, out)
        mu = map_tagged(lambda t, x: x[1], tags, out)
        nu = map_tagged(lambda t, x: x[2], tags, out)
        return updates, RAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)
