"""Shared optimizer plumbing (schedule resolution)."""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

ScalarOrSchedule = Union[float, Callable[[jax.Array], jax.Array]]


def lr_at(learning_rate: ScalarOrSchedule, count: jax.Array) -> jax.Array:
    """Resolve a constant-or-schedule learning rate at a step count."""
    if callable(learning_rate):
        return learning_rate(count)
    return jnp.asarray(learning_rate)
