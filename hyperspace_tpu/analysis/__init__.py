"""hyperlint — AST-based static analysis for this repo's JAX/TPU hazards.

    python -m hyperspace_tpu.analysis                 # lint the default set
    python -m hyperspace_tpu.analysis pkg file.py     # lint specific paths
    python -m hyperspace_tpu.analysis --json          # findings artifact
    python -m hyperspace_tpu.analysis --list-rules

One parse per file, a Rule registry (docs/static-analysis.md has the
catalog), per-line ``# hyperlint: disable=<rule> — reason`` suppressions,
human and JSON output.  The rules encode this repo's own incident
history: recompile storms, donated-buffer reads, host syncs on the hot
path, tracer leaks, alarm-swallowing handlers, bf16 policy leaks, and
catalog/doc drift.  Run by ``tests/analysis/`` inside tier-1, so the
tree cannot merge dirty.
"""

from hyperspace_tpu.analysis.core import (  # noqa: F401
    Finding,
    Report,
    Rule,
    default_rules,
    lint_file,
    lint_paths,
    make_context,
    repo_root,
)
from hyperspace_tpu.analysis.rules import ALL_RULES, RULES_BY_ID  # noqa: F401
