"""telemetry-catalog: every registry name in code is documented.

Migrated from ``scripts/check_telemetry_catalog.py`` (PR 2/PR 4): the
counter catalog in docs/observability.md is the contract dashboards and
the bench read; an undocumented counter is invisible telemetry, and a
typo'd READ (``get("ns/nmae")`` silently returning 0) is worse.  The
script path remains as a shim over this rule.

AST-accurate version of the same scan, over every package file plus the
repo-root ``bench.py``:

- writes: ``inc("name")`` / ``set_gauge("name")`` / ``observe("name",
  v)`` calls (any receiver — ``observe`` is the histogram kind added in
  PR 7; a ``Histogram().observe(value)`` instance call has no string
  first argument and stays out);
- reads: ``get("ns/name")`` calls whose literal first argument carries a
  ``/`` (every registry name is namespaced; plain dict ``.get("key")``
  stays out) — including ``hist/<name>`` snapshot-entry reads;
- the ``# telemetry-catalog: name`` escape for dynamically-built names.

Each name must appear as a backticked token in docs/observability.md.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from hyperspace_tpu.analysis.core import (FileContext, ProjectContext, Rule,
                                          make_context)

DOC_REL = "docs/observability.md"
_ANNOT_RX = re.compile(r"#\s*telemetry-catalog:\s*(\S+)")
_WRITE_FNS = {"inc", "set_gauge", "observe"}

# line-based fallback for text the AST cannot parse (the shim must not
# silently drop a mid-refactor file's names — the old scanner was
# line-based and caught them)
_FALLBACK_WRITE_RX = re.compile(
    r"\b(?:inc|set_gauge|observe)\(\s*[\"']([^\"']+)[\"']")
_FALLBACK_READ_RX = re.compile(r"\bget\(\s*[\"']([^\"' ]*/[^\"' ]*)[\"']")


def names_in_text(text: str, rel: str) -> dict[str, list[str]]:
    """Regex scan of raw text — the pre-AST behavior, kept as the
    unparseable-file fallback for :func:`counters_in_code`."""
    found: dict[str, list[str]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        for rx in (_FALLBACK_WRITE_RX, _FALLBACK_READ_RX, _ANNOT_RX):
            for m in rx.finditer(line):
                found.setdefault(m.group(1), []).append(f"{rel}:{lineno}")
    return found


def _call_fn_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def names_in_context(ctx: FileContext) -> dict[str, list[str]]:
    """{registry name: ["rel:line", ...]} for one parsed file."""
    found: dict[str, list[str]] = {}

    def add(name: str, lineno: int) -> None:
        found.setdefault(name, []).append(f"{ctx.rel}:{lineno}")

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        fn = _call_fn_name(node)
        if fn in _WRITE_FNS:
            add(first.value, node.lineno)
        elif (fn == "get" and "/" in first.value
              and " " not in first.value):
            add(first.value, node.lineno)
    for lineno, comment in ctx.comments.items():
        for m in _ANNOT_RX.finditer(comment):
            add(m.group(1), lineno)
    return found


def documented_names(doc_text: str) -> set[str]:
    """Names carried in the catalog doc (any backticked token)."""
    return set(re.findall(r"`([^`\s]+)`", doc_text))


def _merge(into: dict[str, list[str]], more: dict[str, list[str]]) -> None:
    for k, v in more.items():
        into.setdefault(k, []).extend(v)


class TelemetryCatalogRule(Rule):
    id = "telemetry-catalog"
    severity = "error"
    summary = ("registry counter/gauge/histogram names (writes AND "
               "namespaced reads) missing from docs/observability.md")

    def check_project(self, proj: ProjectContext):
        # the analysis package is exempt (its docstrings/messages name
        # the very tokens this rule hunts — same reason scripts/ was
        # never self-scanned)
        scanned = [c for c in proj.contexts
                   if (c.rel.startswith("hyperspace_tpu/")
                       and not c.rel.startswith("hyperspace_tpu/analysis/"))
                   or c.rel == "bench.py"]
        if not scanned:
            return []
        doc = proj.read_doc(DOC_REL)
        if doc is None:
            return [self.finding(scanned[0], 1,
                                 f"missing catalog doc: {DOC_REL}")]
        documented = documented_names(doc)
        found: dict[str, list[str]] = {}
        for ctx in scanned:
            _merge(found, names_in_context(ctx))
        findings = []
        by_rel = {c.rel: c for c in scanned}
        for name in sorted(found):
            if name in documented:
                continue
            rel, _, line = found[name][0].partition(":")
            ctx = by_rel[rel]
            findings.append(self.finding(
                ctx, int(line),
                f"telemetry name {name!r} is used in code but missing "
                f"from {DOC_REL}'s catalog — add its row (or the "
                "`# telemetry-catalog: <name>` escape for dynamic "
                "names)"))
        return findings


# --- script-shim API (scripts/check_telemetry_catalog.py) --------------------


def counters_in_code(pkg_dir: str) -> dict[str, list[str]]:
    """Legacy contract: scan every .py under ``pkg_dir`` plus the
    sibling ``bench.py``; rel paths from the package's parent."""
    root = os.path.dirname(os.path.abspath(pkg_dir))
    found: dict[str, list[str]] = {}
    paths = []
    for dirpath, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in dirpath:
            continue
        paths += [os.path.join(dirpath, n) for n in sorted(files)
                  if n.endswith(".py")]
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        if rel.startswith("hyperspace_tpu/analysis/"):
            continue  # self-exempt, as check_project (lint code names
            # the tokens it hunts)
        try:
            ctx = make_context(path, root=root)
        except SyntaxError:
            with open(path, encoding="utf-8") as f:
                _merge(found, names_in_text(f.read(), rel))
            continue
        _merge(found, names_in_context(ctx))
    return found
