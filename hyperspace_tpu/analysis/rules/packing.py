"""packing-literal: nibble pack/unpack idioms stay in quant.py/kernels/.

Historical incident: ISSUE 16's int4 lane stores two's-complement
nibbles in a PLANAR layout (byte ``j`` = element ``j`` low, element
``ceil(D/2)+j`` high — serve/quant.py).  During development the traced
unpack was hand-copied into the engine's two-stage scan with raw
``& 15`` / ``>> 4`` literals; any third copy that drifts (interleaved
order, missed sign extension, ``0xF0`` mask without the shift) decodes
a VALID-looking table into garbage coordinates — no crash, just wrong
neighbors.  The layout is load-bearing and must have exactly two
implementations: ``serve/quant.py`` (host + traced twins) and
``kernels/scan_topk.py`` (in-register tile unpack).

Flagged in any other package file:

- a ``&`` whose either operand is the literal ``0xF`` (15) or ``0xF0``
  (240) — the nibble masks.  ``0xFF`` (255) is a BYTE mask and never
  fires (``data/mnist.py``'s IDX-header ``magic & 0xFF`` is legitimate);
- ``x >> 4`` / ``x << 4`` where ``x`` is not itself a constant — the
  nibble shifts (pure constant arithmetic like ``1 << 4`` never fires).

Escape: ``# hyperlint: disable=packing-literal — reason``; the fix is
usually to call ``serve/quant.py``'s ``pack_int4_rows`` /
``unpack_int4_rows`` / ``unpack_int4_jnp`` instead.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

ALLOWED_FILE = "hyperspace_tpu/serve/quant.py"
ALLOWED_DIR = "hyperspace_tpu/kernels/"

# the two nibble masks; 0xFF deliberately absent
_NIBBLE_MASKS = (0xF, 0xF0)


def in_scope(rel: str) -> bool:
    """Package-scoped like precision-literal: the analysis package is
    self-exempt (lint code names the tokens it hunts)."""
    rel = rel.replace("\\", "/")
    if not rel.startswith("hyperspace_tpu/"):
        return False
    if rel.startswith("hyperspace_tpu/analysis/"):
        return False
    return rel != ALLOWED_FILE and not rel.startswith(ALLOWED_DIR)


def _is_const_int(node: ast.AST, values=None) -> bool:
    return (isinstance(node, ast.Constant)
            and type(node.value) is int
            and (values is None or node.value in values))


def _packing_nodes(ctx: FileContext):
    """(node, what) per nibble pack/unpack idiom in the tree."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.BinOp):
            continue
        if isinstance(node.op, ast.BitAnd):
            for side in (node.left, node.right):
                if _is_const_int(side, _NIBBLE_MASKS):
                    yield node, f"nibble mask `& {side.value:#x}`"
                    break
        elif isinstance(node.op, (ast.RShift, ast.LShift)):
            if (_is_const_int(node.right, (4,))
                    and not isinstance(node.left, ast.Constant)):
                tok = ">>" if isinstance(node.op, ast.RShift) else "<<"
                yield node, f"nibble shift `{tok} 4`"


class PackingLiteralRule(Rule):
    id = "packing-literal"
    severity = "error"
    summary = ("raw int4 nibble pack/unpack idiom outside "
               "serve/quant.py/kernels/ — the planar layout must not fork")

    def check_file(self, ctx: FileContext):
        if not in_scope(ctx.rel):
            return []
        findings = []
        for node, what in _packing_nodes(ctx):
            findings.append(self.finding(
                ctx, node,
                f"{what} outside the int4 packing boundary — call "
                "serve/quant.py's pack_int4_rows/unpack_int4_rows/"
                "unpack_int4_jnp (or the kernel's tile unpack) instead "
                "of re-deriving the planar nibble layout"))
        return findings
