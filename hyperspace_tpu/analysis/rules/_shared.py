"""AST helpers shared by the JAX-hazard rules (jit/scan region finding)."""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from hyperspace_tpu.analysis.core import FileContext

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)


def is_jit_name(resolved: Optional[str]) -> bool:
    """Whether a resolved dotted name is the jax.jit entry point."""
    return resolved in ("jax.jit", "jax.pjit") or (
        resolved is not None and resolved.endswith((".jax.jit", ".pjit")))


def is_scan_name(resolved: Optional[str]) -> bool:
    return resolved is not None and (
        resolved == "jax.lax.scan" or resolved.endswith("lax.scan"))


def jit_call_target(ctx: FileContext, call: ast.Call) -> bool:
    return isinstance(call, ast.Call) and is_jit_name(ctx.resolve(call.func))


def partial_jit_decorator(ctx: FileContext, dec: ast.AST) -> Optional[ast.Call]:
    """The ``partial(jax.jit, ...)`` call node when ``dec`` is one."""
    if (isinstance(dec, ast.Call) and ctx.resolve(dec.func) in
            ("functools.partial", "partial") and dec.args
            and is_jit_name(ctx.resolve(dec.args[0]))):
        return dec
    return None


def jitted_defs(ctx: FileContext) -> dict[str, ast.FunctionDef]:
    """{name: def} for functions that become jitted programs: decorated
    with ``jax.jit`` / ``partial(jax.jit, ...)``, wrapped by name in a
    ``jax.jit(name, ...)`` call, or passed as a ``lax.scan`` body."""
    defs: dict[str, ast.FunctionDef] = {}
    by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                if (is_jit_name(ctx.resolve(dec))
                        or partial_jit_decorator(ctx, dec) is not None):
                    defs[node.name] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func)
        wraps = (is_jit_name(resolved) or is_scan_name(resolved))
        if wraps and node.args and isinstance(node.args[0], ast.Name):
            for fd in by_name.get(node.args[0].id, ()):
                defs[fd.name] = fd
    return defs


def scan_body_nodes(ctx: FileContext) -> list[ast.AST]:
    """The function bodies (defs or lambdas) passed to ``lax.scan``."""
    by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    out: list[ast.AST] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and is_scan_name(ctx.resolve(node.func)) and node.args):
            continue
        fn = node.args[0]
        if isinstance(fn, ast.Lambda):
            out.append(fn)
        elif isinstance(fn, ast.Name):
            out.extend(by_name.get(fn.id, ()))
    return out


def walk_scope(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk a scope's own statements without descending into nested
    function/class/lambda scopes (their names are not this scope's)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(node))


def scopes(ctx: FileContext) -> Iterator[ast.AST]:
    """The module plus every function def (scopes for name tracking)."""
    yield ctx.tree
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def const_str_tuple(node: ast.AST) -> tuple[str, ...]:
    """String constants inside a tuple/list/single-constant node."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def const_int_tuple(node: ast.AST) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


UNHASHABLE_LITERALS = (ast.Dict, ast.List, ast.Set, ast.ListComp,
                       ast.SetComp, ast.DictComp)


def unhashable_kind(node: ast.AST) -> Optional[str]:
    """'dict'/'list'/'set' when ``node`` is an unhashable literal (or a
    bare dict()/list()/set() constructor call)."""
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in ("dict", "list", "set")):
        return node.func.id
    return None
