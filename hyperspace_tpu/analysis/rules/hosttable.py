"""full-table-materialization: device transfer of a host master table.

Historical incident class this PR makes structural: the beyond-HBM
story (ROADMAP item 3, ``parallel/host_table.py``) rests on ONE
invariant — the master embedding table lives in host memory and visits
the device only as bounded blocks (the hot-row cache's bucketed
uploads, the streamed index builder's ``[chunk, D]`` tiles).  A single
``jnp.asarray(master.to_array())`` in a hot path silently re-caps the
whole design at one chip's HBM — and it compiles, runs, and passes
small-table tests, which is exactly the kind of hazard this suite
exists to catch at lint time.

What fires (error): a call to ``jax.device_put`` / ``jnp.asarray``
(import-alias resolved) whose transferred operand is

- a ``HostEmbedTable`` construction — ``HostEmbedTable(...)`` or its
  classmethod constructors (``from_array`` / ``build`` /
  ``load_sharded``), bare or dotted;
- a ``.to_array()`` call — :meth:`HostEmbedTable.to_array` is the
  sanctioned full-table materializer for small-table eval paths, and
  shipping its result to device is the whole-table transfer;
- a name bound from either (one-step taint, tracked file-wide in
  SOURCE order like the materialized-distmat rule: latest binding
  before the call wins, rebinding to anything else clears it).

What stays clean: streamed blocks (``iter_chunks`` tiles,
``gather``-ed row batches) — bounded by construction — and everything
inside ``parallel/host_table.py`` itself, the one sanctioned home of
master→device transfers (the hot-row cache's uploads live there).

Fix: route rows through ``DeviceHotCache.ensure`` (training) or
``HostEmbedTable.iter_chunks`` (streaming builds); a deliberate
small-table exit documents itself with the per-line suppression.
"""

from __future__ import annotations

import ast
from typing import Optional

from hyperspace_tpu.analysis.core import FileContext, Rule

_TRANSFERS = ("jax.device_put", "jnp.asarray", "jax.numpy.asarray")
_CONSTRUCTORS = ("from_array", "build", "load_sharded")

# the hot-cache module: the one file allowed to move master rows to
# device (bucketed, bounded) — and the table class's own home
_EXEMPT_SUFFIX = "parallel/host_table.py"


def _basename(resolved: Optional[str]) -> str:
    return (resolved or "").rsplit(".", 1)[-1]


def _is_master_source(ctx: FileContext, node: ast.AST) -> bool:
    """A HostEmbedTable construction, or a ``.to_array()`` call."""
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func) or ""
    parts = resolved.split(".")
    if "HostEmbedTable" in parts:
        # HostEmbedTable(...) or HostEmbedTable.from_array/... — both
        # hand back the host master object
        return parts[-1] == "HostEmbedTable" or parts[-1] in _CONSTRUCTORS
    if isinstance(node.func, ast.Attribute) and node.func.attr == "to_array":
        return True
    return False


def _transferred_operand(node: ast.Call) -> Optional[ast.AST]:
    if node.args:
        return node.args[0]
    return None


class FullTableMaterializationRule(Rule):
    id = "full-table-materialization"
    severity = "error"
    summary = ("jax.device_put / jnp.asarray of a host master table "
               "(HostEmbedTable / .to_array()) outside "
               "parallel/host_table.py — the beyond-HBM invariant: "
               "stream chunks or go through DeviceHotCache")

    def check_file(self, ctx: FileContext):
        rel = ctx.rel.replace("\\", "/")
        if rel.endswith(_EXEMPT_SUFFIX):
            return []
        findings = []
        # one-step name taint in SOURCE order (the materialized-distmat
        # pass structure: ast.walk is breadth-first, so events must be
        # re-sorted or a nested function's later rebind would clear a
        # module-level taint out of order)
        events = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                events.append((node.lineno, node.col_offset, "assign",
                               node))
            elif (isinstance(node, ast.Call)
                  and ctx.resolve(node.func) in _TRANSFERS):
                events.append((node.lineno, node.col_offset, "xfer", node))
        tainted: dict[str, int] = {}
        for _, _, kind, node in sorted(events, key=lambda e: (e[0], e[1])):
            if kind == "assign":
                tgt = node.targets[0]
                if _is_master_source(ctx, node.value):
                    tainted[tgt.id] = node.lineno
                else:
                    tainted.pop(tgt.id, None)
                continue
            arg = _transferred_operand(node)
            if arg is None:
                continue
            hit = _is_master_source(ctx, arg) or (
                isinstance(arg, ast.Name) and arg.id in tainted)
            if hit:
                findings.append(self.finding(
                    ctx, node,
                    "host master table shipped to device whole — the "
                    "beyond-HBM design caps device residency at the "
                    "hot-row cache / streamed chunks; use "
                    "DeviceHotCache.ensure or iter_chunks "
                    "(parallel/host_table.py), or suppress a "
                    "documented small-table exit"))
        return findings
