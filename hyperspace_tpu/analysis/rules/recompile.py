"""recompile-hazard: jit wrappers built per call/iteration, and
unhashable values reaching static args.

Historical incident: the PR 3 serving engine exists because per-request
recompiles were the serving failure mode — its ``jax/recompiles``
contract (one compile per (bucket, k), zero steady-state) is tested.
The hazards this rule catches are the ways that contract quietly breaks:

- ``jax.jit(...)`` inside a ``for``/``while`` loop: a fresh wrapper per
  iteration — at best a cache lookup per step on the hot path, at worst
  a recompile per iteration when anything in the closure differs;
- ``jax.jit(f)(...)`` built and invoked in one expression inside a
  function: the wrapper is discarded after the call, so every call pays
  wrapper construction + cache lookup (and recompiles whenever ``f`` is
  a fresh closure object);
- a ``static_argnames``/``static_argnums`` parameter whose default (or a
  call-site value) is a dict/list/set: statics must hash — unhashable
  values raise, and per-call-distinct hashables retrace every call.

Factory functions that BUILD and RETURN a jitted callable once (the
``make_*_step`` idiom everywhere in this repo) are fine and not flagged.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule
from hyperspace_tpu.analysis.rules._shared import (
    const_int_tuple, const_str_tuple, is_jit_name, partial_jit_decorator,
    unhashable_kind)

_LOOPS = (ast.For, ast.While, ast.AsyncFor)


def _static_kwargs(call: ast.Call) -> tuple[tuple[str, ...], tuple[int, ...]]:
    names: tuple[str, ...] = ()
    nums: tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = const_str_tuple(kw.value)
        elif kw.arg == "static_argnums":
            nums = const_int_tuple(kw.value)
    return names, nums


def _param_default(fd: ast.FunctionDef, name: str):
    """The default-value node for parameter ``name``, or None."""
    args = fd.args
    pos = args.posonlyargs + args.args
    n_def = len(args.defaults)
    for i, a in enumerate(pos):
        if a.arg == name:
            j = i - (len(pos) - n_def)
            return args.defaults[j] if j >= 0 else None
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if a.arg == name:
            return d
    return None


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    severity = "error"
    summary = ("jax.jit built per call/loop iteration, or unhashable "
               "dict/list/set values on static args")

    def check_file(self, ctx: FileContext):
        findings = []
        defs_by_name = {n.name: n for n in ast.walk(ctx.tree)
                        if isinstance(n, ast.FunctionDef)}
        # {callable name: static argnames} for call-site value checks
        static_fns: dict[str, tuple[str, ...]] = {}

        def check_static_spec(call: ast.Call, fd: ast.FunctionDef | None):
            names, nums = _static_kwargs(call)
            if fd is None:
                return names
            params = ([a.arg for a in fd.args.posonlyargs + fd.args.args]
                      + [a.arg for a in fd.args.kwonlyargs])
            static_names = list(names)
            for i in nums:
                if 0 <= i < len(params):
                    static_names.append(params[i])
            for p in static_names:
                kind = unhashable_kind(_param_default(fd, p))
                if kind is not None:
                    findings.append(self.finding(
                        ctx, _param_default(fd, p),
                        f"static arg {p!r} of {fd.name!r} defaults to a "
                        f"{kind} — statics must be hashable: every call "
                        "either raises or retraces (use a tuple or move "
                        "it out of the statics)"))
            return tuple(static_names)

        for node in ast.walk(ctx.tree):
            # decorated defs: @jax.jit / @partial(jax.jit, static_*=...)
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    pj = partial_jit_decorator(ctx, dec)
                    if pj is not None:
                        static_fns[node.name] = check_static_spec(pj, node)
                    elif (isinstance(dec, ast.Call)
                          and is_jit_name(ctx.resolve(dec.func))):
                        static_fns[node.name] = check_static_spec(dec, node)
                continue
            if not (isinstance(node, ast.Call)
                    and is_jit_name(ctx.resolve(node.func))):
                continue
            # jax.jit(fn, static_*=...) call form
            fd = None
            if node.args and isinstance(node.args[0], ast.Name):
                fd = defs_by_name.get(node.args[0].id)
            statics = check_static_spec(node, fd)
            parent = ctx.parents.get(id(node))
            if (isinstance(parent, ast.Assign)
                    and len(parent.targets) == 1
                    and isinstance(parent.targets[0], ast.Name)):
                static_fns[parent.targets[0].id] = statics
            # jit under a loop: fresh wrapper per iteration
            loop = next((a for a in ctx.ancestors(node)
                         if isinstance(a, _LOOPS)), None)
            if loop is not None:
                findings.append(self.finding(
                    ctx, node,
                    "jax.jit inside a loop builds a fresh wrapper every "
                    "iteration (cache lookup per step; recompile when the "
                    "closure differs) — hoist it to module/__init__ "
                    "scope or build it once before the loop"))
                continue
            # jax.jit(f)(...) immediate invocation inside a function
            if (isinstance(parent, ast.Call) and parent.func is node
                    and any(isinstance(a, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))
                            for a in ctx.ancestors(node))):
                findings.append(self.finding(
                    ctx, node,
                    "jax.jit(f)(...) builds and discards the jitted "
                    "wrapper on every call — bind it once (module scope "
                    "or a factory) so the compile cache can do its job",
                    severity="warning"))
        # call sites passing unhashable literals for known static args
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in static_fns):
                continue
            for kw in node.keywords:
                if kw.arg in static_fns[node.func.id]:
                    kind = unhashable_kind(kw.value)
                    if kind is not None:
                        findings.append(self.finding(
                            ctx, kw.value,
                            f"{kind} passed for static arg {kw.arg!r} of "
                            f"jitted {node.func.id!r} — unhashable "
                            "statics raise or retrace per call; pass a "
                            "tuple"))
        return findings
