"""swallow-base-exception: handlers that can eat alarms and errors.

Historical incident: PR 4's bench watchdog.  The per-leg SIGALRM
deadline raises inside whatever code is running — and the benched code
is full of defensive ``except Exception`` blocks.  A handler broad
enough to catch the alarm swallowed it once, and the leg ran unbounded
with the alarm already spent; ``_LegTimeout`` had to become a
``BaseException`` subclass to get past them (bench.py).

Two shapes are flagged:

- **error** — ``except BaseException`` or a bare ``except:`` whose body
  neither re-raises nor uses the caught exception: this swallows
  ``KeyboardInterrupt``, ``SystemExit``, and the bench's ``_LegTimeout``
  alarm outright.  Cleanup-and-reraise (``except BaseException: ...;
  raise``) is the legitimate form and is not flagged.
- **warning** — ``except Exception`` (or a tuple containing it) whose
  body is SILENT (only ``pass``/``continue``/``break``): real failures
  vanish without a trace.  Handlers that log, build an error record, or
  reference the caught exception are considered handled.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

_BROADEST = {"BaseException"}
_BROAD = {"Exception"}


def _caught_names(handler: ast.ExceptHandler) -> set[str]:
    """Leaf class names this handler catches ('' for a bare except)."""
    t = handler.type
    if t is None:
        return {""}
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for n in nodes:
        if isinstance(n, ast.Attribute):  # e.g. builtins.BaseException
            out.add(n.attr)
        elif isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _has_raise(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _uses_caught(handler: ast.ExceptHandler) -> bool:
    if not handler.name:
        return False
    return any(isinstance(n, ast.Name) and n.id == handler.name
               for stmt in handler.body for n in ast.walk(stmt))


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # stray docstring/ellipsis
        return False
    return True


class SwallowBaseExceptionRule(Rule):
    id = "swallow-base-exception"
    severity = "error"
    summary = ("bare/BaseException handlers without re-raise; silent "
               "'except Exception: pass'")

    def check_file(self, ctx: FileContext):
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            caught = _caught_names(node)
            if caught & _BROADEST or "" in caught:
                if _has_raise(node) or _uses_caught(node):
                    continue
                what = ("bare `except:`" if "" in caught
                        else "`except BaseException`")
                findings.append(self.finding(
                    ctx, node,
                    f"{what} without re-raise swallows KeyboardInterrupt "
                    "/ SystemExit / the bench's _LegTimeout alarm (the "
                    "PR 4 watchdog bug class) — catch Exception, or "
                    "re-raise after cleanup"))
            elif caught & _BROAD:
                if _has_raise(node) or _uses_caught(node):
                    continue
                if _is_silent(node):
                    findings.append(self.finding(
                        ctx, node,
                        "silent `except Exception: pass` — real failures "
                        "vanish without a trace; narrow the exception "
                        "type, log, or re-raise (suppress with a reason "
                        "when best-effort really is the design)",
                        severity="warning"))
        return findings
