"""materialized-distmat: ``lax.top_k`` over a materialized distance matrix.

Historical incident: the hazard class PR 10 retired.  Before the fused
scan-top-k kernel (``kernels/scan_topk.py``), the obvious way to rank
neighbors was ``d = pdist(q, table, ...); lax.top_k(-d, k)`` — compute
the full [B, N] distance matrix, write it to HBM, read it back, sort.
At serve scale that materialization IS the latency (the scan is
HBM-bandwidth-bound, docs/kernels.md); the engine's chunked scans and
the fused kernel exist precisely so the full-table distance matrix
never lands in memory.  A new call site re-growing the pattern outside
``kernels/`` (where the tiled implementations legitimately sort their
own in-register tiles) should be caught at lint time.

What fires: a call to ``lax.top_k`` / ``jax.lax.top_k`` whose ranked
operand (directly, under unary ``-``, or via a name bound from one —
tracked file-wide in SOURCE order, latest binding before the call
wins: rebinding the name to anything else clears it) is

- a call to a pairwise-distance-matrix producer: ``pdist`` /
  ``poincare_pdist`` / ``lorentz_pdist`` / ``cdist`` (import-alias
  resolved, bare or dotted), or
- a ``.dist(...)`` call using the O(N²) broadcast idiom — two or more
  arguments each carrying a ``None``-axis subscript
  (``x[:, None, :]`` × ``y[None, :, :]``).

Chunked scans stay clean: their ``top_k`` operands come from tile
closures / stacked candidate buffers, not from a distmat producer.
Files under ``kernels/`` are out of scope (the fused kernels are the
sanctioned home of tile-level sorting).  Fix: route the ranking through
``serve/engine.py``'s chunked scans or ``kernels/scan_topk.py``.
"""

from __future__ import annotations

import ast
from typing import Optional

from hyperspace_tpu.analysis.core import FileContext, Rule

_PRODUCERS = ("pdist", "poincare_pdist", "lorentz_pdist", "cdist")
_TOPK = ("lax.top_k", "jax.lax.top_k")


def _basename(resolved: Optional[str]) -> str:
    return (resolved or "").rsplit(".", 1)[-1]


def _has_none_axis(node: ast.AST) -> bool:
    """Does the expression carry a ``[..., None, ...]`` subscript — the
    broadcast half of the pairwise-distance idiom?"""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Subscript):
            continue
        sl = sub.slice
        elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        for e in elts:
            if isinstance(e, ast.Constant) and e.value is None:
                return True
    return False


def _is_distmat_call(ctx: FileContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func)
    if _basename(resolved) in _PRODUCERS:
        return True
    # m.dist(x[:, None, :], y[None, :, :]) — the all-pairs broadcast
    if (isinstance(node.func, ast.Attribute) and node.func.attr == "dist"
            and sum(1 for a in node.args if _has_none_axis(a)) >= 2):
        return True
    return False


def _ranked_operand(node: ast.Call) -> Optional[ast.AST]:
    if not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub):
        return arg.operand
    return arg


class MaterializedDistmatRule(Rule):
    id = "materialized-distmat"
    severity = "warning"
    summary = ("lax.top_k over a materialized full-table distance "
               "matrix (pdist / broadcast .dist) outside kernels/ — "
               "use the chunked engine scans or kernels/scan_topk.py")

    def check_file(self, ctx: FileContext):
        rel = ctx.rel.replace("\\", "/")
        if "/kernels/" in f"/{rel}":
            return []
        findings = []
        # scope = the whole file: taint tracking is per assigned name,
        # one step deep (d = pdist(...); top_k(-d)) — redefinitions
        # overwrite, so a name rebound to something else goes clean.
        # Events are processed in SOURCE order (ast.walk is
        # breadth-first: a nested function's assigns would otherwise
        # clear/set taint out of order relative to module-level sites)
        events = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                events.append((node.lineno, node.col_offset, "assign",
                               node))
            elif (isinstance(node, ast.Call)
                  and ctx.resolve(node.func) in _TOPK):
                events.append((node.lineno, node.col_offset, "topk", node))
        tainted: dict[str, int] = {}
        for _, _, kind, node in sorted(events, key=lambda e: (e[0], e[1])):
            if kind == "assign":
                tgt = node.targets[0]
                if _is_distmat_call(ctx, node.value):
                    tainted[tgt.id] = node.lineno
                else:
                    tainted.pop(tgt.id, None)
                continue
            arg = _ranked_operand(node)
            if arg is None:
                continue
            hit = _is_distmat_call(ctx, arg) or (
                isinstance(arg, ast.Name) and arg.id in tainted)
            if hit:
                findings.append(self.finding(
                    ctx, node,
                    "lax.top_k ranks a materialized full-table distance "
                    "matrix — the [B, N] tile is written to and re-read "
                    "from HBM just to be sorted; stream it instead "
                    "(serve/engine.py chunked scans, or the fused "
                    "kernels/scan_topk.py kernel)"))
        return findings
