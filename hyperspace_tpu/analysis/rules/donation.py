"""donation-hazard: a donated buffer read again after the dispatch.

Historical incident: the PR 1 chunked stepper donates the carried train
state (``jax.jit(body, donate_argnums=(0,))``) — during that work, code
that kept using the OLD state object after a dispatch read deallocated
buffers.  XLA donation invalidates the argument's buffers at dispatch;
depending on backend/timing that read is an error, garbage, or silently
stale — the worst kind of bug.

The rule tracks, per scope: callables bound from a ``jax.jit(...)`` call
carrying ``donate_argnums``/``donate_argnames``, calls to them, and any
LATER read of a name that was passed in a donated slot without being
rebound first.  ``state = step(state)`` — the correct idiom — rebinds
the name at the call line and is clean.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule
from hyperspace_tpu.analysis.rules._shared import (
    const_int_tuple, const_str_tuple, is_jit_name, scopes, walk_scope)


def _donation_spec(call: ast.Call):
    """(argnums, argnames) from a jax.jit call, or None when not donating."""
    nums: tuple[int, ...] = ()
    names: tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = const_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            names = const_str_tuple(kw.value)
    return (nums, names) if (nums or names) else None


def _donated_arg_names(call: ast.Call, spec) -> list[str]:
    nums, names = spec
    out = []
    for i in nums:
        if 0 <= i < len(call.args) and isinstance(call.args[i], ast.Name):
            out.append(call.args[i].id)
    for kw in call.keywords:
        if kw.arg in names and isinstance(kw.value, ast.Name):
            out.append(kw.value.id)
    return out


def _assign_targets(node: ast.AST) -> set[str]:
    """Names a statement (re)binds."""
    out: set[str] = set()
    if isinstance(node, ast.Assign):
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        for n in ast.walk(node.target):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


class DonationHazardRule(Rule):
    id = "donation-hazard"
    severity = "error"
    summary = "name passed in a donate_argnums slot is read after dispatch"

    def check_file(self, ctx: FileContext):
        findings = []
        for scope in scopes(ctx):
            nodes = list(walk_scope(scope))
            # donating callables bound in this scope
            donors: dict[str, tuple] = {}
            for node in nodes:
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and is_jit_name(ctx.resolve(node.value.func))):
                    continue
                spec = _donation_spec(node.value)
                if spec is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donors[t.id] = spec
            # calls through them (plus direct jax.jit(f, donate...)(x))
            dispatches = []  # (call node, donated names, rebound names)
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                spec = None
                if (isinstance(node.func, ast.Name)
                        and node.func.id in donors):
                    spec = donors[node.func.id]
                elif (isinstance(node.func, ast.Call)
                      and is_jit_name(ctx.resolve(node.func.func))):
                    spec = _donation_spec(node.func)
                if spec is None:
                    continue
                donated = _donated_arg_names(node, spec)
                if not donated:
                    continue
                stmt = node
                for anc in ctx.ancestors(node):
                    stmt = anc
                    if isinstance(anc, ast.stmt):
                        break
                dispatches.append((node, donated, _assign_targets(stmt)))
            if not dispatches:
                continue
            # later reads of donated names without an intervening rebind
            # — (line, col) positions, so `out = step(state); log(state)`
            # on ONE line is still a read after the dispatch
            loads: dict[str, list[tuple[int, int]]] = {}
            stores: dict[str, list[tuple[int, int]]] = {}
            for node in nodes:
                if isinstance(node, ast.Name):
                    d = loads if isinstance(node.ctx, ast.Load) else stores
                    d.setdefault(node.id, []).append(
                        (node.lineno, node.col_offset))
            for call, donated, rebound in dispatches:
                end = (getattr(call, "end_lineno", call.lineno),
                       getattr(call, "end_col_offset", 1 << 30))
                for name in donated:
                    if name in rebound:
                        continue
                    later = sorted(pos for pos in loads.get(name, ())
                                   if pos > end)
                    if not later:
                        continue
                    first = later[0]
                    if any(end < pos < first
                           for pos in stores.get(name, ())):
                        continue  # rebound before the read
                    findings.append(self.finding(
                        ctx, first[0],
                        f"{name!r} is donated to the dispatch at line "
                        f"{call.lineno} and read again here — donation "
                        "invalidates its buffers (the chunked-stepper "
                        "bug class); rebind the call's result "
                        f"({name} = ...) or drop the donation"))
        return findings
