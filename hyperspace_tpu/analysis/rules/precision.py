"""precision-literal: the bf16 lint, AST-accurate.

Historical incident: PR 5 shipped the mixed-precision policy with a
regex lint (``scripts/check_precision_policy.py``) because bf16 literals
kept leaking past the boundary-safety policy during development.  The
regex misses aliased imports (``import jax.numpy as q; q.bfloat16``),
``from jax.numpy import bfloat16``, and can false-positive on strings in
odd positions.  This rule is the AST port — same contract, structural
matching; the script path remains as a shim over this rule.

Policy (docs/precision.md): ``hyperspace_tpu/precision.py`` is the ONE
place package code may name bf16; ``hyperspace_tpu/kernels/`` picks
dtypes from its INPUT dtype and is exempt.  Flagged in any other package
file:

- any ``<base>.bfloat16`` attribute (``jnp``/``np``/``jax.numpy``/any
  alias — the base does not matter, there is no legitimate non-dtype
  ``.bfloat16``);
- ``from <mod> import bfloat16`` (and uses of the imported name);
- a string literal equal to ``"bfloat16"`` (dtype strings; docstrings
  merely *discussing* bf16 never fire — they are not the token).

Escapes: the legacy ``# precision-policy: ok (reason)`` annotation keeps
working, as does ``# hyperlint: disable=precision-literal``.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from hyperspace_tpu.analysis.core import FileContext, Rule, context_from_text

LEGACY_ANNOT = "precision-policy: ok"
ALLOWED_FILE = "hyperspace_tpu/precision.py"
ALLOWED_DIR = "hyperspace_tpu/kernels/"

# the legacy regex — kept only as the fallback for unparseable text fed
# to the script shim's violations_in_text()
_LEGACY_RX = re.compile(
    r"(?:\bjnp\.bfloat16\b|\bjax\.numpy\.bfloat16\b|\bnp\.bfloat16\b"
    r"|[\"']bfloat16[\"'])")


def in_scope(rel: str) -> bool:
    """Whether the policy applies to this repo-relative path.  The
    analysis package itself is exempt for the same reason scripts/ was
    never self-scanned: lint code names the tokens it hunts."""
    rel = rel.replace("\\", "/")
    if not rel.startswith("hyperspace_tpu/"):
        return False
    if rel.startswith("hyperspace_tpu/analysis/"):
        return False
    return rel != ALLOWED_FILE and not rel.startswith(ALLOWED_DIR)


def _bf16_nodes(ctx: FileContext):
    """(node, what) per bf16 literal in the tree."""
    imported_names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "bfloat16":
                    imported_names.add(a.asname or a.name)
                    yield node, f"from-import of bfloat16"
        elif isinstance(node, ast.Attribute) and node.attr == "bfloat16":
            base = ctx.dotted(node.value) or "<expr>"
            yield node, f"{base}.bfloat16"
        elif (isinstance(node, ast.Constant)
              and node.value == "bfloat16"):
            yield node, '"bfloat16" dtype string'
        elif (isinstance(node, ast.Name) and node.id in imported_names
              and isinstance(node.ctx, ast.Load)):
            yield node, f"use of imported {node.id!r}"


class PrecisionLiteralRule(Rule):
    id = "precision-literal"
    severity = "error"
    summary = ("ad-hoc bf16 literal outside precision.py/kernels/ "
               "(AST port of check_precision_policy)")

    def check_file(self, ctx: FileContext):
        if not in_scope(ctx.rel):
            return []
        findings = []
        for node, what in _bf16_nodes(ctx):
            line = getattr(node, "lineno", 0)
            if LEGACY_ANNOT in ctx.comment_text(line):
                continue
            findings.append(self.finding(
                ctx, node,
                f"{what} outside the precision policy — route the dtype "
                "decision through hyperspace_tpu/precision.py "
                "(docs/precision.md), or annotate a flag-name line with "
                f"`# {LEGACY_ANNOT} (reason)`"))
        return findings


# --- script-shim API (scripts/check_precision_policy.py) ---------------------


def violations_in_text(text: str, rel: str) -> list[str]:
    """Legacy contract: ``["rel:lineno: stripped line", ...]`` for bf16
    literals in CODE.  AST-based; unparseable text falls back to the old
    comment-stripped regex so the shim never crashes on a fragment."""
    try:
        ctx = context_from_text(text, rel=rel)
    except SyntaxError:
        out = []
        for lineno, line in enumerate(text.splitlines(), 1):
            if LEGACY_ANNOT in line:
                continue
            code = line.split("#", 1)[0]
            if _LEGACY_RX.search(code):
                out.append(f"{rel}:{lineno}: {line.strip()}")
        return out
    rule = PrecisionLiteralRule()
    lines_hit: list[int] = []
    for node, _what in _bf16_nodes(ctx):
        line = getattr(node, "lineno", 0)
        if LEGACY_ANNOT in ctx.comment_text(line):
            continue
        if rule.id in ctx.suppressions.get(line, ()):
            continue
        lines_hit.append(line)
    return [f"{rel}:{ln}: {ctx.line_text(ln).strip()}"
            for ln in sorted(set(lines_hit))]


def scan_package(pkg_dir: str, root: Optional[str] = None) -> list[str]:
    """Legacy contract: offenders across every .py under ``pkg_dir``
    (rel paths taken from the package's parent, as before).  Scope is
    decided on the path RELATIVE TO ``pkg_dir`` mapped into the package
    namespace, so any directory tree passed in gets the same exemptions
    (root ``precision.py``, ``kernels/``, ``analysis/``) instead of a
    silent all-clean when it does not live at ``hyperspace_tpu/``."""
    import os

    pkg_abs = os.path.abspath(pkg_dir)
    root = root or os.path.dirname(pkg_abs)
    offenders: list[str] = []
    for dirpath, _dirs, files in os.walk(pkg_abs):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            scoped = ("hyperspace_tpu/"
                      + os.path.relpath(path, pkg_abs).replace(os.sep, "/"))
            if not in_scope(scoped):
                continue
            with open(path, encoding="utf-8") as f:
                offenders += violations_in_text(f.read(), rel)
    return offenders
