"""unbounded-retry: sleep-and-retry loops with no exit budget.

Historical incident: PR 9's failure-domain pass added retry-with-
backoff around checkpoint saves and latency injection at the fault
sites — exactly the code shape where a `while True: ... time.sleep(...)`
with no attempt cap or deadline check turns a transient failure into a
silent hang (the serving analog: a stuck retry holds an admission slot
forever, and the bounded queue sheds everything behind it).  The
checkpoint retry is the pattern to copy: ``for attempt in
range(max_attempts + 1)`` with exponential backoff and a final
re-raise.

What fires: a loop that cannot exhaust on its own — ``while True:`` /
``while 1:`` or ``for … in itertools.count(…)`` — whose body calls
``time.sleep`` and contains NO bound evidence.  Bound evidence (the
heuristic's escape hatches) is any comparison that either

- names an identifier smelling of a budget (``attempt``, ``retry``,
  ``retries``, ``tries``, ``max…``, ``budget``, ``deadline``,
  ``remaining``, ``timeout``, ``elapsed``), or
- reads a clock (``time.monotonic`` / ``time.time`` /
  ``time.perf_counter``) — a deadline check.

Bounded ``for`` loops (``range``, a finite iterable) never fire:
iteration itself is the budget.  Condition-driven ``while`` loops
(``while not stop.is_set()``) never fire either — something external
can end them, and flagging every polling loop would bury the signal.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

_BUDGET_TOKENS = ("attempt", "retry", "retries", "tries", "max",
                  "budget", "deadline", "remaining", "timeout",
                  "elapsed")
_CLOCK_CALLS = ("time.monotonic", "time.time", "time.perf_counter")


def _is_constant_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _is_count_iter(ctx: FileContext, node: ast.For) -> bool:
    call = node.iter
    if not isinstance(call, ast.Call):
        return False
    resolved = ctx.resolve(call.func) or ""
    return resolved == "itertools.count" or resolved.endswith(".count") \
        and resolved.startswith("itertools")


def _calls_sleep(ctx: FileContext, body: list[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve(node.func) or ""
                if resolved == "time.sleep":
                    return True
    return False


def _has_bound_evidence(ctx: FileContext, body: list[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Compare):
                continue
            for sub in ast.walk(node):
                name = None
                if isinstance(sub, ast.Name):
                    name = sub.id
                elif isinstance(sub, ast.Attribute):
                    name = sub.attr
                if name is not None:
                    low = name.lower()
                    if any(t in low for t in _BUDGET_TOKENS):
                        return True
                if isinstance(sub, ast.Call):
                    resolved = ctx.resolve(sub.func) or ""
                    if resolved in _CLOCK_CALLS:
                        return True
    return False


class UnboundedRetryRule(Rule):
    id = "unbounded-retry"
    severity = "warning"
    summary = ("while-True / itertools.count loops containing "
               "time.sleep with no max-attempts bound or deadline "
               "check")

    def check_file(self, ctx: FileContext):
        findings = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.While):
                if not _is_constant_true(node.test):
                    continue
                shape = "while True"
            elif isinstance(node, ast.For):
                if not _is_count_iter(ctx, node):
                    continue
                shape = "for … in itertools.count()"
            else:
                continue
            if not _calls_sleep(ctx, node.body):
                continue
            if _has_bound_evidence(ctx, node.body):
                continue
            findings.append(self.finding(
                ctx, node,
                f"{shape} loop sleeps with no max-attempts bound or "
                "deadline check — a transient failure becomes a silent "
                "hang; bound it like the checkpoint save retry "
                "(for attempt in range(max_attempts + 1) + backoff + "
                "re-raise), or check a deadline before sleeping"))
        return findings
