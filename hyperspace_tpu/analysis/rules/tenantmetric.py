"""tenant-unlabeled-metric: registry-scoped serve metrics carry a
tenant label.

Historical incident: ISSUE 20's engine registry put N tenant stacks
behind the ONE front door, and the first draft of its admission path
bumped the plain ``serve/tenant_admissions`` counter.  Every tenant's
paging traffic folded into one series — the dashboard showed a healthy
aggregate admission rate while one cold tenant thrashed its whole
engine in and out of device memory on every request.  The serve plane's
per-tenant convention (telemetry/exposition.py) is the double-write:
the base name keeps the aggregate series AND a ``tenant_metric(name,
tenant)`` twin (``<name>@tenant=<t>``) attributes it, which the
``/metrics`` exposition folds into one Prometheus family with a
``tenant`` label.

What fires (warning): an ``inc(`` / ``set_gauge(`` / ``observe(`` call
in **registry-scoped serve code** (``hyperspace_tpu/serve/registry.py``
— the one file whose every write happens on behalf of a specific
tenant stack) whose literal first argument lacks the ``@tenant=``
label.  Dynamic names built through :func:`tenant_metric` (or any
non-literal expression) never fire — the double-write helper is the
fix, not the target.

A write that is GENUINELY registry-global (the resident-count gauge —
a property of the whole device, not of one tenant's load) is
suppressed at its line with a reason:
``# hyperlint: disable=tenant-unlabeled-metric — <why>`` — the same
accepted-hazard visibility contract as every other rule.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

# the serve files whose telemetry writes are always on behalf of one
# tenant's stack; the rest of the serve plane double-writes through the
# batcher's lifecycle (already labeled) or predates tenancy
SCOPE_SUFFIXES = ("hyperspace_tpu/serve/registry.py",)

_WRITE_FNS = {"inc", "set_gauge", "observe"}
_TENANT_SEP = "@tenant="


def in_scope(rel: str) -> bool:
    return rel.endswith(SCOPE_SUFFIXES)


def _call_fn_name(node: ast.Call):
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


class TenantUnlabeledMetricRule(Rule):
    id = "tenant-unlabeled-metric"
    severity = "warning"
    summary = ("registry-scoped serve metrics written without a "
               "@tenant= label — every tenant folds into one series "
               "and per-tenant pathologies vanish in the aggregate")

    def check_file(self, ctx: FileContext):
        if not in_scope(ctx.rel):
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args
                    and _call_fn_name(node) in _WRITE_FNS):
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # tenant_metric(...) / dynamic names: labeled
            name = first.value
            if _TENANT_SEP in name:
                continue
            findings.append(self.finding(
                ctx, node,
                f"metric {name!r} written from registry-scoped serve "
                "code without a tenant label — double-write a "
                "tenant_metric(name, tenant) twin beside the "
                "aggregate, or suppress with a reason if the reading "
                "is genuinely registry-global"))
        return findings
