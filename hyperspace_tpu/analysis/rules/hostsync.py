"""host-sync-in-hot-path: device→host fetches inside the hot regions.

Historical incident: the PR 1/PR 2 loop work exists to keep the host
OUT of the step path — K steps vanish into one ``lax.scan`` dispatch and
the loss is fetched once per log boundary, never per step.  A stray
``.item()`` / ``float()`` / ``jax.device_get`` inside a scan body or a
trace-span block silently reserializes host and device (or, inside a
traced scan body, fails outright at trace time).

Hot regions:

- the body of any function (def or lambda) passed to ``lax.scan`` —
  there ``np.asarray``/``np.array`` are flagged too, because a traced
  value cannot be materialized at all (ConcretizationTypeError);
- the body of any ``with span("..."):`` block (``telemetry/trace.py``)
  — the instrumented dispatch paths (``dispatch``, ``metrics_flush``,
  ``query``); here only the unambiguous sync markers fire: ``.item()``,
  ``jax.device_get``, and ``float(x)`` on a non-literal.

The one-per-boundary ``float(loss)`` flush in ``train/loop.py`` is the
DOCUMENTED sync point and carries an inline suppression — the pattern to
copy when a sync is the design.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule
from hyperspace_tpu.analysis.rules._shared import scan_body_nodes


def _span_bodies(ctx: FileContext) -> list[tuple[str, list[ast.stmt]]]:
    """(span name, body statements) per ``with span("..."):`` block."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            resolved = ctx.resolve(call.func) or ""
            if not (resolved == "span" or resolved.endswith(".span")):
                continue
            name = ""
            if call.args and isinstance(call.args[0], ast.Constant):
                name = str(call.args[0].value)
            out.append((name, node.body))
    return out


def _sync_kind(ctx: FileContext, node: ast.AST) -> str | None:
    """'item'/'device_get'/'float'/'asarray' when ``node`` is a host-sync
    call, else None."""
    if not isinstance(node, ast.Call):
        return None
    if (isinstance(node.func, ast.Attribute) and node.func.attr == "item"
            and not node.args and not node.keywords):
        return "item"
    resolved = ctx.resolve(node.func) or ""
    if resolved == "jax.device_get" or resolved.endswith(".device_get"):
        return "device_get"
    if (isinstance(node.func, ast.Name) and node.func.id == "float"
            and len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)):
        return "float"
    if resolved in ("numpy.asarray", "numpy.array"):
        return "asarray"
    return None


class HostSyncRule(Rule):
    id = "host-sync-in-hot-path"
    severity = "warning"
    summary = (".item()/float()/device_get/np.asarray inside lax.scan "
               "bodies or span(...) dispatch blocks")

    def check_file(self, ctx: FileContext):
        findings = []
        seen: set[int] = set()

        def scan_region(root_nodes, where: str, include_asarray: bool):
            for root in root_nodes:
                for node in ast.walk(root):
                    kind = _sync_kind(ctx, node)
                    if kind is None or id(node) in seen:
                        continue
                    if kind == "asarray" and not include_asarray:
                        continue
                    seen.add(id(node))
                    what = {"item": ".item()",
                            "device_get": "jax.device_get",
                            "float": "float(...)",
                            "asarray": "np.asarray/np.array"}[kind]
                    findings.append(self.finding(
                        ctx, node,
                        f"{what} {where} — a device→host sync on the hot "
                        "path (the per-step fetch the chunked loop "
                        "exists to remove); batch the fetch at a log "
                        "boundary, or suppress with a reason if this IS "
                        "the documented sync point"))

        scan_region(scan_body_nodes(ctx), "inside a lax.scan body",
                    include_asarray=True)
        for name, body in _span_bodies(ctx):
            label = (f"inside the span({name!r}) block" if name
                     else "inside a span(...) block")
            scan_region(body, label, include_asarray=False)
        return findings
