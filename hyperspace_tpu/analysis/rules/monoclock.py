"""monotonic-clock: wall-clock subtraction in latency-bearing code.

Historical incident: ISSUE 17's span layer decomposes every request into
stage durations whose sum must equal end-to-end latency within 5 %.  A
single ``time.time()`` in that chain breaks the invariant invisibly —
NTP slews the wall clock by milliseconds (exactly the magnitude of the
stages being measured), and a step backwards yields a *negative* stage
duration that poisons a histogram forever.  ``time.time()`` is correct
for TIMESTAMPS (access-log ``ts`` fields, incident headers); it is never
correct for DURATIONS.

Flagged, in ``serve/``, ``telemetry/``, and ``train/`` only (the
latency-bearing planes; elsewhere wall-clock arithmetic can be
legitimate, e.g. deadline math against external epochs):

- ``time.time() - t0`` / ``t1 - time.time()`` — a resolved
  ``time.time`` call as either operand of a subtraction (aliased
  imports included: ``from time import time``);
- ``t = time.time()`` ... ``t2 - t`` — a name assigned from
  ``time.time()`` used as a subtraction operand anywhere in the file.

Not flagged: bare ``time.time()`` stamps (stored, logged, compared with
``<``), and any ``time.perf_counter()`` / ``time.monotonic()`` math —
those are the fix.

Escape: ``# hyperlint: disable=monotonic-clock — reason`` on the
subtraction line, for the rare deliberate wall-clock delta (e.g.
cross-process skew estimation, where wall clock IS the subject).
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

SCOPES = (
    "hyperspace_tpu/serve/",
    "hyperspace_tpu/telemetry/",
    "hyperspace_tpu/train/",
)


def in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return any(rel.startswith(p) for p in SCOPES)


def _is_walltime_call(ctx: FileContext, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and ctx.resolve(node.func) == "time.time")


def _tainted_names(ctx: FileContext) -> set:
    """Names assigned (anywhere in the file) from a bare ``time.time()``
    call — simple single-target assignments only; anything fancier
    already fires as a direct-call operand or is out of reach."""
    names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _is_walltime_call(ctx, node.value)):
            names.add(node.targets[0].id)
        elif (isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)
                and node.value is not None
                and _is_walltime_call(ctx, node.value)):
            names.add(node.target.id)
    return names


class MonotonicClockRule(Rule):
    id = "monotonic-clock"
    severity = "error"
    summary = ("time.time() used for a duration in serve/telemetry/train "
               "— NTP slew corrupts latency math; use time.perf_counter()")

    def check_file(self, ctx: FileContext):
        if not in_scope(ctx.rel):
            return []
        tainted = _tainted_names(ctx)

        def bad_operand(op: ast.AST) -> bool:
            if _is_walltime_call(ctx, op):
                return True
            return (isinstance(op, ast.Name)
                    and isinstance(op.ctx, ast.Load)
                    and op.id in tainted)

        findings = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                operands = (node.left, node.right)
            elif (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Sub)):
                operands = (node.value,)
            else:
                continue
            if any(bad_operand(op) for op in operands):
                findings.append(self.finding(
                    ctx, node,
                    "wall-clock subtraction: time.time() measures the "
                    "NTP-slewed wall clock, not elapsed time — use "
                    "time.perf_counter() (or time.monotonic()) for "
                    "durations; time.time() is for timestamps only"))
        return findings
