"""jit-cache-defeat: fresh function objects reaching ``jax.jit`` per
call.

Historical incident: the compile-time pillar (PR 13) exists because one
short run logged ``jax/recompiles=1532`` — and the cheapest way to
manufacture that number is ``jax.jit`` over a function object that is
REBUILT on every call.  ``jax.jit``'s dispatch cache lives on the
wrapper and keys traces on the wrapped callable's identity: a lambda or
a def created inside the enclosing function body is a NEW object each
time the enclosing function runs, so every call pays wrapper
construction + a fresh trace — and even with the persistent
compilation cache active, a per-call trace still pays tracing, cache-key
hashing, and a disk read where a warm in-process cache would pay a dict
lookup.

Flagged (error), when the jit call sits inside a function:

- ``jax.jit`` over a **lambda**;
- ``jax.jit`` over a **def nested in the enclosing function** (by name
  or as a decorator on the nested def).

Not flagged:

- module-scope binds (``double = jax.jit(lambda v: v * 2)``): built
  once per process;
- **factories** — the jitted callable escapes via ``return`` (bare
  name or tuple element, or the jit call itself returned): the
  ``make_*_step`` idiom everywhere in this repo builds once and hands
  the wrapper to a loop;
- binds onto ``self``/attributes (one per object construction);
- AOT pipelines (``jax.jit(f).lower(...).compile()``): explicit
  compilation never touches the dispatch cache, so there is no cache
  to defeat.

The recompile-hazard rule covers the adjacent shapes (jit in a loop,
build-and-discard invocation); this rule covers the function-identity
class those miss — a jit built once per call OUTSIDE any loop, which
looks bound but retraces every time.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule
from hyperspace_tpu.analysis.rules._shared import (
    is_jit_name, partial_jit_decorator, walk_scope)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _escaping_names(encl: ast.AST) -> set[str]:
    """Names the enclosing function returns AS VALUES (bare name or
    tuple/list element) — the factory escape.  ``return run(state)`` is
    NOT an escape: the wrapper is still rebuilt per call."""
    out: set[str] = set()
    for node in walk_scope(encl):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        vals = (node.value.elts
                if isinstance(node.value, (ast.Tuple, ast.List))
                else [node.value])
        for v in vals:
            if isinstance(v, ast.Name):
                out.add(v.id)
    return out


def _nested_def_names(encl: ast.AST) -> set[str]:
    """Defs declared directly in this function's scope (fresh objects
    per call of the enclosing function)."""
    return {n.name for n in walk_scope(encl) if isinstance(n, _FUNCS)}


class JitCacheDefeatRule(Rule):
    id = "jit-cache-defeat"
    severity = "error"
    summary = ("jax.jit over a lambda or nested def — a fresh function "
               "object per call defeats the jit cache")

    def check_file(self, ctx: FileContext):
        findings = []
        # per-enclosing-function caches (built lazily: most files have
        # no jit calls at all)
        escapes: dict[int, set] = {}
        nested: dict[int, set] = {}

        def info(encl):
            if id(encl) not in escapes:
                escapes[id(encl)] = _escaping_names(encl)
                nested[id(encl)] = _nested_def_names(encl)
            return escapes[id(encl)], nested[id(encl)]

        for node in ast.walk(ctx.tree):
            # decorated nested defs: @jax.jit / @partial(jax.jit, ...)
            # on a def inside a function — fresh jitted object per call
            # of the enclosing function unless the name escapes
            if isinstance(node, _FUNCS):
                encl = next((a for a in ctx.ancestors(node)
                             if isinstance(a, _FUNCS)), None)
                if encl is None:
                    continue
                for dec in node.decorator_list:
                    if (is_jit_name(ctx.resolve(dec))
                            or partial_jit_decorator(ctx, dec) is not None):
                        esc, _nd = info(encl)
                        if node.name not in esc:
                            findings.append(self.finding(
                                ctx, dec,
                                f"@jax.jit on {node.name!r}, a def nested "
                                f"inside {encl.name!r}: a fresh jitted "
                                "function per call — every call retraces; "
                                "hoist the def to module scope or return "
                                "the jitted callable (factory idiom)"))
                continue
            if not (isinstance(node, ast.Call)
                    and is_jit_name(ctx.resolve(node.func)) and node.args):
                continue
            encl = next((a for a in ctx.ancestors(node)
                         if isinstance(a, _FUNCS)), None)
            if encl is None:
                continue  # module scope: bound once per process
            parent = ctx.parents.get(id(node))
            # AOT escape: jax.jit(f).lower(...) — no dispatch cache
            if isinstance(parent, ast.Attribute) and parent.attr == "lower":
                continue
            target = node.args[0]
            esc, nested_names = info(encl)
            if isinstance(target, ast.Lambda):
                what = "a lambda"
            elif (isinstance(target, ast.Name)
                  and target.id in nested_names):
                what = f"nested function {target.id!r}"
            else:
                continue  # module-level callables keep their identity
            # factory exemptions: the wrapper escapes the function
            if isinstance(parent, ast.Return):
                continue
            if isinstance(parent, ast.Assign):
                tgt_names = [t.id for t in parent.targets
                             if isinstance(t, ast.Name)]
                if any(isinstance(t, ast.Attribute)
                       for t in parent.targets):
                    continue  # self.fn = jax.jit(...): once per object
                if any(t in esc for t in tgt_names):
                    continue  # assigned then returned: factory
            findings.append(self.finding(
                ctx, node,
                f"jax.jit over {what} inside {encl.name!r}: the wrapped "
                "function is a FRESH object every call, so the jit "
                "dispatch cache never hits and every call retraces "
                "(1532-recompiles class) — hoist it to module scope, or "
                "return the jitted callable once (factory idiom)"))
        return findings
