"""metric-unit-suffix: duration/size metric names end in a unit suffix.

Historical incident: the PR 7 histogram layer fixed a convention —
values are MILLISECONDS — purely by call-site discipline, and the PR 2
counter catalog already carries both ``jax/compile_s`` (seconds) and
``serve/dispatch_ms`` (milliseconds).  A metric named ``serve/dispatch``
or ``ckpt/save_time`` is a latent dashboard bug: the unit drift is
invisible in code and only surfaces when a panel mixes seconds into a
milliseconds axis (or bytes into rows) and misreads by 1000×.

What fires (warning): an ``observe(`` / ``inc(`` / ``set_gauge(`` call
whose literal name carries a **duration or size token** as an
underscore-separated segment — durations: ``ms``/``msec``/``sec``/
``secs``/``seconds``/``latency``/``duration``/``elapsed``/``wait``/
``time``; sizes: ``bytes``/``byte``/``kb``/``mb``/``gb``/``rows``/
``row`` — but does NOT end in one of the sanctioned unit suffixes
``_ms`` / ``_s`` / ``_bytes`` / ``_rows`` (a bare final segment of
``ms``/``s``/``bytes``/``rows`` after the last ``/`` also counts:
``ckpt/bytes`` is fine).

Names with no unit-smelling token never fire (``serve/requests``,
``prefetch/queue_depth`` are counts and levels — unitless by nature);
a unit-bearing name whose suffix names a STATISTIC instead
(``host_table/io_rows_peak``) is suppressed at its line with a reason,
the same accepted-hazard visibility contract as every other rule.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

_WRITE_FNS = {"inc", "set_gauge", "observe"}
_UNIT_SUFFIXES = ("_ms", "_s", "_bytes", "_rows")
# a bare unit as the final path segment (``ckpt/bytes``) is as good as
# a suffixed one
_UNIT_SEGMENTS = {"ms", "s", "bytes", "rows"}
_DURATION_TOKENS = {"ms", "msec", "sec", "secs", "seconds", "latency",
                    "duration", "elapsed", "wait", "time"}
_SIZE_TOKENS = {"bytes", "byte", "kb", "mb", "gb", "rows", "row"}


def _unit_smell(name: str):
    """The (kind, token) this name smells of, or None."""
    for seg in name.replace("/", "_").split("_"):
        if seg in _DURATION_TOKENS:
            return "duration", seg
        if seg in _SIZE_TOKENS:
            return "size", seg
    return None


def _has_unit_suffix(name: str) -> bool:
    if name.endswith(_UNIT_SUFFIXES):
        return True
    return name.rsplit("/", 1)[-1] in _UNIT_SEGMENTS


def _call_fn_name(node: ast.Call):
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


class MetricUnitSuffixRule(Rule):
    id = "metric-unit-suffix"
    severity = "warning"
    summary = ("duration/size metric names missing a _ms/_s/_bytes/"
               "_rows unit suffix — unit drift is invisible until a "
               "dashboard misreads it")

    def check_file(self, ctx: FileContext):
        findings = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args
                    and _call_fn_name(node) in _WRITE_FNS):
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            name = first.value
            smell = _unit_smell(name)
            if smell is None or _has_unit_suffix(name):
                continue
            kind, token = smell
            findings.append(self.finding(
                ctx, node,
                f"metric name {name!r} carries the {kind} token "
                f"{token!r} but does not end in a unit suffix "
                "(_ms/_s/_bytes/_rows) — name the unit or a dashboard "
                "will misread it"))
        return findings
