"""tracer-leak: Python control flow on traced values (heuristic).

Historical incident class: a ``jit``/``scan`` body that branches with
Python ``if``/``while`` on a traced value raises
``ConcretizationTypeError`` at best; at worst (when the value happens to
be concrete at trace time — a closure, a first-call constant) it bakes
ONE branch into the compiled program and silently serves stale control
flow forever after.  The scan-carry variant is exactly what ROADMAP's
pod-scale training multiplies.

Heuristic, deliberately conservative (severity ``note``): inside a
jitted function or a ``lax.scan`` body, flag

- ``if``/``while`` whose test calls into ``jnp.*``/``jax.*`` (e.g.
  ``if jnp.any(x > 0):``) or calls a reduction method (``.any()``/
  ``.all()``/``.item()``) — shape/dtype introspection (``jnp.ndim``,
  ``jnp.shape``, ``jnp.dtype``, ``jnp.issubdtype``, ...) is static
  under trace and does NOT fire;
- ``int(...)``/``bool(...)``/``float(...)`` whose argument contains such
  a call — host casts that force the tracer concrete.

Use ``jax.lax.cond``/``jnp.where``/``lax.while_loop`` instead, or hoist
the decision out of the traced region.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule
from hyperspace_tpu.analysis.rules._shared import jitted_defs, scan_body_nodes

# static-under-trace introspection: never a tracer leak
_STATIC_FNS = {"ndim", "shape", "dtype", "issubdtype", "result_type",
               "iinfo", "finfo", "isdtype", "size"}
_REDUCTION_METHODS = {"any", "all", "item"}


def _traced_value_call(ctx: FileContext, expr: ast.AST) -> ast.AST | None:
    """A call node inside ``expr`` that plausibly produces/reads a traced
    value: a non-static ``jnp.*``/``jax.*`` call or an ``.any()``-style
    reduction method."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _REDUCTION_METHODS
                and not node.args):
            return node
        resolved = ctx.resolve(node.func) or ""
        parts = resolved.split(".")
        if parts[0] == "jax" or resolved.startswith("jax.numpy"):
            if parts[-1] not in _STATIC_FNS:
                return node
    return None


class TracerLeakRule(Rule):
    id = "tracer-leak"
    severity = "note"
    summary = ("Python if/while/int() on traced values inside jit/scan "
               "regions (heuristic)")

    def check_file(self, ctx: FileContext):
        findings = []
        regions: list[ast.AST] = list(jitted_defs(ctx).values())
        regions += [n for n in scan_body_nodes(ctx) if n not in regions]
        seen: set[int] = set()
        for region in regions:
            for node in ast.walk(region):
                if id(node) in seen:
                    continue
                if isinstance(node, (ast.If, ast.While)):
                    hit = _traced_value_call(ctx, node.test)
                    if hit is not None:
                        seen.add(id(node))
                        kw = "while" if isinstance(node, ast.While) else "if"
                        findings.append(self.finding(
                            ctx, node,
                            f"Python `{kw}` on a traced value inside a "
                            "jit/scan region — concretization error or a "
                            "silently baked-in branch; use lax.cond / "
                            "jnp.where / lax.while_loop (heuristic: "
                            "suppress if the value is genuinely static)"))
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Name)
                      and node.func.id in ("int", "bool", "float")
                      and node.args):
                    hit = _traced_value_call(ctx, node.args[0])
                    if hit is not None:
                        seen.add(id(node))
                        findings.append(self.finding(
                            ctx, node,
                            f"{node.func.id}(...) on a traced value "
                            "inside a jit/scan region forces the tracer "
                            "concrete — keep it on device (astype / "
                            "lax ops) or hoist the cast out of the "
                            "traced region"))
        return findings
