"""flag-doc-drift: every CLI flag is documented in README/docs.

Same catalog-lint pattern as the telemetry counters (PR 2), applied to
the user-facing flag surface — the README flag tables are the contract
users (and the bench driver) read, and a flag that exists only in the
source is invisible:

- every ``key=`` override field of a dataclass config in
  ``hyperspace_tpu/cli/`` (RunConfig, ServeConfig — the ``key=value``
  CLI grammar exposes every public field) must appear as ``key=``
  somewhere in README.md or docs/*.md;
- every ``--flag`` registered by ``bench.py``'s argparse must appear as
  ``--flag`` there too.

Underscore-private fields are skipped.  Dynamically-built flags can't be
scanned; keep them literal (they are today).
"""

from __future__ import annotations

import ast
import re

from hyperspace_tpu.analysis.core import FileContext, ProjectContext, Rule


def _is_dataclass_decorated(ctx: FileContext, node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        resolved = ctx.resolve(target) or ""
        if resolved == "dataclass" or resolved.endswith(".dataclass"):
            return True
    return False


def config_fields(ctx: FileContext) -> list[tuple[str, int]]:
    """(field name, line) per public field of each dataclass config."""
    out = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.ClassDef)
                and _is_dataclass_decorated(ctx, node)):
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and not stmt.target.id.startswith("_")):
                out.append((stmt.target.id, stmt.lineno))
    return out


def bench_flags(ctx: FileContext) -> list[tuple[str, int]]:
    """(--flag, line) per argparse add_argument in the file."""
    out = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args):
            continue
        first = node.args[0]
        if (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("--")):
            out.append((first.value, node.lineno))
    return out


def _kv_documented(name: str, docs: str) -> bool:
    return re.search(rf"\b{re.escape(name)}=", docs) is not None


def _flag_documented(flag: str, docs: str) -> bool:
    return re.search(rf"{re.escape(flag)}(?![\w-])", docs) is not None


class FlagDocDriftRule(Rule):
    id = "flag-doc-drift"
    severity = "error"
    summary = ("CLI key= fields and bench --flags missing from the "
               "README/docs flag tables")

    def check_project(self, proj: ProjectContext):
        docs = "\n".join(t for t in proj.doc_texts().values() if t)
        findings = []
        if not docs:
            docs = ""  # every flag is then drift — the right failure
        for ctx in proj.contexts:
            if ctx.rel.startswith("hyperspace_tpu/cli/"):
                for name, line in config_fields(ctx):
                    if not _kv_documented(name, docs):
                        findings.append(self.finding(
                            ctx, line,
                            f"CLI flag {name}= ({ctx.rel}) has no "
                            f"`{name}=` row in README.md/docs/*.md — "
                            "add it to the flag table (the catalog "
                            "pattern: undocumented flags are invisible)"))
            elif ctx.rel == "bench.py":
                for flag, line in bench_flags(ctx):
                    if not _flag_documented(flag, docs):
                        findings.append(self.finding(
                            ctx, line,
                            f"bench flag {flag} has no mention in "
                            "README.md/docs/*.md — document it beside "
                            "the other bench flags"))
        return findings
