"""The hyperlint rule set — one module per rule, registered here.

Each rule is grounded in an incident from this repo's history (see the
module docstrings and docs/static-analysis.md for the catalog).
"""

from hyperspace_tpu.analysis.rules.asyncblock import BlockingCallInAsyncRule
from hyperspace_tpu.analysis.rules.catalog import TelemetryCatalogRule
from hyperspace_tpu.analysis.rules.distmat import MaterializedDistmatRule
from hyperspace_tpu.analysis.rules.donation import DonationHazardRule
from hyperspace_tpu.analysis.rules.exceptions import SwallowBaseExceptionRule
from hyperspace_tpu.analysis.rules.flags import FlagDocDriftRule
from hyperspace_tpu.analysis.rules.frozen import FrozenTableMutationRule
from hyperspace_tpu.analysis.rules.hostsync import HostSyncRule
from hyperspace_tpu.analysis.rules.hosttable import (
    FullTableMaterializationRule)
from hyperspace_tpu.analysis.rules.jitcache import JitCacheDefeatRule
from hyperspace_tpu.analysis.rules.monoclock import MonotonicClockRule
from hyperspace_tpu.analysis.rules.mpio import MultiprocessUnsafeIORule
from hyperspace_tpu.analysis.rules.packing import PackingLiteralRule
from hyperspace_tpu.analysis.rules.precision import PrecisionLiteralRule
from hyperspace_tpu.analysis.rules.recompile import RecompileHazardRule
from hyperspace_tpu.analysis.rules.retry import UnboundedRetryRule
from hyperspace_tpu.analysis.rules.tenantmetric import (
    TenantUnlabeledMetricRule)
from hyperspace_tpu.analysis.rules.tracerleak import TracerLeakRule
from hyperspace_tpu.analysis.rules.units import MetricUnitSuffixRule

ALL_RULES = (
    RecompileHazardRule,
    JitCacheDefeatRule,
    DonationHazardRule,
    HostSyncRule,
    TracerLeakRule,
    SwallowBaseExceptionRule,
    UnboundedRetryRule,
    BlockingCallInAsyncRule,
    MaterializedDistmatRule,
    FullTableMaterializationRule,
    FrozenTableMutationRule,
    PrecisionLiteralRule,
    PackingLiteralRule,
    MetricUnitSuffixRule,
    TenantUnlabeledMetricRule,
    MonotonicClockRule,
    MultiprocessUnsafeIORule,
    TelemetryCatalogRule,
    FlagDocDriftRule,
)

RULES_BY_ID = {cls.id: cls for cls in ALL_RULES}
