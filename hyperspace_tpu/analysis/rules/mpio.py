"""multiprocess-unsafe-io: unguarded filesystem writes in multihost-
reachable modules.

Historical incident class this PR (pod-scale multi-host training) makes
structural: on a pod EVERY process runs the same script, so a plain
``open(path, "w")`` in the train plane executes N times against one
shared filesystem — racing writers corrupt trend files, manifests and
exports in ways that never show single-process (the checkpoint commit
protocol in ``parallel/host_table.save_owned_rows`` exists precisely
because of this).  The rule encodes the two sanctioned shapes
(docs/multihost.md "One writer or one path each"):

- **process-0-gated**: the write sits under (or behind an early-exit
  of) an ``if`` whose test mentions a process-identity token —
  ``process_index`` / ``process_count`` / ``process_id`` /
  ``is_primary`` / ``primary`` / ``pi`` / ``pid`` / ``rank`` — e.g.
  ``if jax.process_index() == 0:`` or ``if mh.is_primary():``;
- **per-host-pathed**: the write target carries a process token
  (``f"shard_{pi:05d}.npy"``, ``f"digest.{pid}.json"``), directly or
  transitively through local assignments (``idx = process_index()``
  taints ``idx``; ``path = f"{root}.{idx}"`` then taints ``path``).

What fires (warning): in scoped modules — ``hyperspace_tpu/train/``,
``hyperspace_tpu/parallel/``, ``hyperspace_tpu/cli/train.py``,
``hyperspace_tpu/serve/artifact.py`` (the modules a pod run actually
executes on every process) — a write neither gated nor per-host-pathed:

- ``open(path, mode)`` with a w/a/x/+ mode;
- ``os.rename`` / ``os.replace`` / ``shutil.move`` / ``shutil.copy*``
  (the atomic-commit tails of a write);
- ``Path.write_text`` / ``Path.write_bytes``.

Single-process-only APIs that multihost callers never reach document
themselves with the per-line suppression and a reason — the grep-able
record that the multi-writer question was ASKED and answered.
"""

from __future__ import annotations

import ast
import re

from hyperspace_tpu.analysis.core import FileContext, Rule

_SCOPE_PREFIXES = ("hyperspace_tpu/train/", "hyperspace_tpu/parallel/")
_SCOPE_FILES = ("hyperspace_tpu/cli/train.py",
                "hyperspace_tpu/serve/artifact.py")

_RENAMES = ("os.rename", "os.replace", "shutil.move", "shutil.copy",
            "shutil.copy2", "shutil.copyfile", "shutil.copytree")
_WRITE_ATTRS = ("write_text", "write_bytes")

_TOKEN_RX = re.compile(
    r"\b(process_index|process_count|process_id|is_primary|primary"
    r"|pi|pid|rank)\b")


def _write_mode(node: ast.Call) -> bool:
    """True when an ``open`` call's mode string writes (w/a/x/+)."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:  # bare open(path) reads
        return False
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return any(c in mode.value for c in "wax+")
    return True  # dynamic mode: assume the worst, it's a warning


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of odd nodes
        return ""


def _tainted_names(tree: ast.AST) -> set[str]:
    """Names assigned (transitively) from a process-identity expression:
    ``idx = jax.process_index()`` taints ``idx``, and then
    ``path = f"{root}.{idx}"`` taints ``path`` — the per-host-path
    shape flows through locals.  Flow-insensitive by design (a warning
    rule errs toward trusting the author's naming)."""
    assigns = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            assigns.append((node.targets[0].id, _safe_unparse(node.value)))
    tainted: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, src in assigns:
            if name in tainted:
                continue
            if _TOKEN_RX.search(src) or any(
                    re.search(rf"\b{re.escape(t)}\b", src)
                    for t in tainted):
                tainted.add(name)
                changed = True
    return tainted


def _has_token(src: str, tainted: set[str]) -> bool:
    return bool(_TOKEN_RX.search(src)) or any(
        re.search(rf"\b{re.escape(t)}\b", src) for t in tainted)


class MultiprocessUnsafeIORule(Rule):
    id = "multiprocess-unsafe-io"
    severity = "warning"
    summary = ("unguarded filesystem write in a multihost-reachable "
               "module — gate on process 0 (mh.is_primary) or use a "
               "per-host path")

    def check_file(self, ctx: FileContext):
        rel = ctx.rel.replace("\\", "/")
        if not (rel.startswith(_SCOPE_PREFIXES) or rel in _SCOPE_FILES):
            return []
        tainted = _tainted_names(ctx.tree)

        # process-identity ``if`` statements, for both guard shapes:
        # ancestry (write inside the if) and early-exit (an earlier if
        # in the same function body gated who gets this far)
        guard_ifs = {id(n) for n in ast.walk(ctx.tree)
                     if isinstance(n, ast.If)
                     and _has_token(_safe_unparse(n.test), tainted)}

        def guarded(node: ast.AST) -> bool:
            func = None
            for anc in ctx.ancestors(node):
                if id(anc) in guard_ifs:
                    return True
                if func is None and isinstance(
                        anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    func = anc
            if func is not None:  # early-exit guard above the write
                for stmt in ast.walk(func):
                    if (id(stmt) in guard_ifs
                            and stmt.lineno < node.lineno):
                        return True
            return False

        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func) or ""
            target = what = None
            if resolved == "open" and node.args and _write_mode(node):
                target, what = node.args[0], "open(..., 'w')"
            elif resolved in _RENAMES and len(node.args) >= 2:
                target, what = node.args[1], resolved
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _WRITE_ATTRS):
                target, what = node.func.value, f".{node.func.attr}()"
            if target is None:
                continue
            if _has_token(_safe_unparse(target), tainted) or guarded(node):
                continue
            findings.append(self.finding(
                ctx, node,
                f"{what} in a multihost-reachable module with no "
                "process gate and no per-host path — on a pod every "
                "process runs this line against one shared filesystem; "
                "gate on mh.is_primary() / process_index() == 0, write "
                "to a per-host path, or suppress with a reason if this "
                "API is single-process by contract"))
        return findings
