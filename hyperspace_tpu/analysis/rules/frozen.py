"""frozen-table-mutation: in-place write to a frozen engine array.

Incident class the live-index PR (r18) makes structural: a
``QueryEngine``'s arrays — the embedding ``table``, the quantized
``scan_table``/``scan_scale``/``pq_codebooks`` lanes, the coarse
index's ``centroids``/``cells`` — are FROZEN after construction.
Every cache key, artifact fingerprint, and ``scan_signature`` is
derived from them once; an in-place write (``eng.table[i] = row``)
silently desynchronizes all three: queries race a half-applied table,
the batcher keeps serving cached results for rows that no longer
exist, and the artifact fingerprint attests to bytes that are gone.
It compiles, it runs, and small tests pass — visibility is the only
casualty, which is exactly the hazard class this suite catches at
lint time.

The sanctioned mutation paths are the ones that keep the invariants:
``LiveQueryEngine.upsert``/``delete`` (``serve/delta.py``) stage
writes in a delta segment behind a generation-folded scan signature,
and ``HostEmbedTable`` (``parallel/host_table.py``) owns the host
master's storage including ``write_back``/``append_rows``.  Those two
modules are the exempt homes of the writes; everywhere else a write
is a bug.

What fires (error): an ``ast.Assign`` / ``ast.AugAssign`` whose
target is

- a subscript over a frozen attribute — ``eng.table[i] = row``,
  ``idx.cells[c] += 1``, ``live._pen[slot] = INF`` — the classic
  in-place poke; or
- a rebind of a frozen attribute on an object OTHER than ``self`` /
  ``cls`` — ``eng.scan_table = requantize(...)`` swaps an engine's
  lane out from under its fingerprint (a class initializing its OWN
  attribute in ``__init__`` stays clean).

What stays clean: ``serve/delta.py`` and ``parallel/host_table.py``
(the sanctioned homes), ``self.table = ...`` construction, reads,
and writes to local arrays that merely share a name.

Fix: route point mutations through ``LiveQueryEngine.upsert`` /
``delete`` and bulk rebuilds through compaction or a blue-green
rollover; a deliberate surgical write documents itself with the
per-line suppression.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from hyperspace_tpu.analysis.core import FileContext, Rule

# the frozen array surface: engine lanes (engine.py), quantization
# payloads (quant.py), the coarse index (index.py), and the delta
# segment's own internals (writable only inside serve/delta.py)
_FROZEN_ATTRS = frozenset({
    "table", "scan_table", "scan_scale", "pq_codebooks",
    "codes", "codebooks",
    "centroids", "cells",
    "_rows", "_ids", "_pen", "_drop", "_seq",
})

# the two sanctioned homes of table mutation: the delta segment layer
# and the host master's storage (write_back / append_rows live there)
_EXEMPT_SUFFIXES = ("serve/delta.py", "parallel/host_table.py")


def _flatten_targets(targets: Iterable[ast.AST]) -> Iterable[ast.AST]:
    for tgt in targets:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            yield from _flatten_targets(tgt.elts)
        else:
            yield tgt


def _own_attribute(node: ast.Attribute) -> bool:
    """``self.x`` / ``cls.x`` — the owning class's own slot."""
    return (isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls"))


class FrozenTableMutationRule(Rule):
    id = "frozen-table-mutation"
    severity = "error"
    summary = ("in-place write to a frozen engine/index array "
               "(table / scan lanes / codes / centroids / cells) "
               "outside serve/delta.py and parallel/host_table.py — "
               "mutations go through LiveQueryEngine.upsert/delete "
               "or HostEmbedTable")

    def check_file(self, ctx: FileContext) -> List:
        rel = ctx.rel.replace("\\", "/")
        if rel.endswith(_EXEMPT_SUFFIXES):
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            else:
                continue
            for tgt in _flatten_targets(targets):
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Attribute)
                        and tgt.value.attr in _FROZEN_ATTRS):
                    findings.append(self.finding(
                        ctx, node,
                        f"in-place write to frozen array "
                        f"'.{tgt.value.attr}[...]' — cache keys, the "
                        f"artifact fingerprint, and scan_signature "
                        f"all go stale; route the mutation through "
                        f"LiveQueryEngine.upsert/delete "
                        f"(serve/delta.py) or HostEmbedTable "
                        f"(parallel/host_table.py)"))
                    break
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr in _FROZEN_ATTRS
                        and not _own_attribute(tgt)):
                    findings.append(self.finding(
                        ctx, node,
                        f"rebinding frozen array '.{tgt.attr}' on a "
                        f"foreign object swaps an engine lane out "
                        f"from under its fingerprint — rebuild via "
                        f"compaction or a blue-green rollover instead "
                        f"(serve/rollover.py)"))
                    break
        return findings
