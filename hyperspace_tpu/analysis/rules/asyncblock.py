"""blocking-call-in-async: synchronous blocking calls on the event loop.

Historical incident (foregrounded by the r13 subsystem this rule ships
with): the HTTP front door (``serve/server.py`` + ``serve/collator.py``)
runs EVERY request on one asyncio event loop — the whole point of the
continuous-batching design is that the loop only ever parks on
awaitables while device work rides the dispatch executor.  One stray
``time.sleep`` (or a blocking socket call, or sync file I/O) inside an
``async def`` freezes every in-flight request for its duration: the
p99-at-offered-qps headline degrades by exactly that blocking time, and
under load the bounded admission queue fills and sheds — an outage shape
that profiles as "the server is slow" rather than "this one line parks
the loop".

What fires — calls lexically inside an ``async def`` body whose NEAREST
enclosing function is that ``async def`` (a nested sync ``def`` is a
helper that may legitimately run on the executor; calls inside it are
out of scope):

- ``time.sleep(...)`` — the asyncio analog is ``await asyncio.sleep``;
- blocking ``socket``-module calls (``socket.socket``,
  ``socket.create_connection``, ``socket.getaddrinfo``, …) — use the
  loop's ``asyncio.open_connection`` / ``loop.getaddrinfo``;
- sync file I/O: builtin ``open`` / ``io.open``, ``os.popen``,
  ``subprocess.run``/``check_output``/``call``, and ``pathlib``-style
  ``.read_text()`` / ``.write_text()`` / ``.read_bytes()`` /
  ``.write_bytes()`` attribute calls — push them through
  ``run_in_executor``.

The escape hatch is the standard suppression grammar, one annotated
line per accepted call::

    data = path.read_text()  # hyperlint: disable=blocking-call-in-async — startup-only, loop not serving yet

There is deliberately no module-level escape: every accepted block on
the event loop stays visible at its line.
"""

from __future__ import annotations

import ast

from hyperspace_tpu.analysis.core import FileContext, Rule

# resolved dotted names that block outright
_BLOCKING_RESOLVED = {
    "time.sleep": "time.sleep(...) parks the event loop — use "
                  "`await asyncio.sleep(...)`",
    "open": "sync file I/O on the event loop — run it on an executor "
            "(`loop.run_in_executor`)",
    "io.open": "sync file I/O on the event loop — run it on an executor",
    "os.popen": "blocking subprocess pipe on the event loop — use "
                "`asyncio.create_subprocess_*`",
    "subprocess.run": "blocking subprocess on the event loop — use "
                      "`asyncio.create_subprocess_*`",
    "subprocess.check_output": "blocking subprocess on the event loop — "
                               "use `asyncio.create_subprocess_*`",
    "subprocess.call": "blocking subprocess on the event loop — use "
                       "`asyncio.create_subprocess_*`",
}
# any call into the socket module blocks (or hands back an object whose
# use blocks); asyncio's stream/loop APIs are the non-blocking surface
_SOCKET_PREFIX = "socket."
# pathlib-style sync file I/O by method name (receiver type unknowable
# statically; these names have no common non-blocking homonym)
_FILE_IO_ATTRS = {"read_text", "write_text", "read_bytes", "write_bytes"}


def _async_body_calls(ctx: FileContext):
    """Call nodes whose nearest enclosing function is an ``async def``."""
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested function: its own scope (async ones
                # are walked by the outer ast.walk pass)
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))


class BlockingCallInAsyncRule(Rule):
    id = "blocking-call-in-async"
    severity = "error"
    summary = ("time.sleep / blocking socket calls / sync file I/O "
               "inside async def bodies")

    def check_file(self, ctx: FileContext):
        findings = []
        for call in _async_body_calls(ctx):
            resolved = ctx.resolve(call.func) or ""
            why = _BLOCKING_RESOLVED.get(resolved)
            if why is None and resolved.startswith(_SOCKET_PREFIX):
                why = (f"`{resolved}` is a blocking socket call — use "
                       "asyncio streams (`asyncio.open_connection`) or "
                       "the loop's socket methods")
            if (why is None and isinstance(call.func, ast.Attribute)
                    and call.func.attr in _FILE_IO_ATTRS):
                why = (f".{call.func.attr}() is sync file I/O — run it "
                       "on an executor (`loop.run_in_executor`)")
            if why is None:
                continue
            findings.append(self.finding(
                ctx, call,
                f"blocking call inside an async def: {why}; every "
                "in-flight request on this event loop stalls for its "
                "duration — or suppress with a reason if the loop is "
                "provably not serving here"))
        return findings
