"""``python -m hyperspace_tpu.analysis`` — the hyperlint CLI.

Exit code 0 = clean, 1 = findings (or parse errors).  ``--json`` prints
the machine-readable findings artifact (file, line, rule, severity) so
bench/CI rounds can diff finding counts across PRs.
"""

from __future__ import annotations

import argparse
import os
import sys

from hyperspace_tpu.analysis.core import (lint_paths, repo_root,
                                          to_json_text)
from hyperspace_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

DEFAULT_TARGETS = ("hyperspace_tpu", "bench.py", "scripts")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hyperspace_tpu.analysis",
        description="AST lint for this repo's JAX/TPU hazard classes "
                    "(docs/static-analysis.md).")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: hyperspace_tpu, "
                         "bench.py, scripts under the repo root)")
    ap.add_argument("--json", action="store_true",
                    help="emit the JSON findings artifact instead of "
                         "human-readable lines")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths + docs lookups "
                         "(default: the checkout containing the package)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id:24} [{cls.severity:7}] {cls.summary}")
        return 0

    rules = None
    if args.rules:
        ids = [t.strip() for t in args.rules.split(",") if t.strip()]
        unknown = [i for i in ids if i not in RULES_BY_ID]
        if unknown:
            ap.error(f"unknown rule id(s): {', '.join(unknown)} "
                     f"(see --list-rules)")
        rules = [RULES_BY_ID[i]() for i in ids]

    root = os.path.abspath(args.root) if args.root else repo_root()
    paths = args.paths or [os.path.join(root, t) for t in DEFAULT_TARGETS]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        ap.error(f"no such path(s): {', '.join(missing)}")
    report = lint_paths(paths, root=root, rules=rules)
    print(to_json_text(report) if args.json else report.human())
    return report.exit_code()


if __name__ == "__main__":
    sys.exit(main())
