"""hyperlint core: one AST parse per file, a Rule registry, suppressions.

The shared machinery every rule rides on (docs/static-analysis.md):

- :func:`make_context` parses a file ONCE into a :class:`FileContext`
  carrying the tree, the raw lines, a parent map, an import-alias table
  (so ``import jax.numpy as q; q.bfloat16`` resolves the same as
  ``jnp.bfloat16`` — the aliased-import blind spot of the old regex
  lints), and the per-line suppression table;
- :class:`Rule` subclasses implement ``check_file`` (per-file AST walk)
  and/or ``check_project`` (cross-file contracts: the telemetry catalog,
  the flag-doc tables);
- :func:`lint_paths` runs a rule set over a path list and returns a
  :class:`Report` (human text or the ``--json`` findings artifact).

Suppression grammar — one line, same line as the finding::

    something_hazardous()  # hyperlint: disable=rule-id — why it is fine

Several ids comma-separate; the reason after the id list is free text
(an em-dash or two spaces separate it).  A suppression names the exact
rule it silences — there is deliberately no file-level or blanket "all"
escape, so every accepted hazard is visible at its line.

This module imports nothing outside the stdlib: linting never pays for
(or depends on) a jax import.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Iterable, Iterator, Optional

SEVERITIES = ("error", "warning", "note")

_SUPPRESS_RX = re.compile(
    r"#\s*hyperlint:\s*disable=([A-Za-z0-9_.\-]+(?:\s*,\s*[A-Za-z0-9_.\-]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding — the unit of both output formats."""

    rule: str
    severity: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}/{self.severity}] {self.message}")


def _collect_aliases(tree: ast.AST) -> dict[str, str]:
    """{local name: dotted module/object path} from every import in the
    file (function-local imports included — this codebase lazy-imports
    heavily)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            base = "." * node.level + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name)
    return aliases


class FileContext:
    """One parsed file: tree, lines, aliases, parents, suppressions."""

    def __init__(self, path: str, rel: str, text: str, tree: ast.Module):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.tree = tree
        self.lines = text.splitlines()
        # directives live in COMMENTS only — a string literal that merely
        # mentions the grammar (help text, a test asserting on lint
        # output) must not register a suppression
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            for lineno, line in enumerate(self.lines, 1):
                if "#" in line:
                    self.comments[lineno] = line[line.index("#"):]
        self.suppressions: dict[int, set[str]] = {}
        for lineno, comment in self.comments.items():
            m = _SUPPRESS_RX.search(comment)
            if m:
                self.suppressions[lineno] = {
                    t.strip() for t in m.group(1).split(",") if t.strip()}
        self.aliases = _collect_aliases(tree)
        self._parents: Optional[dict[int, ast.AST]] = None

    # --- structure helpers ----------------------------------------------------

    @property
    def parents(self) -> dict[int, ast.AST]:
        """{id(node): parent node} — built once, on first use."""
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[id(child)] = parent
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def dotted(self, node: ast.AST) -> Optional[str]:
        """``a.b.c`` for a Name/Attribute chain, else None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name with the head segment expanded through the file's
        import aliases: ``q.bfloat16`` → ``jax.numpy.bfloat16`` when the
        file did ``import jax.numpy as q``."""
        d = self.dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        base = self.aliases.get(head, head)
        return f"{base}.{rest}" if rest else base

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def comment_text(self, lineno: int) -> str:
        """The comment on ``lineno`` ("" when none) — annotation escapes
        are matched against this, never against string literals."""
        return self.comments.get(lineno, "")

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        return rule_id in self.suppressions.get(lineno, ())


class ProjectContext:
    """The whole lint run: every parsed file plus the repo root (for
    cross-file rules that read docs)."""

    def __init__(self, root: str, contexts: list[FileContext]):
        self.root = root
        self.contexts = contexts
        self.by_rel = {c.rel: c for c in contexts}

    def get(self, rel: str) -> Optional[FileContext]:
        return self.by_rel.get(rel.replace(os.sep, "/"))

    def read_doc(self, rel: str) -> Optional[str]:
        path = os.path.join(self.root, rel)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()

    def doc_texts(self) -> dict[str, str]:
        """{rel: text} for README.md + every docs/*.md under the root."""
        out = {}
        readme = self.read_doc("README.md")
        if readme is not None:
            out["README.md"] = readme
        docs_dir = os.path.join(self.root, "docs")
        if os.path.isdir(docs_dir):
            for name in sorted(os.listdir(docs_dir)):
                if name.endswith(".md"):
                    out[f"docs/{name}"] = self.read_doc(f"docs/{name}")
        return out


class Rule:
    """Base class: subclasses set ``id``/``severity``/``summary`` and
    implement ``check_file`` and/or ``check_project``."""

    id: str = ""
    severity: str = "warning"
    summary: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, proj: ProjectContext) -> Iterable[Finding]:
        return ()

    def finding(self, ctx: FileContext, node, message: str,
                severity: Optional[str] = None) -> Finding:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) else node
        col = getattr(node, "col_offset", 0) if not isinstance(node, int) else 0
        return Finding(rule=self.id, severity=severity or self.severity,
                       path=ctx.rel, line=line, col=col, message=message)


@dataclasses.dataclass
class Report:
    findings: list[Finding]
    parse_errors: list[tuple[str, str]]  # (rel, message)
    files_scanned: int

    @property
    def clean(self) -> bool:
        return not self.findings and not self.parse_errors

    def exit_code(self) -> int:
        return 0 if self.clean else 1

    def to_json(self) -> dict:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "counts": dict(sorted(counts.items())),
            "parse_errors": [{"path": p, "message": m}
                             for p, m in self.parse_errors],
            "clean": self.clean,
        }

    def human(self) -> str:
        out = [f.render() for f in self.findings]
        out += [f"{p}: parse error: {m}" for p, m in self.parse_errors]
        n = len(self.findings)
        if self.clean:
            out.append(f"hyperlint OK: {self.files_scanned} files, "
                       "0 findings")
        else:
            out.append(f"hyperlint: {n} finding{'s' if n != 1 else ''} in "
                       f"{self.files_scanned} files")
        return "\n".join(out)


# --- runner -------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".cache", "_native"}


def repo_root() -> str:
    """The checkout containing this package (analysis/ → package → root)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    seen: set[str] = set()  # overlapping inputs (pkg + pkg/sub) dedupe

    def emit(path: str) -> Iterator[str]:
        if path not in seen:
            seen.add(path)
            yield path

    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield from emit(p)
        elif os.path.isdir(p):
            for dirpath, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield from emit(os.path.join(dirpath, name))


def make_context(path: str, rel: Optional[str] = None,
                 root: Optional[str] = None) -> FileContext:
    """Parse ``path`` once; raises SyntaxError for unparseable files."""
    root = root or repo_root()
    if rel is None:
        rel = os.path.relpath(os.path.abspath(path), root)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    return FileContext(path, rel, text, ast.parse(text, filename=path))


def context_from_text(text: str, rel: str = "<text>") -> FileContext:
    """A context for in-memory source (fixtures, the script shims)."""
    return FileContext(rel, rel, text, ast.parse(text))


def default_rules() -> list[Rule]:
    from hyperspace_tpu.analysis.rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def _filter_suppressed(findings: list[Finding],
                       by_rel: dict[str, FileContext]) -> list[Finding]:
    out = []
    for f in findings:
        ctx = by_rel.get(f.path)
        if ctx is not None and ctx.suppressed(f.rule, f.line):
            continue
        out.append(f)
    return out


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               rules: Optional[list[Rule]] = None) -> Report:
    """Run ``rules`` (default: all registered) over every ``*.py`` under
    ``paths``; project rules run once with the full file set."""
    root = os.path.abspath(root) if root else repo_root()
    rules = default_rules() if rules is None else rules
    contexts: list[FileContext] = []
    parse_errors: list[tuple[str, str]] = []
    n = 0
    for path in iter_python_files(paths):
        n += 1
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            contexts.append(make_context(path, rel=rel, root=root))
        except SyntaxError as e:
            parse_errors.append((rel, f"{e.msg} (line {e.lineno})"))
    findings: list[Finding] = []
    for ctx in contexts:
        for rule in rules:
            findings.extend(rule.check_file(ctx))
    proj = ProjectContext(root, contexts)
    for rule in rules:
        findings.extend(rule.check_project(proj))
    findings = _filter_suppressed(findings, proj.by_rel)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=findings, parse_errors=parse_errors,
                  files_scanned=n)


def lint_file(path: str, rel: Optional[str] = None,
              root: Optional[str] = None,
              rules: Optional[list[Rule]] = None) -> Report:
    """Single-file convenience (fixture tests): ``rel`` overrides the
    repo-relative path the path-scoped rules see."""
    rules = default_rules() if rules is None else rules
    try:
        ctx = make_context(path, rel=rel, root=root)
    except SyntaxError as e:
        return Report(findings=[], files_scanned=1, parse_errors=[
            (rel or path, f"{e.msg} (line {e.lineno})")])
    findings = []
    for rule in rules:
        findings.extend(rule.check_file(ctx))
    findings = _filter_suppressed(findings, {ctx.rel: ctx})
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=findings, parse_errors=[], files_scanned=1)


def to_json_text(report: Report) -> str:
    return json.dumps(report.to_json(), indent=2, sort_keys=False)
