"""Hyperbolic graph convolution (HGCN, Chami et al. NeurIPS 2019).

SURVEY.md §2 "HGC conv layer" / §3.2: each layer is

    linear in the tangent space at the origin  →  attention-weighted
    neighbor aggregation (masked segment ops)  →  activation  →
    expmap back at the *next* layer's curvature.

TPU-first design decisions [PLAN]:

- All dense work happens in **origin-tangent coordinates**: one big [N, d]
  matmul on the MXU, no per-node exp/log in the inner loop.  (The reference
  family computes aggregation in the tangent space at each node x_i; at the
  origin the math is identical up to parallel transport and is one fused
  matmul instead of N small ones — the standard TPU/XLA formulation.)
- Aggregation over the padded edge list is masked ``segment_sum`` /
  segment-softmax with a static ``num_segments`` — no ragged ops
  (SURVEY.md §7 hard-part #3).
- Per-layer curvature can be **learned** (softplus-parameterized), matching
  the trainable-curvature option of the reference family.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.nn.scatter import sym_segment_aggregate


# --- segment ops (shared with any graph aggregation) --------------------------


def segment_softmax(
    logits: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    mask: Optional[jax.Array] = None,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """Softmax of ``logits`` within each segment; masked entries get 0.

    Max-subtracted for stability; safe for empty segments.  Pass
    ``indices_are_sorted=True`` for receiver-sorted edge lists (the
    ``data.graphs.prepare`` layout) to take XLA's sorted-scatter fast path.
    """
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments,
                                  indices_are_sorted=indices_are_sorted)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    ex = jnp.exp(logits - seg_max[segment_ids])
    if mask is not None:
        ex = jnp.where(mask, ex, 0.0)
    denom = jax.ops.segment_sum(ex, segment_ids, num_segments,
                                indices_are_sorted=indices_are_sorted)
    return ex / jnp.maximum(denom[segment_ids], 1e-15)


# --- attention logits ---------------------------------------------------------


ATT_LOGIT_BOUND = 30.0


def bounded_att_logits(pre: jax.Array, negative_slope: float = 0.2):
    """leaky_relu + smooth ±30 squash: the TPU-first softmax precondition.

    The textbook segment softmax needs a per-receiver max shift for exp
    safety — on the padded-edge-list layout that costs a CSR max pass,
    an [E] gather of the maxima, and their backward bookkeeping, every
    layer (measured 0.083 s/layer fwd+bwd at arxiv scale, the single
    biggest attention overhead — docs/benchmarks.md r04).  Squashing the
    logits through ``B·tanh(·/B)`` with B=30 bounds them so ``exp`` is
    exact-range-safe in f32 AND bf16 by construction (e^±30 ≈ 1e±13),
    deleting the max machinery: the whole weight computation becomes one
    XLA-fused elementwise pass.  Unlike a hard clip the squash keeps a
    nonzero gradient everywhere (1 − tanh² ≈ 1 for |x| < 10; real logits
    live well inside that), and it doubles as a logit-explosion guard —
    the r03 attention collapse study motivated exactly this kind of
    bounding.  All attention paths (planned, fallback, node-sharded)
    share this helper so their outputs stay equivalence-testable.
    """
    lm = nn.leaky_relu(pre, negative_slope)
    return ATT_LOGIT_BOUND * jnp.tanh(lm / ATT_LOGIT_BOUND)


# --- tangent coordinate helpers ----------------------------------------------


def tangent0_coords(manifold, x: jax.Array) -> jax.Array:
    """Origin-tangent coordinates of logmap0(x) as a d-vector.

    On the hyperboloid, origin-tangent vectors have time coordinate 0, so
    the space part is a faithful coordinate chart; on the ball the tangent
    space at 0 is just R^d.
    """
    v = manifold.logmap0(x)
    if isinstance(manifold, Lorentz):
        return v[..., 1:]
    return v


def from_tangent0_coords(manifold, v: jax.Array) -> jax.Array:
    """Inverse of :func:`tangent0_coords` followed by expmap0."""
    if isinstance(manifold, Lorentz):
        # zero-pad time-coordinate lift — pad, not concatenate (the
        # sharded-path rule; see manifolds/lorentz._pad_last)
        v = manifold.tangent_from_origin_coords(v)
    return manifold.expmap0(v)


def make_manifold(kind: str, c) -> Any:
    if kind == "lorentz":
        return Lorentz(c)
    if kind == "poincare":
        return PoincareBall(c)
    if kind == "euclidean":
        # flat control (c is ignored): the same HGCConv becomes a plain
        # GCN — tangent0 charts are identities — giving the
        # hyperbolic-vs-Euclidean quality comparison a shared codepath
        from hyperspace_tpu.manifolds import Euclidean

        return Euclidean()
    raise ValueError(f"unknown manifold kind {kind!r}")


class HGCConv(nn.Module):
    """One hyperbolic graph-conv layer.

    Input: points on ``(kind, c_in)``; output: points on ``(kind, c_out)``
    — the curvature transfer happens by activating in the shared origin
    tangent chart and exp-mapping at the output curvature (SURVEY.md §3.2
    "curvature_{l+1} transfer").  When ``learn_c`` is set, ``c_out`` is a
    per-layer learned parameter (softplus of a free scalar, init at the
    given value).
    """

    features: int  # manifold dimension of the output
    kind: str = "lorentz"
    c_in: float = 1.0
    c_out: float = 1.0
    learn_c: bool = False
    use_att: bool = False
    use_bias: bool = True
    activation: Callable = nn.relu
    dropout_rate: float = 0.0
    kernel_init: Callable = nn.initializers.glorot_uniform()
    # dtype for the gathered edge messages only (the aggregation kernel
    # accumulates in f32 regardless) — jnp.bfloat16 halves the dominant
    # HBM traffic of the layer at ~bf16-matmul-level quality cost; None
    # keeps the input dtype
    agg_dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [N, ambient_in] points
        g,             # data.graphs.DeviceGraph (x field unused here)
        *,
        deterministic: bool = True,
    ) -> tuple[jax.Array, Any]:
        m_in = make_manifold(self.kind, self.c_in)
        if self.learn_c:
            import numpy as np

            init = float(np.log(np.expm1(self.c_out)))
            c_raw = self.param("c_raw", nn.initializers.constant(init), ())
            c_out = nn.softplus(c_raw)
        else:
            c_out = self.c_out
        m_out = make_manifold(self.kind, c_out)

        n = x.shape[0]
        v = tangent0_coords(m_in, x)  # [N, d_in]
        kernel = self.param("kernel", self.kernel_init, (v.shape[-1], self.features), v.dtype)
        h = v @ kernel  # the MXU matmul
        if self.use_bias:
            h = h + self.param("bias", nn.initializers.zeros, (self.features,), v.dtype)
        if self.dropout_rate > 0.0:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=deterministic)

        # node-sharded graphs (parallel/node_shard.py) carry their own
        # per-shard edge lists + precomputed mean weights: aggregation is
        # a shard_map (all-gather + local block-CSR) and the rest of the
        # layer is ordinary row-wise math that GSPMD keeps node-sharded
        if hasattr(g, "w_fwd"):
            from hyperspace_tpu.parallel.node_shard import (
                node_sharded_aggregate,
                node_sharded_att_aggregate,
            )

            if self.use_att:
                # receiver partitioning keeps the segment softmax
                # shard-local; autodiff collectives carry the backward
                a_s = self.param("att_src", self.kernel_init,
                                 (self.features, 1), h.dtype)
                a_r = self.param("att_dst", self.kernel_init,
                                 (self.features, 1), h.dtype)
                agg = node_sharded_att_aggregate(
                    h, (h @ a_s)[:, 0], (h @ a_r)[:, 0], g, self.agg_dtype)
            else:
                agg = node_sharded_aggregate(h, g, self.agg_dtype)
            agg = agg.astype(h.dtype)
            out = from_tangent0_coords(m_out, self.activation(agg))
            return out, m_out

        senders, receivers, edge_mask = g.senders, g.receivers, g.edge_mask

        sorted_fast = g.rev_perm is not None
        w_static = False
        if self.use_att:
            # GAT-style additive attention in the tangent chart.
            a_s = self.param("att_src", self.kernel_init, (self.features, 1), h.dtype)
            a_r = self.param("att_dst", self.kernel_init, (self.features, 1), h.dtype)
            alpha_s = (h @ a_s)[:, 0]
            alpha_r = (h @ a_r)[:, 0]
            if sorted_fast and g.plan is not None:
                # fused planned path (nn/scatter.att_partial_planned):
                # the sender pick rides the message gather as an extra
                # feature column (ONE random [E] gather/layer), bounded-
                # logit softmax needs no max pass, num/den are one CSR
                # pass each, and the backward re-uses saved residual rows
                # instead of re-gathering.  (Row gathers cost ~28 ms per
                # 2.4 M edges on v5e regardless of width — pass count is
                # the whole game.)  On well-clustered graphs the
                # clustered edges drop out of the [E] stream entirely:
                # their logits, weights, aggregation, and whole backward
                # run in-tile from VMEM-resident blocks
                # (nn/scatter.cluster_att_partial), and only the
                # straggler subset pays the planned passes.  The two
                # [N, F+1] (num | den) partials add and divide ONCE.
                from hyperspace_tpu.nn.scatter import (
                    att_combine,
                    att_partial_planned,
                    cluster_att_partial,
                )

                cl = g.cluster
                if cl is not None and cl.att_ok:
                    h_in = (h if self.agg_dtype is None
                            else h.astype(self.agg_dtype))
                    nd = cluster_att_partial(h_in, alpha_s, alpha_r, cl,
                                             n, 0.2)
                    nd = nd + att_partial_planned(
                        h, alpha_s, alpha_r, cl.s_send, cl.s_recv,
                        cl.s_rev_local, cl.s_mask, cl.s_plan, n,
                        self.agg_dtype, 0.2)
                else:
                    nd = att_partial_planned(
                        h, alpha_s, alpha_r, senders, receivers,
                        g.rev_perm, edge_mask, g.plan, n, self.agg_dtype,
                        0.2)
                agg = att_combine(nd, h.dtype)
                out = from_tangent0_coords(m_out, self.activation(agg))
                return out, m_out
            logits = bounded_att_logits(
                alpha_s[senders] + alpha_r[receivers])
            w = segment_softmax(logits, receivers, n, mask=edge_mask,
                                indices_are_sorted=sorted_fast)
        elif g.cluster is not None:
            # cluster-pair SpMM kernel (kernels/cluster.py): block-dense
            # edges aggregate as two one-hot MXU matmuls over VMEM tiles
            # (no [E, F] message round-trip); stragglers keep the CSR
            # path; the symmetric backward runs the same two-path program
            from hyperspace_tpu.nn.scatter import cluster_sym_aggregate

            h_in = h if self.agg_dtype is None else h.astype(self.agg_dtype)
            agg = cluster_sym_aggregate(h_in, g.cluster, n).astype(h.dtype)
            out = from_tangent0_coords(m_out, self.activation(agg))
            return out, m_out
        else:
            # mean aggregation: 1/deg; degree is static per graph, so prefer
            # the precomputed g.deg over a per-step segment count
            ones = edge_mask.astype(h.dtype)
            if g.deg is not None:
                deg = g.deg.astype(h.dtype)
            else:
                deg = jax.ops.segment_sum(ones, receivers, n,
                                          indices_are_sorted=sorted_fast)
            w = ones / jnp.maximum(deg[receivers], 1.0)
            w_static = True
        h_in = h if self.agg_dtype is None else h.astype(self.agg_dtype)
        w_in = w if self.agg_dtype is None else w.astype(self.agg_dtype)
        if sorted_fast:
            # receiver-sorted scatter in forward AND backward (nn/scatter.py)
            pb, pc, pf = g.plan if g.plan is not None else (None, None, None)
            agg = sym_segment_aggregate(h_in, w_in, senders, receivers,
                                        g.rev_perm, pb, pc, pf, n, not w_static)
        else:
            msgs = w_in[:, None] * h_in[senders]
            agg = jax.ops.segment_sum(
                msgs.astype(jnp.promote_types(msgs.dtype, jnp.float32)),
                receivers, n)
        agg = agg.astype(h.dtype)

        out = from_tangent0_coords(m_out, self.activation(agg))
        return out, m_out
