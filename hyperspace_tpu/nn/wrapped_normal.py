"""Wrapped normal distribution on hyperbolic manifolds.

Semantics per Nagano et al. 2019 (Lorentz) and Mathieu et al. 2019 (ball) —
SURVEY.md §2 "WrappedNormal", required by the HVAE workload (BASELINE.json
configs[3]: "Hyperbolic VAE on MNIST with wrapped-normal prior").

Sampling (reparameterized, fully differentiable):
    v ~ N(0, scale)        in orthonormal coordinates of T_origin
    u = PT_{origin→μ}(v)   (parallel transport)
    z = exp_μ(u)

Density (w.r.t. the Riemannian volume measure):
    log p(z) = log N(v; 0, scale) − logdetexp(μ, z)

where v recovers from z by the inverse path and logdetexp is the Jacobian
of the exponential map, (d−1)·log(sinh(√c r)/(√c r)).

Orthonormal-coordinate conventions at the origin:
- Lorentz: tangent = (0, v); Minkowski metric restricted to T_origin is the
  identity on space coords, so coords are the space part as-is.
- Poincaré ball: metric at 0 is λ₀²·I with λ₀ = 2, so an orthonormal
  coordinate vector v corresponds to the ambient vector v/2.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp



def _log_normal(v: jax.Array, scale: jax.Array) -> jax.Array:
    """Diagonal-Gaussian log density, summed over the last axis."""
    var = scale**2
    return jnp.sum(
        -0.5 * (v**2 / var + jnp.log(2.0 * jnp.pi * var)), axis=-1
    )


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class WrappedNormal:
    """WrappedNormal(manifold, loc, scale).

    loc: [..., D] point on the manifold (D = ambient dim).
    scale: [..., d] positive std-devs in origin-tangent coords (d = manifold
    dim; for Lorentz D = d+1, for the ball D = d).

    Registered as a pytree (like the manifolds) so a jitted encoder can
    return a WrappedNormal posterior directly (HVAE, BASELINE configs[3]).
    """

    manifold: Any
    loc: jax.Array
    scale: jax.Array

    def tree_flatten(self):
        return (self.manifold, self.loc, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def dim(self) -> int:
        return self.scale.shape[-1]

    # --- distribution API -----------------------------------------------------

    def rsample(self, key: jax.Array, sample_shape: tuple = ()) -> jax.Array:
        return self._rsample_with_coords(key, sample_shape)[0]

    def _rsample_with_coords(self, key: jax.Array, sample_shape: tuple = ()):
        """(z, v): the sample and its origin-chart coordinates — callers
        holding v can evaluate the density without the exp/log round-trip."""
        m = self.manifold
        shape = sample_shape + self.scale.shape
        v = self.scale * jax.random.normal(key, shape, self.scale.dtype)
        u0 = m.tangent_from_origin_coords(v)
        loc = jnp.broadcast_to(self.loc, sample_shape + self.loc.shape)
        u = m.ptransp0(loc, u0)
        return m.expmap(loc, u), v  # expmap ends in proj on every manifold

    def log_prob(self, z: jax.Array) -> jax.Array:
        """Log density w.r.t. the Riemannian volume measure; shape [...]."""
        m = self.manifold
        u = m.logmap(self.loc, z)
        u0 = m.ptransp(self.loc, m.origin(u.shape, u.dtype), u)
        v = m.origin_coords_from_tangent(u0)
        return _log_normal(v, self.scale) - m.logdetexp(self.loc, z)

    def sample_and_log_prob(self, key: jax.Array, sample_shape: tuple = ()):
        """Sample + density in one pass: the freshly-drawn coordinates v give
        the density directly (‖v‖ is the geodesic radius, transport is an
        isometry), skipping log_prob's logmap/ptransp/arcosh inverse chain —
        cheaper and boundary-stable on the VAE hot path."""
        z, v = self._rsample_with_coords(key, sample_shape)
        lp = _log_normal(v, self.scale) - self.manifold.logdetexp_from_coords(v)
        return z, lp
