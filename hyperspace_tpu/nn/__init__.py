from hyperspace_tpu.nn.layers import HypAct, HypLinear, LorentzLinear
from hyperspace_tpu.nn.mlr import HypMLR, LorentzMLR, hyp_mlr_logits
from hyperspace_tpu.nn.wrapped_normal import WrappedNormal

__all__ = [
    "HypAct",
    "HypLinear",
    "LorentzLinear",
    "HypMLR",
    "LorentzMLR",
    "hyp_mlr_logits",
    "WrappedNormal",
]
