from hyperspace_tpu.nn.attention import (
    HypMultiHeadAttention,
    lorentz_attention,
    lorentz_attention_tiled,
)
from hyperspace_tpu.nn.decoders import FermiDiracDecoder
from hyperspace_tpu.nn.gcn import HGCConv, segment_softmax
from hyperspace_tpu.nn.layers import HypAct, HypLinear, LorentzLinear
from hyperspace_tpu.nn.mlr import HypMLR, LorentzMLR, hyp_mlr_logits
from hyperspace_tpu.nn.wrapped_normal import WrappedNormal

__all__ = [
    "FermiDiracDecoder",
    "HGCConv",
    "HypAct",
    "HypLinear",
    "HypMLR",
    "HypMultiHeadAttention",
    "LorentzLinear",
    "LorentzMLR",
    "WrappedNormal",
    "hyp_mlr_logits",
    "lorentz_attention",
    "lorentz_attention_tiled",
    "segment_softmax",
]
