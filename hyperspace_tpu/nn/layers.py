"""Hyperbolic NN layers (flax.linen).

Implements the layer inventory of SURVEY.md §2: the gyro-linear layer
(reference CUDA kernel N5), the fully-hyperbolic Lorentz linear layer
(HyboNet), and the tangent-space activation with curvature transfer (HGCN).

Parameterization convention [PLAN]: layer-internal manifold-valued
parameters (biases, hyperplane base points) are stored as **tangent vectors
at the origin** and mapped with ``expmap0`` in the forward pass.  The stored
parameter is Euclidean, so these layers train under any optax optimizer and
need no manifold-tag plumbing through flax; the *unconstrained-storage +
constrained-forward* pattern is the TPU-friendly equivalent of the
reference's ManifoldParameter class.  Embedding tables, by contrast, are
true on-manifold parameters driven by :mod:`hyperspace_tpu.optim` with
manifold tags.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.hyplinear import hyp_linear
from hyperspace_tpu.manifolds import Lorentz, PoincareBall
from hyperspace_tpu.manifolds import lorentz, smath
from hyperspace_tpu.precision import compute_matmul


class HypLinear(nn.Module):
    """Gyro-linear layer on the Poincaré ball: y = (M ⊗_c x) ⊕_c b.

    Semantics per Ganea et al. 2018 (reference kernel N5, SURVEY.md §2
    "HypLinear / gyro-linear").  Input/output are points on the ball of the
    layer's manifold.
    """

    features: int
    manifold: PoincareBall
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.glorot_uniform()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d_in = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (d_in, self.features), x.dtype)
        if self.use_bias:
            # bias is a tangent vector at the origin; exp0 makes it a point
            bias_t = self.param("bias", nn.initializers.zeros, (self.features,), x.dtype)
            b = self.manifold.expmap0(bias_t)
        else:
            b = jnp.zeros((self.features,), x.dtype)  # x ⊕ 0 = x exactly
        # fused matmul → Möbius rescale → ⊕ bias → proj (kernel N5)
        return hyp_linear(x, kernel, b, self.manifold.c)


class LorentzLinear(nn.Module):
    """Fully-hyperbolic linear layer on the hyperboloid (HyboNet).

    Semantics per Chen et al. ACL 2022 (SURVEY.md §2 "LorentzLinear"): the
    full ambient input (time + space coordinates) feeds an ordinary matmul
    producing the output *space* coordinates, and the output time coordinate
    is reconstructed from the hyperboloid constraint

        t = sqrt(1/c + ‖space‖²).

    No tangent-space detour — one MXU matmul plus a norm, and the output is
    on-manifold by construction (the TPU-native win of the Lorentz model).
    ``dim`` is the *manifold* dimension: output ambient shape is dim+1.
    """

    dim: int
    manifold: Lorentz
    use_bias: bool = True
    activation: Optional[Callable] = None
    kernel_init: Callable = nn.initializers.glorot_uniform()
    # mixed-precision compute dtype for the matmul ONLY (the layer's MXU
    # mass): inputs and kernel are cast to it, the product is cast back
    # to the storage dtype BEFORE the bias add and the time-coordinate
    # reconstruction — the hyperboloid constraint math (safe_sqrt of
    # 1/c + ‖space‖²) always runs full-precision.  None (default) is the
    # exact pre-policy layer (hyperspace_tpu/precision.py).
    compute_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d_in = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (d_in, self.dim), x.dtype)
        h = x
        if self.activation is not None:
            h = self.activation(h)
        space = compute_matmul(h, kernel, self.compute_dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.dim,), x.dtype)
            space = space + bias
        # time-coordinate reconstruction: pad+add, never concatenate
        # (manifolds/lorentz.with_time_coordinate — the sharded-path rule)
        return lorentz.with_time_coordinate(
            space, jnp.asarray(self.manifold.c, x.dtype))


class HypAct(nn.Module):
    """Tangent-space activation with curvature transfer (HGCN).

    y = exp0^{c_out}( act( log0^{c_in}(x) ) ) — Chami et al. 2019 use this
    between layers whose curvatures differ (SURVEY.md §3.2 "curvature_{l+1}
    transfer").  Works for any pair of manifolds that share a tangent space
    at the origin of the same width (ball→ball, lorentz→lorentz).
    """

    manifold_in: Any
    manifold_out: Any
    activation: Callable = nn.relu

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # activate in the origin chart: unconstrained coordinates, so any
        # elementwise nonlinearity keeps the result a valid tangent vector
        m_in, m_out = self.manifold_in, self.manifold_out
        v = m_in.origin_coords_from_tangent(m_in.logmap0(x))
        v = self.activation(v)
        return m_out.expmap0(m_out.tangent_from_origin_coords(v))
