"""Edge-batched manifold distances with planned-scatter VJPs.

The LP decoder's backward pass is a scatter of ~millions of per-pair
gradient rows into the [N, D] embedding — at ogbn-arxiv scale the single
most expensive op in the HGCN train step (2 × 47 ms unsorted scatters vs
41 ms for the whole encoder forward).  These ops keep the *math* of
``manifold.sqdist`` untouched (the backward re-runs its exact VJP
per edge — clamps, custom gradients and learned-curvature cotangents
included) and reorganize only the scatter:

- :func:`graph_edge_sqdist` — distances along the training graph's own
  edge list.  The layout from ``data.graphs.prepare`` (receiver-sorted,
  reverse-edge involution π, CSR plan) turns BOTH endpoint scatters into
  one sorted block-CSR matmul: sender-side cotangents re-index through π
  (``dz[i] = Σ_e gs_{π(e)} δ(r_e = i)``) and merge with the receiver-side
  ones into a single ``csr_segment_sum``.
- :func:`pair_sqdist_semi_planned` — (u, v) pairs where the u column is
  static and sorted with its own plan (e.g. negatives that re-randomize
  only v each step): u-side scatter planned, v-side plain.

Both return the same values and gradients as ``m.sqdist(z[a], z[b])``
(tests/nn/test_edge_dist.py asserts it).

When it wins (measured on v5e at ogbn-arxiv scale): the planned scatter
is ~4× an unsorted one at wide feature dims (F≈128: 22 ms vs ~90 ms),
but for the HGCN LP decoder's narrow 33-dim embeddings the unsorted
scatters cost only ~47 ms while the symmetric edge list doubles the
gather/elementwise work — so ``train_step_lp`` (plain pairs) stays the
default there and ``train_step_lp_planned`` is the alternative for
wide-embedding or scatter-dominated regimes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hyperspace_tpu.nn.scatter import _sorted_segsum


def _sqdist_fn(kind: str):
    from hyperspace_tpu.nn.gcn import make_manifold

    def f(a, b, c):
        return make_manifold(kind, c).sqdist(a, b)

    return f


@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def graph_edge_sqdist(
    z: jax.Array,          # [N, D] points on the manifold
    c,                     # curvature (traced scalar; grads flow)
    senders: jax.Array,    # [E] int32
    receivers: jax.Array,  # [E] int32, sorted ascending
    rev_perm: jax.Array,   # [E] int32 involution edge -> reverse edge
    plan_block,            # CSR work items ([T] int32 each) or None
    plan_chunk,
    plan_first,
    kind: str = "lorentz",
) -> jax.Array:
    """sqdist(z[s_e], z[r_e]) per edge, with a single planned VJP scatter."""
    return _sqdist_fn(kind)(z[senders], z[receivers], c)


def _ge_fwd(z, c, s, r, rp, pb, pc, pf, kind):
    return graph_edge_sqdist(z, c, s, r, rp, pb, pc, pf, kind), (
        z, c, s, r, rp, pb, pc, pf)


def _ge_bwd(kind, res, gbar):
    z, c, s, r, rp, pb, pc, pf = res
    zs, zr = z[s], z[r]
    # Distance symmetry collapses both endpoint cotangents into ONE
    # receiver-side partial: with D(a,b) = ∂sqdist(a,b)/∂b (= ∂/∂a at the
    # swapped pair, since sqdist(a,b) = sqdist(b,a)), the sender-side
    # cotangent of edge e lands at edge π(e) as
    #     gs_{π(e)} = D(zr_e, zs_e) · ḡ_{π(e)} ,
    # i.e. the SAME per-edge vector as gr_e scaled by the π-permuted
    # scalar — so only the [E] cotangent permutes, never an [E, D] tensor
    # (a full-row permute gather costs 124 ms at arxiv scale; the scalar
    # one is free).
    _, vjp_r = jax.vjp(lambda b: _sqdist_fn(kind)(zs, b, c), zr)
    (gr_both,) = vjp_r(gbar + gbar[rp])
    dz = _sorted_segsum(gr_both, r, pb, pc, pf, z.shape[0])
    # curvature cotangent uses the original ḡ (c is not edge-indexed)
    _, vjp_c = jax.vjp(lambda cc: _sqdist_fn(kind)(zs, zr, cc), c)
    (dc,) = vjp_c(gbar)
    return dz.astype(z.dtype), dc, None, None, None, None, None, None


graph_edge_sqdist.defvjp(_ge_fwd, _ge_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def pair_sqdist_semi_planned(
    z: jax.Array,   # [N, D]
    c,
    u: jax.Array,   # [P] int32, sorted ascending, static across steps
    v: jax.Array,   # [P] int32, arbitrary (fresh randomness each step)
    plan_block,     # CSR plan for u, or None
    plan_chunk,
    plan_first,
    kind: str = "lorentz",
) -> jax.Array:
    """sqdist(z[u_p], z[v_p]) with the u-side VJP scatter planned."""
    return _sqdist_fn(kind)(z[u], z[v], c)


def _ps_fwd(z, c, u, v, pb, pc, pf, kind):
    return pair_sqdist_semi_planned(z, c, u, v, pb, pc, pf, kind), (
        z, c, u, v, pb, pc, pf)


def _ps_bwd(kind, res, gbar):
    z, c, u, v, pb, pc, pf = res
    _, vjp = jax.vjp(_sqdist_fn(kind), z[u], z[v], c)
    gu, gv, dc = vjp(gbar)
    dz = _sorted_segsum(gu, u, pb, pc, pf, z.shape[0])
    # v side is fresh randomness each step — unsorted scatter is the cost
    # of that; accumulate it in ≥f32 so bf16 cotangents don't truncate
    acc_dt = jnp.promote_types(gv.dtype, jnp.float32)
    dz = dz.astype(acc_dt) + jax.ops.segment_sum(
        gv.astype(acc_dt), v, z.shape[0])
    return dz.astype(z.dtype), dc, None, None, None, None, None


pair_sqdist_semi_planned.defvjp(_ps_fwd, _ps_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(12,))
def pair_sqdist_planned(
    z: jax.Array,   # [N, D]
    c,
    u: jax.Array,   # [P] int32, sorted ascending, static across steps
    v: jax.Array,   # [P] int32, static across steps (any order)
    u_pb, u_pc, u_pf,   # CSR plan for u
    v_perm: jax.Array,  # [P] int32 static argsort of v
    v_sorted: jax.Array,  # [P] = v[v_perm]
    v_pb, v_pc, v_pf,   # CSR plan for v_sorted
    kind: str = "lorentz",
) -> jax.Array:
    """sqdist(z[u_p], z[v_p]) with BOTH VJP scatters planned.

    For *static* pair sets (e.g. the training positives, fixed for a whole
    run) the v column can be pre-sorted too: the backward permutes the
    v-side cotangents through the static ``v_perm`` and feeds them to the
    same sorted block-CSR scatter as the u side — no unsorted scatter
    anywhere in the decoder (VERDICT r1 #6: fold the Fermi–Dirac decoder's
    distance pass into the planned kernel).  Build the inputs once with
    ``models.hgcn.make_planned_pairs``.
    """
    return _sqdist_fn(kind)(z[u], z[v], c)


def _pair_planned_fwd(z, c, u, v, u_pb, u_pc, u_pf, v_perm, v_sorted,
                      v_pb, v_pc, v_pf, kind):
    out = pair_sqdist_planned(z, c, u, v, u_pb, u_pc, u_pf, v_perm,
                              v_sorted, v_pb, v_pc, v_pf, kind)
    return out, (z, c, u, v, u_pb, u_pc, u_pf, v_perm, v_sorted,
                 v_pb, v_pc, v_pf)


def _pair_planned_bwd(kind, res, gbar):
    (z, c, u, v, u_pb, u_pc, u_pf, v_perm, v_sorted, v_pb, v_pc, v_pf) = res
    _, vjp = jax.vjp(_sqdist_fn(kind), z[u], z[v], c)
    gu, gv, dc = vjp(gbar)
    n = z.shape[0]
    dz = _sorted_segsum(gu, u, u_pb, u_pc, u_pf, n)
    dz = dz + _sorted_segsum(gv[v_perm], v_sorted, v_pb, v_pc, v_pf, n)
    return (dz.astype(z.dtype), dc, None, None, None, None, None, None,
            None, None, None, None)


pair_sqdist_planned.defvjp(_pair_planned_fwd, _pair_planned_bwd)
