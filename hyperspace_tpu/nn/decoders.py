"""Link-prediction decoders (SURVEY.md §2 "Fermi–Dirac LP decoder").

Chami et al. 2019: edge probability from the geodesic distance,

    p(u ~ v) = 1 / ( exp( (d(u,v)² − r) / t ) + 1 ),

with learnable radius ``r`` and temperature ``t``.  ROC-AUC of this score on
held-out edges is the [B] north-star quality metric.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax


class FermiDiracDecoder(nn.Module):
    """Edge logits from squared distances; sigmoid(logit) = the F-D prob."""

    r_init: float = 2.0
    t_init: float = 1.0

    @nn.compact
    def __call__(self, sqdist: jax.Array) -> jax.Array:
        r = self.param("r", nn.initializers.constant(self.r_init), ())
        # inverse-softplus so softplus(t_raw) inits at t_init (python math:
        # a jnp constant here would be staged under jit and unconcretizable)
        t_raw = self.param(
            "t_raw", nn.initializers.constant(math.log(math.expm1(self.t_init))), ()
        )
        t = nn.softplus(t_raw) + 1e-4
        return (r - sqdist) / t  # logit; 1/(e^{(d²-r)/t}+1) = sigmoid(logit)
