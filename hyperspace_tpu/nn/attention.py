"""Hyperbolic attention (reference CUDA kernel N7; SURVEY.md §2).

Semantics per Gulcehre et al. 2019 / HyboNet (Chen et al. ACL 2022): the
attention score of query q against key k is an affine function of their
**squared Lorentz distance**,

    s(q, k) = (−d²_L(q, k) + β) / τ ,

with learnable bias β and temperature τ, and the aggregation of the values
is the **Lorentz centroid** (Law et al. 2019) of the value points under the
softmax weights — output points stay on the hyperboloid by construction.

TPU-first structure [PLAN]:

- d²_L(q,k) = −2/c − 2⟨q,k⟩_L expands the whole score matrix into ONE
  Minkowski Gram matrix q @ diag(−1,1,…,1) @ kᵀ — a single MXU matmul.
- The centroid numerator Σ w_j v_j is another matmul; the normalization is
  a row-wise rescale.  So hyperbolic attention = 2 matmuls + softmax, the
  same cost shape as Euclidean attention.
- ``lorentz_attention_tiled`` computes the same thing scanning over KV
  blocks with an online softmax — the pure-JAX twin of the flash-style
  Pallas kernel and the building block ring/Ulysses sequence parallelism
  wraps (SURVEY.md §5 "Long-context / sequence parallelism").
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.attention import flash_attention
from hyperspace_tpu.manifolds import Lorentz
from hyperspace_tpu.manifolds import lorentz, smath
from hyperspace_tpu.nn.layers import LorentzLinear
from hyperspace_tpu.precision import compute_matmul


def minkowski_gram(q: jax.Array, k: jax.Array) -> jax.Array:
    """[..., Nq, D] × [..., Nk, D] → ⟨q_i, k_j⟩_L as one matmul."""
    k_flip = k.at[..., 0].multiply(-1.0)
    return q @ jnp.swapaxes(k_flip, -1, -2)


def lorentz_attention(
    q: jax.Array,  # [..., Nq, D] points on the hyperboloid
    k: jax.Array,  # [..., Nk, D]
    v: jax.Array,  # [..., Nk, D]
    manifold: Lorentz,
    *,
    beta: jax.Array | float = 0.0,
    tau: jax.Array | float = 1.0,
    mask: Optional[jax.Array] = None,  # [..., Nq, Nk] True = attend
) -> jax.Array:
    """Dense hyperbolic attention; returns hyperboloid points [..., Nq, D]."""
    c = jnp.asarray(manifold.c, q.dtype)
    gram = minkowski_gram(q, k)  # [..., Nq, Nk]
    sqd = -2.0 / c - 2.0 * gram  # squared Lorentz distance
    logits = (-sqd + beta) / tau
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    w = jnp.where(jnp.isnan(w), 0.0, w)  # fully-masked rows
    s = w @ v  # centroid numerator: second matmul
    nrm = smath.safe_sqrt(smath.clamp_min(
        -_mdot_self(s), smath.eps_for(q.dtype)))
    return s / (smath.sqrt_c(c) * nrm)


def _mdot_self(s: jax.Array) -> jax.Array:
    return (jnp.sum(s[..., 1:] * s[..., 1:], axis=-1, keepdims=True)
            - s[..., :1] * s[..., :1])


def lorentz_attention_tiled(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    manifold: Lorentz,
    *,
    beta: jax.Array | float = 0.0,
    tau: jax.Array | float = 1.0,
    mask: Optional[jax.Array] = None,
    block_size: int = 128,
) -> jax.Array:
    """KV-tiled hyperbolic attention with an online softmax.

    Mathematically identical to :func:`lorentz_attention`; scans KV blocks
    carrying (running max, running denominator, running numerator) — the
    flash-attention recurrence.  The Lorentz centroid's normalizer-free
    numerator makes the value accumulation a plain weighted sum, so the
    recurrence is unchanged from Euclidean flash attention; only the final
    row-rescale differs.  This is the oracle twin of the Pallas kernel and
    the per-device body of ring attention.
    """
    c = jnp.asarray(manifold.c, q.dtype)
    nk = k.shape[-2]
    pad = (-nk) % block_size
    if pad:
        padder = lambda a: jnp.concatenate(
            [a, jnp.zeros(a.shape[:-2] + (pad, a.shape[-1]), a.dtype)], axis=-2)
        k, v = padder(k), padder(v)
        block_mask = jnp.arange(nk + pad) < nk
        if mask is None:
            mask = jnp.broadcast_to(block_mask, q.shape[:-1] + (nk + pad,))
        else:
            mask = jnp.concatenate([
                mask, jnp.zeros(mask.shape[:-1] + (pad,), bool)], axis=-1)
    n_blocks = k.shape[-2] // block_size

    kb = jnp.moveaxis(k.reshape(k.shape[:-2] + (n_blocks, block_size, k.shape[-1])), -3, 0)
    vb = jnp.moveaxis(v.reshape(v.shape[:-2] + (n_blocks, block_size, v.shape[-1])), -3, 0)
    if mask is not None:
        mb = jnp.moveaxis(mask.reshape(mask.shape[:-1] + (n_blocks, block_size)), -2, 0)
    else:
        mb = None

    m0 = jnp.full(q.shape[:-1], -jnp.inf, q.dtype)  # running max
    l0 = jnp.zeros(q.shape[:-1], q.dtype)  # running denom
    s0 = jnp.zeros_like(q)  # running numerator

    def body(carry, blk):
        m_run, l_run, s_run = carry
        if mb is None:
            kj, vj = blk
            maskj = None
        else:
            kj, vj, maskj = blk
        gram = minkowski_gram(q, kj)
        logits = (2.0 / c + 2.0 * gram + beta) / tau
        if maskj is not None:
            logits = jnp.where(maskj, logits, -jnp.inf)
        m_new = jnp.maximum(m_run, jnp.max(logits, axis=-1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_run), m_run - m_safe, -jnp.inf))
        p = jnp.exp(logits - m_safe[..., None])
        if maskj is not None:
            p = jnp.where(maskj, p, 0.0)
        l_new = alpha * l_run + jnp.sum(p, axis=-1)
        s_new = alpha[..., None] * s_run + p @ vj
        return (m_new, l_new, s_new), None

    blks = (kb, vb) if mb is None else (kb, vb, mb)
    (m_f, l_f, s_f), _ = jax.lax.scan(body, (m0, l0, s0), blks)
    s = s_f / smath.clamp_min(l_f, smath.min_norm(q.dtype))[..., None]
    nrm = smath.safe_sqrt(smath.clamp_min(-_mdot_self(s), smath.eps_for(q.dtype)))
    return s / (smath.sqrt_c(c) * nrm)


class HypMultiHeadAttention(nn.Module):
    """Multi-head hyperbolic self/cross attention on the hyperboloid.

    Q/K/V projections are :class:`LorentzLinear` maps into per-head
    hyperboloids of dimension ``dim // num_heads``; heads are concatenated
    in space coordinates and fused by an output LorentzLinear — every
    intermediate stays on-manifold.
    """

    dim: int  # total manifold dim across heads
    num_heads: int = 4
    manifold: Lorentz = None  # type: ignore[assignment]
    tau_init: float = 1.0
    # "flash" = kernels/attention.flash_attention — the N7 Pallas kernel
    # on TPU (dense twin elsewhere); "scan" = the XLA online-softmax KV
    # scan (lorentz_attention_tiled, the ring-attention per-device body)
    impl: str = "flash"
    # mixed-precision compute dtype for the Q/K/V projection matmuls and
    # the output LorentzLinear (the attention's MXU mass); the time-
    # coordinate reconstructions and the attention body itself stay in
    # the storage dtype.  None (default) = exact pre-policy module.
    compute_dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self,
        x_q: jax.Array,  # [..., Nq, D]
        x_kv: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,  # [..., Nq, Nk]
    ) -> jax.Array:
        import math

        if x_kv is None:
            x_kv = x_q
        h = self.num_heads
        dh = self.dim // h
        assert dh * h == self.dim, "dim must divide num_heads"
        m = self.manifold

        def proj(name, x):
            # one LorentzLinear into h stacked head-hyperboloids
            kernel = self.param(
                f"{name}_kernel", nn.initializers.glorot_uniform(),
                (x.shape[-1], h * dh), x.dtype)
            # matmul on the compute lane, everything after it f32
            space = compute_matmul(x, kernel, self.compute_dtype)
            space = space.reshape(space.shape[:-1] + (h, dh))
            space = jnp.swapaxes(space, -3, -2)  # [..., h, N, dh]
            # pad+add lift (manifolds/lorentz.with_time_coordinate):
            # [..., h, N, dh+1]
            return lorentz.with_time_coordinate(
                space, jnp.asarray(m.c, x.dtype))

        q, k, v = proj("q", x_q), proj("k", x_kv), proj("v", x_kv)
        # per-head score bias/temperature, shaped to broadcast over [h, Nq, Nk]
        beta = self.param("beta", nn.initializers.zeros, (h, 1, 1), x_q.dtype)
        tau = nn.softplus(self.param(
            "tau_raw", nn.initializers.constant(math.log(math.expm1(self.tau_init))),
            (h, 1, 1), x_q.dtype)) + 1e-4
        if mask is not None:
            mask = mask[..., None, :, :]  # broadcast over heads
        if self.impl == "scan":
            o = lorentz_attention_tiled(q, k, v, m, beta=beta, tau=tau, mask=mask)
        elif self.impl == "flash":
            o = flash_attention(q, k, v, m.c, beta=beta, tau=tau, mask=mask)
        else:
            raise ValueError(f"unknown attention impl {self.impl!r}")
        # concat head space-coords, reconstruct time on the joint hyperboloid
        o_sp = jnp.swapaxes(o[..., 1:], -3, -2)  # [..., N, h, dh]
        o_sp = o_sp.reshape(o_sp.shape[:-2] + (h * dh,))
        merged = lorentz.with_time_coordinate(
            o_sp, jnp.asarray(m.c, x_q.dtype))
        return LorentzLinear(self.dim, m, name="out",
                             compute_dtype=self.compute_dtype)(merged)
