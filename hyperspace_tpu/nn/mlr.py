"""Hyperbolic multinomial logistic regression (the hyperbolic softmax head).

Semantics per Ganea et al. 2018 eq. (25) (SURVEY.md §2 "Hyperbolic MLR /
softmax head"; reference CUDA kernel N6): each class k owns a hyperbolic
hyperplane through point p_k with normal a_k ∈ T_{p_k}, and

    logit_k(x) = (λ_{p_k} ‖a_k‖ / √c) · asinh( 2√c ⟨z_k, a_k⟩
                                               / ((1 − c‖z_k‖²) ‖a_k‖) ),
    z_k = (−p_k) ⊕_c x .

The logit is a smooth signed multiple of the distance from x to the
hyperplane, so ``softmax(logits)`` is the hyperbolic softmax.

Also provides ``lorentz_mlr`` for hyperboloid inputs: points are mapped to
the ball stereographically first (SURVEY.md §2 "Ball↔hyperboloid maps") —
distance-preserving, so the decision geometry is unchanged.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.mlr import hyp_mlr
from hyperspace_tpu.manifolds import PoincareBall, smath
from hyperspace_tpu.manifolds.maps import lorentz_to_ball


def hyp_mlr_logits(
    x: jax.Array, p: jax.Array, a: jax.Array, c
) -> jax.Array:
    """Hyperbolic MLR logits — naive Möbius form (the test oracle).

    x: [..., d] points on the ball; p: [K, d] hyperplane points (on the
    ball); a: [K, d] normals (tangent at p_k). Returns [..., K].

    Materializes z_k = (−p_k) ⊕ x per (point, class) pair; the layers
    below call the fused kernel (hyperspace_tpu/kernels/mlr.py) instead,
    which removes that [..., K, d] intermediate.
    """
    ball = PoincareBall(c)
    cc = jnp.asarray(c, x.dtype)
    sc = smath.clamp_min(smath.sqrt_c(cc), smath.min_norm(x.dtype))
    z = ball.mobius_add(-p, x[..., None, :])  # [..., K, d]
    z2 = smath.sq_norm(z)[..., 0]  # [..., K]
    za = jnp.sum(z * a, axis=-1)  # [..., K]
    a_norm = smath.clamp_min(
        smath.safe_norm(a, keepdims=False), smath.min_norm(x.dtype)
    )  # [K]
    lam_p = ball.lambda_x(p, keepdims=False)  # [K]
    denom = smath.clamp_min(1.0 - cc * z2, smath.eps_for(x.dtype)) * a_norm
    arg = 2.0 * sc * za / denom
    return (lam_p * a_norm / sc) * jnp.arcsinh(arg)


def _mlr_apply(module: nn.Module, xb: jax.Array, ball: PoincareBall,
               num_classes: int, p_init: Callable, a_init: Callable) -> jax.Array:
    """Shared param declaration + logits for ball-coordinate inputs.

    Hyperplane points p_k are stored as origin-tangent vectors (exp0 in the
    forward pass — see hyperspace_tpu/nn/layers.py parameterization note;
    expmap0 already ends in proj).
    """
    d = xb.shape[-1]
    p_t = module.param("p_tangent", p_init, (num_classes, d), xb.dtype)
    a = module.param("a", a_init, (num_classes, d), xb.dtype)
    p = ball.expmap0(p_t)
    return hyp_mlr(xb, p, a, ball.c)


class HypMLR(nn.Module):
    """Hyperbolic softmax head for ball-valued features."""

    num_classes: int
    manifold: PoincareBall
    p_init: Callable = nn.initializers.zeros
    a_init: Callable = nn.initializers.glorot_uniform()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return _mlr_apply(self, x, self.manifold, self.num_classes, self.p_init, self.a_init)


class LorentzMLR(nn.Module):
    """Hyperbolic softmax head for hyperboloid-valued features.

    Maps points to the isometric Poincaré ball, then applies ball MLR.
    """

    num_classes: int
    manifold: object  # Lorentz
    p_init: Callable = nn.initializers.zeros
    a_init: Callable = nn.initializers.glorot_uniform()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.manifold.c
        xb = lorentz_to_ball(x, c)
        return _mlr_apply(self, xb, PoincareBall(c), self.num_classes, self.p_init, self.a_init)
