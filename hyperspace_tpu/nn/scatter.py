"""Sorted symmetric segment aggregation — the TPU answer to irregular
graph scatter (SURVEY.md §7 hard-part #3).

Two pieces stack here, both exploiting the receiver-sorted edge layout
guaranteed by ``data.graphs.prepare``:

1. **Sorted both ways.** The forward aggregation

       out[r] = Σ_e  w_e · h[senders_e]        (receivers sorted ascending)

   scatters by receiver — sorted.  Autodiff's transpose scatters by
   *sender*, unsorted in this layout.  For a **symmetric** edge list
   (every (u, v) stored with its reverse (v, u)) there is an involutive
   permutation π with senders = receivers∘π; re-indexing the VJP sum
   e → π(e) turns the sender-scatter into another receiver-scatter:

       dh[i] = Σ_e w_e ḡ[r_e] δ(s_e = i) = Σ_e w_{π(e)} ḡ[s_e] δ(r_e = i)

   i.e. ``dh = segment_sum(w[π] · ḡ[senders], receivers)`` — sorted
   again.  Only the scalar weights get permuted; the [E, D] tensors
   never do.  Padding edges carry w = 0 and map to themselves under π
   (both arranged by ``prepare``), keeping π a bijection.

2. **Scatter as matmul.** With a CSR work-item plan (also built by
   ``prepare``), each sorted segment-sum dispatches to the block-CSR
   one-hot-matmul Pallas kernel
   (:func:`hyperspace_tpu.kernels.segment.csr_segment_sum`) instead of
   XLA's serialized scatter — ~2.4× at ogbn-arxiv scale on v5e, in both
   the forward and the re-indexed backward pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.segment import csr_segment_sum


def _sorted_segsum(vals, receivers, pb, pc, pf, num_segments):
    if pb is not None:
        return csr_segment_sum(vals, receivers, (pb, pc, pf), num_segments)
    # match the kernel's accumulate-in-≥f32 contract on the XLA fallback:
    # scatter-add in the message dtype would sum thousands of bf16 terms
    # on hub nodes (promote_types keeps f64 accumulation exact under x64)
    acc_dt = jnp.promote_types(vals.dtype, jnp.float32)
    acc = jax.ops.segment_sum(vals.astype(acc_dt), receivers,
                              num_segments, indices_are_sorted=True)
    return acc.astype(vals.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def sym_segment_aggregate(
    h: jax.Array,          # [N, D] node values
    w: jax.Array,          # [E] edge weights (0 on padding edges)
    senders: jax.Array,    # [E] int32
    receivers: jax.Array,  # [E] int32, sorted ascending
    rev_perm: jax.Array,   # [E] int32 involution: edge -> its reverse
    plan_block,            # [T] int32 CSR work items, or None (XLA path)
    plan_chunk,
    plan_first,
    num_segments: int,
    with_dw: bool = True,  # False skips the weight gradient (static w)
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e]; see module doc."""
    return _sorted_segsum(w[:, None] * h[senders], receivers,
                          plan_block, plan_chunk, plan_first, num_segments)


def _agg_fwd(h, w, senders, receivers, rev_perm, pb, pc, pf,
             num_segments, with_dw):
    out = _sorted_segsum(w[:, None] * h[senders], receivers, pb, pc, pf,
                         num_segments)
    return out, (h, w, senders, receivers, rev_perm, pb, pc, pf)


def _agg_bwd(num_segments, with_dw, res, g):
    h, w, senders, receivers, rev_perm, pb, pc, pf = res
    g_s = g[senders]                     # cheap unsorted gather, [E, D]
    dh = _sorted_segsum(w[rev_perm][:, None] * g_s, receivers, pb, pc, pf,
                        num_segments)
    dw = (jnp.sum(g[receivers] * h[senders], axis=-1) if with_dw
          else jnp.zeros_like(w))
    return dh, dw, None, None, None, None, None, None


sym_segment_aggregate.defvjp(_agg_fwd, _agg_bwd)


# --- per-edge scalar picks with planned-scatter VJPs --------------------------
#
# logits_e = α_src[s_e] + α_dst[r_e] (GAT-style attention) backpropagates a
# per-edge scalar into per-node scalars: a scatter-add that XLA serializes
# (sorted or not).  Both directions route through the block-CSR scalar
# reduction instead — the sender direction via the involution π
# (s∘π = r, same identity as sym_segment_aggregate).


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def pick_senders(alpha, senders, receivers, rev_perm, pb, pc, pf,
                 num_segments: int):
    """alpha[senders] with a receiver-sorted planned-scatter VJP."""
    return alpha[senders]


def _ps_fwd(alpha, senders, receivers, rev_perm, pb, pc, pf, num_segments):
    return alpha[senders], (receivers, rev_perm, pb, pc, pf)


def _ps_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    receivers, rev_perm, pb, pc, pf = res
    d = csr_segment_reduce_1d(g[rev_perm], receivers, (pb, pc, pf),
                              num_segments, op="sum")
    return d, None, None, None, None, None, None


pick_senders.defvjp(_ps_fwd, _ps_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def pick_receivers(alpha, receivers, pb, pc, pf, num_segments: int):
    """alpha[receivers] with a planned-scatter VJP (receivers sorted)."""
    return alpha[receivers]


def _pr_fwd(alpha, receivers, pb, pc, pf, num_segments):
    return alpha[receivers], (receivers, pb, pc, pf)


def _pr_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    receivers, pb, pc, pf = res
    d = csr_segment_reduce_1d(g, receivers, (pb, pc, pf),
                              num_segments, op="sum")
    return d, None, None, None, None


pick_receivers.defvjp(_pr_fwd, _pr_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def planned_segment_sum_1d(vals, receivers, pb, pc, pf, num_segments: int):
    """Differentiable per-segment scalar sum on the CSR plan.

    Forward: ``kernels.segment.csr_segment_reduce_1d(op="sum")``;
    VJP: ``d_vals = ḡ[receivers]`` — one row gather, no scatter.
    """
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    return csr_segment_reduce_1d(vals, receivers, (pb, pc, pf),
                                 num_segments, op="sum")


def _pss_fwd(vals, receivers, pb, pc, pf, num_segments):
    return (planned_segment_sum_1d(vals, receivers, pb, pc, pf, num_segments),
            receivers)


def _pss_bwd(num_segments, receivers, g):
    return g[receivers], None, None, None, None


planned_segment_sum_1d.defvjp(_pss_fwd, _pss_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def planned_segment_max_1d(vals, receivers, pb, pc, pf, num_segments: int):
    """Per-segment scalar max on the CSR plan, differentiation-safe.

    The cotangent is zero by construction: the only use is the stable-
    softmax max shift, which the softmax value is invariant to (callers
    treat it as a constant).  Without this wrapper jax.grad would trace
    the pallas_call's missing JVP rule even under stop_gradient.
    """
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    return csr_segment_reduce_1d(vals, receivers, (pb, pc, pf),
                                 num_segments, op="max")


def _psm_fwd(vals, receivers, pb, pc, pf, num_segments):
    return (planned_segment_max_1d(vals, receivers, pb, pc, pf, num_segments),
            receivers)


def _psm_bwd(num_segments, receivers, g):
    return (jnp.zeros(receivers.shape, g.dtype), None, None, None, None)


planned_segment_max_1d.defvjp(_psm_fwd, _psm_bwd)


# --- cluster-pair aggregation (kernels/cluster.py) with the same symmetric
# backward: clustered and straggler subsets are each closed under the edge
# involution (equal pair/mirror-pair counts), so dh runs the identical
# two-path program on (ḡ, w_bwd).  Mean aggregation only — weights are
# static per graph and precomputed host-side (including the reverse-edge
# weights, so the backward needs no index lookup).


class ClusterAgg:
    """Device arrays of a host `kernels.cluster.build_cluster_split`.

    Registered as a pytree so it can ride inside DeviceGraph.  Static
    plan shapes are leaves (int32 arrays), nothing auxiliary.  The
    optional straggler involution/mask (attention; see ClusterSplit doc)
    are None when the split was built without ``rev_perm``.
    """

    # gate for the attention cluster path (cluster_att_partial): the
    # r04 weight-ROUTING path was a wash at any realistic fraction
    # because its static gathers added [E] passes back; the r05 in-tile
    # kernels delete those, so the gate is just "enough clustered edges
    # to beat the kernel's own grid overhead" — the same shape as the
    # mean path's min_pair_edges threshold.  Measured r05 on-chip
    # (docs/benchmarks.md): at the bench graph's 39% clustered fraction
    # the split path runs the att step at 0.291 s vs 0.390 s without
    # (−25%); the win scales with the fraction, and the kernel grid is
    # tiny below ~15%, so the gate sits where the mean-path lever also
    # starts paying.
    ATT_MIN_FRAC = 0.15

    def __init__(self, c_recv, c_send, c_wf, c_wb, c_plan,
                 s_recv, s_send, s_wf, s_wb, s_plan,
                 s_rev_local=None, s_mask=None, use_att_cluster: bool = False):
        self.c_recv, self.c_send = c_recv, c_send
        self.c_wf, self.c_wb = c_wf, c_wb
        self.c_plan = c_plan
        self.s_recv, self.s_send = s_recv, s_send
        self.s_wf, self.s_wb = s_wf, s_wb
        self.s_plan = s_plan
        self.s_rev_local = s_rev_local
        self.s_mask = s_mask
        self.use_att_cluster = bool(use_att_cluster)

    @property
    def att_ok(self) -> bool:
        """Whether attention should take the in-tile cluster path:
        straggler involution present AND the clustered fraction clears
        ATT_MIN_FRAC (decided host-side at to_device time — static
        under jit)."""
        return self.s_rev_local is not None and self.use_att_cluster

    def tree_flatten(self):
        return ((self.c_recv, self.c_send, self.c_wf, self.c_wb,
                 tuple(self.c_plan), self.s_recv, self.s_send, self.s_wf,
                 self.s_wb, tuple(self.s_plan), self.s_rev_local,
                 self.s_mask),
                (self.use_att_cluster,))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, use_att_cluster=aux[0])

    @classmethod
    def from_host(cls, split):
        import jax.numpy as jnp

        dev = lambda a: None if a is None else jnp.asarray(a)
        return cls(dev(split.c_recv), dev(split.c_send), dev(split.c_wf),
                   dev(split.c_wb), tuple(dev(a) for a in split.c_plan),
                   dev(split.s_recv), dev(split.s_send), dev(split.s_wf),
                   dev(split.s_wb), tuple(dev(a) for a in split.s_plan),
                   dev(split.s_rev_local), dev(split.s_mask),
                   use_att_cluster=(split.frac_clustered
                                    >= cls.ATT_MIN_FRAC))


jax.tree_util.register_pytree_node(
    ClusterAgg,
    lambda c: c.tree_flatten(),
    lambda aux, leaves: ClusterAgg.tree_unflatten(aux, leaves))


def _cluster_two_path(h, wf_c, wf_s, agg: ClusterAgg, num_segments: int):
    from hyperspace_tpu.kernels.cluster import cluster_aggregate

    out = cluster_aggregate(h, wf_c, agg.c_recv, agg.c_send,
                            agg.c_plan, num_segments)
    msgs = wf_s.astype(h.dtype)[:, None] * h[agg.s_send]
    out = out + _sorted_segsum(msgs, agg.s_recv, *agg.s_plan,
                               num_segments).astype(out.dtype)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def cluster_sym_aggregate(h, agg: ClusterAgg, num_segments: int):
    """Mean aggregation through the cluster-pair kernel + straggler CSR.

    out[r] = Σ_e w_e h[senders_e] with w the precomputed 1/deg weights;
    ``h`` should already be cast to the aggregation dtype (bf16 messages
    halve the straggler traffic AND let the cluster kernel use the fast
    single-pass MXU mode).
    """
    return _cluster_two_path(h, agg.c_wf, agg.s_wf, agg, num_segments)


def _ca_fwd(h, agg, num_segments):
    return _cluster_two_path(h, agg.c_wf, agg.s_wf, agg, num_segments), agg


def _ca_bwd(num_segments, agg, g):
    # dh[i] = Σ_{e: r_e = i} w_{π(e)} ḡ[s_e] — identical program on
    # (ḡ, w_bwd); both subsets are reversal-closed so the split is exact
    dh = _cluster_two_path(g, agg.c_wb, agg.s_wb, agg, num_segments)
    return dh, None


cluster_sym_aggregate.defvjp(_ca_fwd, _ca_bwd)


# --- fused planned attention aggregation --------------------------------------
#
# The attention layer's cost on TPU is dominated by the NUMBER of
# [E]-length passes, not bytes: a 2.4 M-row gather costs ~28 ms on v5e
# regardless of width (latency-bound).  This op fuses the whole
# softmax-aggregate pipeline around ONE random edge gather:
#
# - forward: alpha_s rides as an extra feature column of h, so the
#   sender pick and the message gather are a single [E, F+1] gather;
#   logits/exp are one fused elementwise pass (bounded-logit softmax —
#   no max machinery, see nn.gcn.bounded_att_logits); numerator and
#   denominator are one block-CSR pass each.
# - backward: the gathered sender rows are SAVED as residuals (a
#   sequential [E, F] write+read ≈ 1.6 ms vs a 28 ms random re-gather),
#   so dw needs no new random gather; the only random backward gather is
#   d_num[senders] for the involution dh; everything else is static-
#   permutation gathers, sorted gathers, and CSR scalar reductions.
#
# The op is a PARTIAL: it returns the unnormalized [N, F+1] (num | den)
# sums so a second partial over a different edge subset (the in-tile
# cluster kernel) can be added before the one division
# (:func:`att_combine`).  The full-edge-list composition is
# :func:`att_aggregate_planned`.


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10))
def att_partial_planned(h, alpha_s, alpha_r, senders, receivers, rev_perm,
                        edge_mask, plan, num_segments: int, agg_dtype,
                        negative_slope: float):
    """Unnormalized attention partials on the planned layout:
    ``out[r] = Σ_e w_e·[h[s_e] | 1]`` (f32 [N, F+1]) with
    ``w_e = exp(bounded_logits(α_s[s_e]+α_r[r_e]))`` and 0 on masked
    edges.  ``edge_mask`` is the bool edge-validity mask (a constant of
    the graph — no cotangent).  Oracle: the unfused pick/exp/segsum
    chain in tests.
    """
    nd, _ = _att_partial_impl(h, alpha_s, alpha_r, senders, receivers,
                              edge_mask, plan, num_segments, agg_dtype,
                              negative_slope)
    return nd


def _att_partial_impl(h, alpha_s, alpha_r, senders, receivers, edge_mask,
                      plan, num_segments, agg_dtype, negative_slope):
    from hyperspace_tpu.nn.gcn import bounded_att_logits

    pb, pc, pf = plan
    f = h.shape[-1]
    ha = jnp.concatenate([h, alpha_s[:, None].astype(h.dtype)], axis=1)
    hs_a = ha[senders]                       # the ONE random gather
    h_s, a_se = hs_a[:, :f], hs_a[:, f]
    a_re = alpha_r[receivers]                # sorted gather
    lm = bounded_att_logits(a_se + a_re, negative_slope)
    w = jnp.where(edge_mask, jnp.exp(lm), 0.0)
    h_in = h_s if agg_dtype is None else h_s.astype(agg_dtype)
    w_in = w if agg_dtype is None else w.astype(agg_dtype)
    # numerator and denominator ride ONE CSR pass: the messages carry a
    # constant 1-column, so segsum(w·[h | 1]) = [Σ w·h | Σ w]
    msgs = jnp.concatenate(
        [w_in[:, None] * h_in, w_in[:, None]], axis=1)
    nd = _sorted_segsum(msgs, receivers, pb, pc, pf,
                        num_segments).astype(jnp.float32)
    return nd, (h_in, w_in, lm)


def _att_partial_fwd(h, alpha_s, alpha_r, senders, receivers, rev_perm,
                     edge_mask, plan, num_segments, agg_dtype,
                     negative_slope):
    nd, (h_in, w_in, lm) = _att_partial_impl(
        h, alpha_s, alpha_r, senders, receivers, edge_mask, plan,
        num_segments, agg_dtype, negative_slope)
    return nd, (h_in, w_in, lm, senders, receivers, rev_perm,
                edge_mask, plan, jnp.zeros((0,), h.dtype))


def _att_partial_bwd(num_segments, agg_dtype, negative_slope, res, g):
    from hyperspace_tpu.kernels.segment import (
        csr_att_bwd_edges,
        csr_segment_reduce_1d,
    )
    from hyperspace_tpu.nn.gcn import ATT_LOGIT_BOUND as B

    (h_in, w_in, lm, senders, receivers, rev_perm, edge_mask, plan,
     h_proto) = res
    h_dtype = h_proto.dtype
    f = h_in.shape[-1]
    pb, pc, pf = plan
    # the cotangent IS the fused d(num)|d(den) block ([N, F+1] f32):
    # ONE gather serves both directions (mirrors the forward's fused
    # num|den aggregation)
    dn_ext = g.astype(jnp.float32)
    dn_dt = dn_ext if agg_dtype is None else dn_ext.astype(agg_dtype)
    dn_s = dn_dt[senders]                # the one random backward gather
    # dh via the involution: sender-scatter becomes a receiver-scatter
    # (the extra lane aggregates Σ w·d_den — sliced off)
    dh = _sorted_segsum(w_in[rev_perm][:, None] * dn_s, receivers,
                        pb, pc, pf, num_segments)[:, :f].astype(h_dtype)
    # dw + softmax chain + d_alpha_r: ONE fused CSR pass — the receiver-
    # side d_num|d_den rows are picked from the resident node block, the
    # ones-augmented residual rows stream by chunk, and the per-receiver
    # reduction accumulates in the same walk (kernels/segment.py)
    # keep the residual stream in its storage dtype (bf16 halves the
    # [E, F+1] HBM read; the kernel upcasts per tile, and a ones column
    # is exact in any float dtype)
    h1 = jnp.concatenate(
        [h_in, jnp.ones_like(w_in, h_in.dtype)[:, None]], axis=1)
    dpre, d_alpha_r = csr_att_bwd_edges(
        dn_ext, h1, jnp.where(edge_mask, w_in.astype(jnp.float32), 0.0),
        lm, receivers, (pb, pc, pf), num_segments, float(B),
        negative_slope)
    d_alpha_s = csr_segment_reduce_1d(dpre[rev_perm], receivers,
                                      (pb, pc, pf), num_segments, op="sum")
    return (dh, d_alpha_s, d_alpha_r, None, None, None, None, None)


att_partial_planned.defvjp(_att_partial_fwd, _att_partial_bwd)


def att_combine(nd: jax.Array, out_dtype) -> jax.Array:
    """num/den of an [N, F+1] attention partial sum (the ONE division,
    applied after all edge-subset partials are added)."""
    num, den = nd[:, :-1], jnp.maximum(nd[:, -1], 1e-15)
    return (num / den[:, None]).astype(out_dtype)


def att_aggregate_planned(h, alpha_s, alpha_r, senders, receivers, rev_perm,
                          edge_mask, plan, num_segments: int, agg_dtype,
                          negative_slope: float):
    """Softmax-attention neighbor aggregation on the planned layout.

    ``out[r] = Σ_e softmax_r(bounded_logits(α_s[s_e]+α_r[r_e])) h[s_e]``
    — numerically identical to the unfused pick/exp/den/aggregate chain
    (the oracle in tests).  Composition of :func:`att_partial_planned`
    and :func:`att_combine` — autodiff of the division produces exactly
    the fused d(num)|d(den) cotangent the partial's VJP consumes.
    """
    nd = att_partial_planned(h, alpha_s, alpha_r, senders, receivers,
                             rev_perm, edge_mask, plan, num_segments,
                             agg_dtype, negative_slope)
    return att_combine(nd, h.dtype)


# --- in-tile attention on the cluster split -----------------------------------
#
# Clustered edges run the kernels/cluster.py fused attention kernels —
# logits, softmax weights, aggregation, and the whole backward computed
# from VMEM-resident endpoint blocks, so the clustered fraction of the
# graph never touches an [E]-length HBM stream in either direction.
# Stragglers run :func:`att_partial_planned` on their own (shorter)
# layout; the two [N, F+1] partials add and divide once.  This is the
# r05 replacement for the r04 weight-routing path, which was measured a
# wash because its static gathers added the [E] passes back.


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def cluster_att_partial(h, alpha_s, alpha_r, agg: ClusterAgg,
                        num_segments: int, negative_slope: float = 0.2):
    """[N, F+1] f32 unnormalized attention partials over the CLUSTERED
    edge subset, logits computed in-tile.  Requires ``agg.att_ok``.
    Twin/oracle: the gathered exp/segsum chain on (c_send, c_recv).
    """
    from hyperspace_tpu.kernels.cluster import cluster_att_fwd
    from hyperspace_tpu.nn.gcn import ATT_LOGIT_BOUND as B

    return cluster_att_fwd(h, alpha_s, alpha_r, agg.c_recv, agg.c_send,
                           agg.c_plan, num_segments, negative_slope,
                           float(B))


def _cap_fwd(h, alpha_s, alpha_r, agg, num_segments, negative_slope):
    return (cluster_att_partial(h, alpha_s, alpha_r, agg, num_segments,
                                negative_slope),
            (h, alpha_s, alpha_r, agg))


def _cap_bwd(num_segments, negative_slope, res, g):
    from hyperspace_tpu.kernels.cluster import cluster_att_bwd
    from hyperspace_tpu.nn.gcn import ATT_LOGIT_BOUND as B

    h, alpha_s, alpha_r, agg = res
    dh, da_s, da_r = cluster_att_bwd(
        g.astype(jnp.float32), h, alpha_s, alpha_r, agg.c_recv,
        agg.c_send, agg.c_plan, num_segments, negative_slope, float(B))
    return (dh.astype(h.dtype), da_s.astype(alpha_s.dtype),
            da_r.astype(alpha_r.dtype), None)  # agg: graph constant


cluster_att_partial.defvjp(_cap_fwd, _cap_bwd)
