"""Sorted symmetric segment aggregation — the TPU answer to irregular
graph scatter (SURVEY.md §7 hard-part #3).

Two pieces stack here, both exploiting the receiver-sorted edge layout
guaranteed by ``data.graphs.prepare``:

1. **Sorted both ways.** The forward aggregation

       out[r] = Σ_e  w_e · h[senders_e]        (receivers sorted ascending)

   scatters by receiver — sorted.  Autodiff's transpose scatters by
   *sender*, unsorted in this layout.  For a **symmetric** edge list
   (every (u, v) stored with its reverse (v, u)) there is an involutive
   permutation π with senders = receivers∘π; re-indexing the VJP sum
   e → π(e) turns the sender-scatter into another receiver-scatter:

       dh[i] = Σ_e w_e ḡ[r_e] δ(s_e = i) = Σ_e w_{π(e)} ḡ[s_e] δ(r_e = i)

   i.e. ``dh = segment_sum(w[π] · ḡ[senders], receivers)`` — sorted
   again.  Only the scalar weights get permuted; the [E, D] tensors
   never do.  Padding edges carry w = 0 and map to themselves under π
   (both arranged by ``prepare``), keeping π a bijection.

2. **Scatter as matmul.** With a CSR work-item plan (also built by
   ``prepare``), each sorted segment-sum dispatches to the block-CSR
   one-hot-matmul Pallas kernel
   (:func:`hyperspace_tpu.kernels.segment.csr_segment_sum`) instead of
   XLA's serialized scatter — ~2.4× at ogbn-arxiv scale on v5e, in both
   the forward and the re-indexed backward pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.segment import csr_segment_sum


def _sorted_segsum(vals, receivers, pb, pc, pf, num_segments):
    if pb is not None:
        return csr_segment_sum(vals, receivers, (pb, pc, pf), num_segments)
    # match the kernel's accumulate-in-≥f32 contract on the XLA fallback:
    # scatter-add in the message dtype would sum thousands of bf16 terms
    # on hub nodes (promote_types keeps f64 accumulation exact under x64)
    acc_dt = jnp.promote_types(vals.dtype, jnp.float32)
    acc = jax.ops.segment_sum(vals.astype(acc_dt), receivers,
                              num_segments, indices_are_sorted=True)
    return acc.astype(vals.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def sym_segment_aggregate(
    h: jax.Array,          # [N, D] node values
    w: jax.Array,          # [E] edge weights (0 on padding edges)
    senders: jax.Array,    # [E] int32
    receivers: jax.Array,  # [E] int32, sorted ascending
    rev_perm: jax.Array,   # [E] int32 involution: edge -> its reverse
    plan_block,            # [T] int32 CSR work items, or None (XLA path)
    plan_chunk,
    plan_first,
    num_segments: int,
    with_dw: bool = True,  # False skips the weight gradient (static w)
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e]; see module doc."""
    return _sorted_segsum(w[:, None] * h[senders], receivers,
                          plan_block, plan_chunk, plan_first, num_segments)


def _agg_fwd(h, w, senders, receivers, rev_perm, pb, pc, pf,
             num_segments, with_dw):
    out = _sorted_segsum(w[:, None] * h[senders], receivers, pb, pc, pf,
                         num_segments)
    return out, (h, w, senders, receivers, rev_perm, pb, pc, pf)


def _agg_bwd(num_segments, with_dw, res, g):
    h, w, senders, receivers, rev_perm, pb, pc, pf = res
    g_s = g[senders]                     # cheap unsorted gather, [E, D]
    dh = _sorted_segsum(w[rev_perm][:, None] * g_s, receivers, pb, pc, pf,
                        num_segments)
    dw = (jnp.sum(g[receivers] * h[senders], axis=-1) if with_dw
          else jnp.zeros_like(w))
    return dh, dw, None, None, None, None, None, None


sym_segment_aggregate.defvjp(_agg_fwd, _agg_bwd)


# --- per-edge scalar picks with planned-scatter VJPs --------------------------
#
# logits_e = α_src[s_e] + α_dst[r_e] (GAT-style attention) backpropagates a
# per-edge scalar into per-node scalars: a scatter-add that XLA serializes
# (sorted or not).  Both directions route through the block-CSR scalar
# reduction instead — the sender direction via the involution π
# (s∘π = r, same identity as sym_segment_aggregate).


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def pick_senders(alpha, senders, receivers, rev_perm, pb, pc, pf,
                 num_segments: int):
    """alpha[senders] with a receiver-sorted planned-scatter VJP."""
    return alpha[senders]


def _ps_fwd(alpha, senders, receivers, rev_perm, pb, pc, pf, num_segments):
    return alpha[senders], (receivers, rev_perm, pb, pc, pf)


def _ps_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    receivers, rev_perm, pb, pc, pf = res
    d = csr_segment_reduce_1d(g[rev_perm], receivers, (pb, pc, pf),
                              num_segments, op="sum")
    return d, None, None, None, None, None, None


pick_senders.defvjp(_ps_fwd, _ps_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def pick_receivers(alpha, receivers, pb, pc, pf, num_segments: int):
    """alpha[receivers] with a planned-scatter VJP (receivers sorted)."""
    return alpha[receivers]


def _pr_fwd(alpha, receivers, pb, pc, pf, num_segments):
    return alpha[receivers], (receivers, pb, pc, pf)


def _pr_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    receivers, pb, pc, pf = res
    d = csr_segment_reduce_1d(g, receivers, (pb, pc, pf),
                              num_segments, op="sum")
    return d, None, None, None, None


pick_receivers.defvjp(_pr_fwd, _pr_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def planned_segment_sum_1d(vals, receivers, pb, pc, pf, num_segments: int):
    """Differentiable per-segment scalar sum on the CSR plan.

    Forward: ``kernels.segment.csr_segment_reduce_1d(op="sum")``;
    VJP: ``d_vals = ḡ[receivers]`` — one row gather, no scatter.
    """
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    return csr_segment_reduce_1d(vals, receivers, (pb, pc, pf),
                                 num_segments, op="sum")


def _pss_fwd(vals, receivers, pb, pc, pf, num_segments):
    return (planned_segment_sum_1d(vals, receivers, pb, pc, pf, num_segments),
            receivers)


def _pss_bwd(num_segments, receivers, g):
    return g[receivers], None, None, None, None


planned_segment_sum_1d.defvjp(_pss_fwd, _pss_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def planned_segment_max_1d(vals, receivers, pb, pc, pf, num_segments: int):
    """Per-segment scalar max on the CSR plan, differentiation-safe.

    The cotangent is zero by construction: the only use is the stable-
    softmax max shift, which the softmax value is invariant to (callers
    treat it as a constant).  Without this wrapper jax.grad would trace
    the pallas_call's missing JVP rule even under stop_gradient.
    """
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    return csr_segment_reduce_1d(vals, receivers, (pb, pc, pf),
                                 num_segments, op="max")


def _psm_fwd(vals, receivers, pb, pc, pf, num_segments):
    return (planned_segment_max_1d(vals, receivers, pb, pc, pf, num_segments),
            receivers)


def _psm_bwd(num_segments, receivers, g):
    return (jnp.zeros(receivers.shape, g.dtype), None, None, None, None)


planned_segment_max_1d.defvjp(_psm_fwd, _psm_bwd)


# --- cluster-pair aggregation (kernels/cluster.py) with the same symmetric
# backward: clustered and straggler subsets are each closed under the edge
# involution (equal pair/mirror-pair counts), so dh runs the identical
# two-path program on (ḡ, w_bwd).  Mean aggregation only — weights are
# static per graph and precomputed host-side (including the reverse-edge
# weights, so the backward needs no index lookup).


class ClusterAgg:
    """Device arrays of a host `kernels.cluster.build_cluster_split`.

    Registered as a pytree so it can ride inside DeviceGraph.  Static
    plan shapes are leaves (int32 arrays), nothing auxiliary.
    """

    def __init__(self, c_recv, c_send, c_wf, c_wb, c_plan,
                 s_recv, s_send, s_wf, s_wb, s_plan):
        self.c_recv, self.c_send = c_recv, c_send
        self.c_wf, self.c_wb = c_wf, c_wb
        self.c_plan = c_plan
        self.s_recv, self.s_send = s_recv, s_send
        self.s_wf, self.s_wb = s_wf, s_wb
        self.s_plan = s_plan

    def tree_flatten(self):
        return ((self.c_recv, self.c_send, self.c_wf, self.c_wb,
                 tuple(self.c_plan), self.s_recv, self.s_send, self.s_wf,
                 self.s_wb, tuple(self.s_plan)), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    @classmethod
    def from_host(cls, split):
        import jax.numpy as jnp

        dev = lambda a: jnp.asarray(a)
        return cls(dev(split.c_recv), dev(split.c_send), dev(split.c_wf),
                   dev(split.c_wb), tuple(dev(a) for a in split.c_plan),
                   dev(split.s_recv), dev(split.s_send), dev(split.s_wf),
                   dev(split.s_wb), tuple(dev(a) for a in split.s_plan))


jax.tree_util.register_pytree_node(
    ClusterAgg,
    lambda c: c.tree_flatten(),
    lambda aux, leaves: ClusterAgg.tree_unflatten(aux, leaves))


def _cluster_two_path(h, wf_c, wf_s, agg: ClusterAgg, num_segments: int):
    from hyperspace_tpu.kernels.cluster import cluster_aggregate

    out = cluster_aggregate(h, wf_c, agg.c_recv, agg.c_send,
                            agg.c_plan, num_segments)
    msgs = wf_s.astype(h.dtype)[:, None] * h[agg.s_send]
    out = out + _sorted_segsum(msgs, agg.s_recv, *agg.s_plan,
                               num_segments).astype(out.dtype)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def cluster_sym_aggregate(h, agg: ClusterAgg, num_segments: int):
    """Mean aggregation through the cluster-pair kernel + straggler CSR.

    out[r] = Σ_e w_e h[senders_e] with w the precomputed 1/deg weights;
    ``h`` should already be cast to the aggregation dtype (bf16 messages
    halve the straggler traffic AND let the cluster kernel use the fast
    single-pass MXU mode).
    """
    return _cluster_two_path(h, agg.c_wf, agg.s_wf, agg, num_segments)


def _ca_fwd(h, agg, num_segments):
    return _cluster_two_path(h, agg.c_wf, agg.s_wf, agg, num_segments), agg


def _ca_bwd(num_segments, agg, g):
    # dh[i] = Σ_{e: r_e = i} w_{π(e)} ḡ[s_e] — identical program on
    # (ḡ, w_bwd); both subsets are reversal-closed so the split is exact
    dh = _cluster_two_path(g, agg.c_wb, agg.s_wb, agg, num_segments)
    return dh, None


cluster_sym_aggregate.defvjp(_ca_fwd, _ca_bwd)
