"""Sorted symmetric segment aggregation — the TPU answer to irregular
graph scatter (SURVEY.md §7 hard-part #3).

Two pieces stack here, both exploiting the receiver-sorted edge layout
guaranteed by ``data.graphs.prepare``:

1. **Sorted both ways.** The forward aggregation

       out[r] = Σ_e  w_e · h[senders_e]        (receivers sorted ascending)

   scatters by receiver — sorted.  Autodiff's transpose scatters by
   *sender*, unsorted in this layout.  For a **symmetric** edge list
   (every (u, v) stored with its reverse (v, u)) there is an involutive
   permutation π with senders = receivers∘π; re-indexing the VJP sum
   e → π(e) turns the sender-scatter into another receiver-scatter:

       dh[i] = Σ_e w_e ḡ[r_e] δ(s_e = i) = Σ_e w_{π(e)} ḡ[s_e] δ(r_e = i)

   i.e. ``dh = segment_sum(w[π] · ḡ[senders], receivers)`` — sorted
   again.  Only the scalar weights get permuted; the [E, D] tensors
   never do.  Padding edges carry w = 0 and map to themselves under π
   (both arranged by ``prepare``), keeping π a bijection.

2. **Scatter as matmul.** With a CSR work-item plan (also built by
   ``prepare``), each sorted segment-sum dispatches to the block-CSR
   one-hot-matmul Pallas kernel
   (:func:`hyperspace_tpu.kernels.segment.csr_segment_sum`) instead of
   XLA's serialized scatter — ~2.4× at ogbn-arxiv scale on v5e, in both
   the forward and the re-indexed backward pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.segment import csr_segment_sum


def _sorted_segsum(vals, receivers, pb, pc, pf, num_segments):
    if pb is not None:
        return csr_segment_sum(vals, receivers, (pb, pc, pf), num_segments)
    # match the kernel's accumulate-in-≥f32 contract on the XLA fallback:
    # scatter-add in the message dtype would sum thousands of bf16 terms
    # on hub nodes (promote_types keeps f64 accumulation exact under x64)
    acc_dt = jnp.promote_types(vals.dtype, jnp.float32)
    acc = jax.ops.segment_sum(vals.astype(acc_dt), receivers,
                              num_segments, indices_are_sorted=True)
    return acc.astype(vals.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def sym_segment_aggregate(
    h: jax.Array,          # [N, D] node values
    w: jax.Array,          # [E] edge weights (0 on padding edges)
    senders: jax.Array,    # [E] int32
    receivers: jax.Array,  # [E] int32, sorted ascending
    rev_perm: jax.Array,   # [E] int32 involution: edge -> its reverse
    plan_block,            # [T] int32 CSR work items, or None (XLA path)
    plan_chunk,
    plan_first,
    num_segments: int,
    with_dw: bool = True,  # False skips the weight gradient (static w)
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e]; see module doc."""
    return _sorted_segsum(w[:, None] * h[senders], receivers,
                          plan_block, plan_chunk, plan_first, num_segments)


def _agg_fwd(h, w, senders, receivers, rev_perm, pb, pc, pf,
             num_segments, with_dw):
    out = _sorted_segsum(w[:, None] * h[senders], receivers, pb, pc, pf,
                         num_segments)
    return out, (h, w, senders, receivers, rev_perm, pb, pc, pf)


def _agg_bwd(num_segments, with_dw, res, g):
    h, w, senders, receivers, rev_perm, pb, pc, pf = res
    g_s = g[senders]                     # cheap unsorted gather, [E, D]
    dh = _sorted_segsum(w[rev_perm][:, None] * g_s, receivers, pb, pc, pf,
                        num_segments)
    dw = (jnp.sum(g[receivers] * h[senders], axis=-1) if with_dw
          else jnp.zeros_like(w))
    return dh, dw, None, None, None, None, None, None


sym_segment_aggregate.defvjp(_agg_fwd, _agg_bwd)
