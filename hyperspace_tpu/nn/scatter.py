"""Sorted symmetric segment aggregation — the TPU answer to irregular
graph scatter (SURVEY.md §7 hard-part #3).

XLA's scatter-add on TPU is ~2.3× faster when the segment ids are sorted
(measured at ogbn-arxiv scale: 2.4 M × 128 f32 rows, 46 ms unsorted →
20 ms sorted).  The forward aggregation

    out[r] = Σ_e  w_e · h[senders_e]        (receivers sorted ascending)

scatters by receiver, so sorting edges by receiver makes the forward
fast — but autodiff's transpose scatters by *sender*, which is unsorted
in that layout, giving the slow path back in the backward pass.

For a **symmetric** edge list (every (u, v) stored with its reverse
(v, u) — guaranteed by ``data.graphs.prepare``) there is an involutive
permutation π with  senders = receivers∘π,  receivers = senders∘π.
Re-indexing the VJP sum e → π(e) turns the sender-scatter into another
receiver-scatter:

    dh[i] = Σ_e w_e ḡ[r_e] δ(s_e = i)  =  Σ_e w_{π(e)} ḡ[s_e] δ(r_e = i)

i.e. ``dh = segment_sum(w[π] · ḡ[senders], receivers)`` — sorted again.
Only the scalar weights get permuted; the [E, D] tensors never do.  The
weight gradient is two gathers: ``dw_e = ⟨ḡ[r_e], h[s_e]⟩``.

Padding edges must carry w = 0 and map to themselves under π (both
arranged by ``prepare``), keeping π a bijection on the padded index set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def sym_segment_aggregate(
    h: jax.Array,          # [N, D] node values
    w: jax.Array,          # [E] edge weights (0 on padding edges)
    senders: jax.Array,    # [E] int32
    receivers: jax.Array,  # [E] int32, sorted ascending
    rev_perm: jax.Array,   # [E] int32 involution: edge -> its reverse
    num_segments: int,
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e]; see module doc."""
    return jax.ops.segment_sum(
        w[:, None] * h[senders], receivers, num_segments,
        indices_are_sorted=True)


def _agg_fwd(h, w, senders, receivers, rev_perm, num_segments):
    out = jax.ops.segment_sum(
        w[:, None] * h[senders], receivers, num_segments,
        indices_are_sorted=True)
    return out, (h, w, senders, receivers, rev_perm)


def _agg_bwd(num_segments, res, g):
    h, w, senders, receivers, rev_perm = res
    g_s = g[senders]                     # cheap unsorted gather, [E, D]
    dh = jax.ops.segment_sum(
        w[rev_perm][:, None] * g_s, receivers, num_segments,
        indices_are_sorted=True)
    dw = jnp.sum(g[receivers] * h[senders], axis=-1)
    return dh, dw, None, None, None


sym_segment_aggregate.defvjp(_agg_fwd, _agg_bwd)
