"""Sorted symmetric segment aggregation — the TPU answer to irregular
graph scatter (SURVEY.md §7 hard-part #3).

Two pieces stack here, both exploiting the receiver-sorted edge layout
guaranteed by ``data.graphs.prepare``:

1. **Sorted both ways.** The forward aggregation

       out[r] = Σ_e  w_e · h[senders_e]        (receivers sorted ascending)

   scatters by receiver — sorted.  Autodiff's transpose scatters by
   *sender*, unsorted in this layout.  For a **symmetric** edge list
   (every (u, v) stored with its reverse (v, u)) there is an involutive
   permutation π with senders = receivers∘π; re-indexing the VJP sum
   e → π(e) turns the sender-scatter into another receiver-scatter:

       dh[i] = Σ_e w_e ḡ[r_e] δ(s_e = i) = Σ_e w_{π(e)} ḡ[s_e] δ(r_e = i)

   i.e. ``dh = segment_sum(w[π] · ḡ[senders], receivers)`` — sorted
   again.  Only the scalar weights get permuted; the [E, D] tensors
   never do.  Padding edges carry w = 0 and map to themselves under π
   (both arranged by ``prepare``), keeping π a bijection.

2. **Scatter as matmul.** With a CSR work-item plan (also built by
   ``prepare``), each sorted segment-sum dispatches to the block-CSR
   one-hot-matmul Pallas kernel
   (:func:`hyperspace_tpu.kernels.segment.csr_segment_sum`) instead of
   XLA's serialized scatter — ~2.4× at ogbn-arxiv scale on v5e, in both
   the forward and the re-indexed backward pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hyperspace_tpu.kernels.segment import csr_segment_sum


def _sorted_segsum(vals, receivers, pb, pc, pf, num_segments):
    if pb is not None:
        return csr_segment_sum(vals, receivers, (pb, pc, pf), num_segments)
    # match the kernel's accumulate-in-≥f32 contract on the XLA fallback:
    # scatter-add in the message dtype would sum thousands of bf16 terms
    # on hub nodes (promote_types keeps f64 accumulation exact under x64)
    acc_dt = jnp.promote_types(vals.dtype, jnp.float32)
    acc = jax.ops.segment_sum(vals.astype(acc_dt), receivers,
                              num_segments, indices_are_sorted=True)
    return acc.astype(vals.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def sym_segment_aggregate(
    h: jax.Array,          # [N, D] node values
    w: jax.Array,          # [E] edge weights (0 on padding edges)
    senders: jax.Array,    # [E] int32
    receivers: jax.Array,  # [E] int32, sorted ascending
    rev_perm: jax.Array,   # [E] int32 involution: edge -> its reverse
    plan_block,            # [T] int32 CSR work items, or None (XLA path)
    plan_chunk,
    plan_first,
    num_segments: int,
    with_dw: bool = True,  # False skips the weight gradient (static w)
) -> jax.Array:
    """out[r] = Σ_{e: receivers_e = r} w_e · h[senders_e]; see module doc."""
    return _sorted_segsum(w[:, None] * h[senders], receivers,
                          plan_block, plan_chunk, plan_first, num_segments)


def _agg_fwd(h, w, senders, receivers, rev_perm, pb, pc, pf,
             num_segments, with_dw):
    out = _sorted_segsum(w[:, None] * h[senders], receivers, pb, pc, pf,
                         num_segments)
    return out, (h, w, senders, receivers, rev_perm, pb, pc, pf)


def _agg_bwd(num_segments, with_dw, res, g):
    h, w, senders, receivers, rev_perm, pb, pc, pf = res
    g_s = g[senders]                     # cheap unsorted gather, [E, D]
    dh = _sorted_segsum(w[rev_perm][:, None] * g_s, receivers, pb, pc, pf,
                        num_segments)
    dw = (jnp.sum(g[receivers] * h[senders], axis=-1) if with_dw
          else jnp.zeros_like(w))
    return dh, dw, None, None, None, None, None, None


sym_segment_aggregate.defvjp(_agg_fwd, _agg_bwd)


# --- per-edge scalar picks with planned-scatter VJPs --------------------------
#
# logits_e = α_src[s_e] + α_dst[r_e] (GAT-style attention) backpropagates a
# per-edge scalar into per-node scalars: a scatter-add that XLA serializes
# (sorted or not).  Both directions route through the block-CSR scalar
# reduction instead — the sender direction via the involution π
# (s∘π = r, same identity as sym_segment_aggregate).


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def pick_senders(alpha, senders, receivers, rev_perm, pb, pc, pf,
                 num_segments: int):
    """alpha[senders] with a receiver-sorted planned-scatter VJP."""
    return alpha[senders]


def _ps_fwd(alpha, senders, receivers, rev_perm, pb, pc, pf, num_segments):
    return alpha[senders], (receivers, rev_perm, pb, pc, pf)


def _ps_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    receivers, rev_perm, pb, pc, pf = res
    d = csr_segment_reduce_1d(g[rev_perm], receivers, (pb, pc, pf),
                              num_segments, op="sum")
    return d, None, None, None, None, None, None


pick_senders.defvjp(_ps_fwd, _ps_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def pick_receivers(alpha, receivers, pb, pc, pf, num_segments: int):
    """alpha[receivers] with a planned-scatter VJP (receivers sorted)."""
    return alpha[receivers]


def _pr_fwd(alpha, receivers, pb, pc, pf, num_segments):
    return alpha[receivers], (receivers, pb, pc, pf)


def _pr_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    receivers, pb, pc, pf = res
    d = csr_segment_reduce_1d(g, receivers, (pb, pc, pf),
                              num_segments, op="sum")
    return d, None, None, None, None


pick_receivers.defvjp(_pr_fwd, _pr_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def planned_segment_sum_1d(vals, receivers, pb, pc, pf, num_segments: int):
    """Differentiable per-segment scalar sum on the CSR plan.

    Forward: ``kernels.segment.csr_segment_reduce_1d(op="sum")``;
    VJP: ``d_vals = ḡ[receivers]`` — one row gather, no scatter.
    """
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    return csr_segment_reduce_1d(vals, receivers, (pb, pc, pf),
                                 num_segments, op="sum")


def _pss_fwd(vals, receivers, pb, pc, pf, num_segments):
    return (planned_segment_sum_1d(vals, receivers, pb, pc, pf, num_segments),
            receivers)


def _pss_bwd(num_segments, receivers, g):
    return g[receivers], None, None, None, None


planned_segment_sum_1d.defvjp(_pss_fwd, _pss_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def planned_segment_max_1d(vals, receivers, pb, pc, pf, num_segments: int):
    """Per-segment scalar max on the CSR plan, differentiation-safe.

    The cotangent is zero by construction: the only use is the stable-
    softmax max shift, which the softmax value is invariant to (callers
    treat it as a constant).  Without this wrapper jax.grad would trace
    the pallas_call's missing JVP rule even under stop_gradient.
    """
    from hyperspace_tpu.kernels.segment import csr_segment_reduce_1d

    return csr_segment_reduce_1d(vals, receivers, (pb, pc, pf),
                                 num_segments, op="max")


def _psm_fwd(vals, receivers, pb, pc, pf, num_segments):
    return (planned_segment_max_1d(vals, receivers, pb, pc, pf, num_segments),
            receivers)


def _psm_bwd(num_segments, receivers, g):
    return (jnp.zeros(receivers.shape, g.dtype), None, None, None, None)


planned_segment_max_1d.defvjp(_psm_fwd, _psm_bwd)


# --- cluster-pair aggregation (kernels/cluster.py) with the same symmetric
# backward: clustered and straggler subsets are each closed under the edge
# involution (equal pair/mirror-pair counts), so dh runs the identical
# two-path program on (ḡ, w_bwd).  Mean aggregation only — weights are
# static per graph and precomputed host-side (including the reverse-edge
# weights, so the backward needs no index lookup).


class ClusterAgg:
    """Device arrays of a host `kernels.cluster.build_cluster_split`.

    Registered as a pytree so it can ride inside DeviceGraph.  Static
    plan shapes are leaves (int32 arrays), nothing auxiliary.  The
    optional weight-routing maps (attention; see ClusterSplit doc) are
    None when the split was built without ``rev_perm``.
    """

    # gate for the weighted (attention) cluster path.  Measured r04:
    # at 8% clustered it is a net loss (0.51 vs 0.50 s att step) AND at
    # 39% it is still a wash (0.500 vs 0.489) — the weight-routing
    # gathers + SDDMM + two-path overhead add [E]-passes, and pass count
    # is what the attention step pays for (28 ms/2.4 M-row gather,
    # width-independent).  The fused att_aggregate_planned beats both,
    # so the gate sits above any realistic fraction until the logits
    # move INSIDE the cluster kernel tiles (future work: alpha tiles are
    # block-resident, so the pick could be a one-hot matmul there).
    # The mean path has no such extra machinery and stays on the cluster
    # kernel at any fraction (its own threshold sweep, r03).
    WEIGHTED_MIN_FRAC = 0.95

    def __init__(self, c_recv, c_send, c_wf, c_wb, c_plan,
                 s_recv, s_send, s_wf, s_wb, s_plan,
                 c_map=None, c_map_rev=None, s_map=None, s_map_rev=None,
                 s_valid=None, inv_map=None, use_weighted: bool = False,
                 ec_pad: int = 0):
        self.c_recv, self.c_send = c_recv, c_send
        self.c_wf, self.c_wb = c_wf, c_wb
        self.c_plan = c_plan
        self.s_recv, self.s_send = s_recv, s_send
        self.s_wf, self.s_wb = s_wf, s_wb
        self.s_plan = s_plan
        self.c_map, self.c_map_rev = c_map, c_map_rev
        self.s_map, self.s_map_rev = s_map, s_map_rev
        self.s_valid, self.inv_map = s_valid, inv_map
        self.use_weighted = bool(use_weighted)
        self.ec_pad = int(ec_pad)

    @property
    def weighted_ok(self) -> bool:
        """Whether attention should take the weighted cluster path: maps
        present AND the clustered fraction clears WEIGHTED_MIN_FRAC
        (decided host-side at to_device time — static under jit)."""
        return self.c_map is not None and self.use_weighted

    def tree_flatten(self):
        return ((self.c_recv, self.c_send, self.c_wf, self.c_wb,
                 tuple(self.c_plan), self.s_recv, self.s_send, self.s_wf,
                 self.s_wb, tuple(self.s_plan), self.c_map, self.c_map_rev,
                 self.s_map, self.s_map_rev, self.s_valid, self.inv_map),
                (self.use_weighted, self.ec_pad))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, use_weighted=aux[0], ec_pad=aux[1])

    @classmethod
    def from_host(cls, split):
        import jax.numpy as jnp

        dev = lambda a: None if a is None else jnp.asarray(a)
        return cls(dev(split.c_recv), dev(split.c_send), dev(split.c_wf),
                   dev(split.c_wb), tuple(dev(a) for a in split.c_plan),
                   dev(split.s_recv), dev(split.s_send), dev(split.s_wf),
                   dev(split.s_wb), tuple(dev(a) for a in split.s_plan),
                   dev(split.c_map), dev(split.c_map_rev), dev(split.s_map),
                   dev(split.s_map_rev), dev(split.s_valid),
                   dev(split.inv_map),
                   use_weighted=(split.frac_clustered
                                 >= cls.WEIGHTED_MIN_FRAC),
                   ec_pad=split.ec_pad)


jax.tree_util.register_pytree_node(
    ClusterAgg,
    lambda c: c.tree_flatten(),
    lambda aux, leaves: ClusterAgg.tree_unflatten(aux, leaves))


def _cluster_two_path(h, wf_c, wf_s, agg: ClusterAgg, num_segments: int):
    from hyperspace_tpu.kernels.cluster import cluster_aggregate

    out = cluster_aggregate(h, wf_c, agg.c_recv, agg.c_send,
                            agg.c_plan, num_segments)
    msgs = wf_s.astype(h.dtype)[:, None] * h[agg.s_send]
    out = out + _sorted_segsum(msgs, agg.s_recv, *agg.s_plan,
                               num_segments).astype(out.dtype)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def cluster_sym_aggregate(h, agg: ClusterAgg, num_segments: int):
    """Mean aggregation through the cluster-pair kernel + straggler CSR.

    out[r] = Σ_e w_e h[senders_e] with w the precomputed 1/deg weights;
    ``h`` should already be cast to the aggregation dtype (bf16 messages
    halve the straggler traffic AND let the cluster kernel use the fast
    single-pass MXU mode).
    """
    return _cluster_two_path(h, agg.c_wf, agg.s_wf, agg, num_segments)


def _ca_fwd(h, agg, num_segments):
    return _cluster_two_path(h, agg.c_wf, agg.s_wf, agg, num_segments), agg


def _ca_bwd(num_segments, agg, g):
    # dh[i] = Σ_{e: r_e = i} w_{π(e)} ḡ[s_e] — identical program on
    # (ḡ, w_bwd); both subsets are reversal-closed so the split is exact
    dh = _cluster_two_path(g, agg.c_wb, agg.s_wb, agg, num_segments)
    return dh, None


cluster_sym_aggregate.defvjp(_ca_fwd, _ca_bwd)


# --- fused planned attention aggregation --------------------------------------
#
# The attention layer's cost on TPU is dominated by the NUMBER of
# [E]-length passes, not bytes: a 2.4 M-row gather costs ~28 ms on v5e
# regardless of width (latency-bound).  This op fuses the whole
# softmax-aggregate pipeline around ONE random edge gather:
#
# - forward: alpha_s rides as an extra feature column of h, so the
#   sender pick and the message gather are a single [E, F+1] gather;
#   logits/exp are one fused elementwise pass (bounded-logit softmax —
#   no max machinery, see nn.gcn.bounded_att_logits); numerator and
#   denominator are one block-CSR pass each; the division folds in.
# - backward: the gathered sender rows are SAVED as residuals (a
#   sequential [E, F] write+read ≈ 1.6 ms vs a 28 ms random re-gather),
#   so dw needs no new random gather; the only random backward gather is
#   d_num[senders] for the involution dh; everything else is static-
#   permutation gathers, sorted gathers, and CSR scalar reductions.


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10))
def att_aggregate_planned(h, alpha_s, alpha_r, senders, receivers, rev_perm,
                          edge_mask, plan, num_segments: int, agg_dtype,
                          negative_slope: float):
    """Softmax-attention neighbor aggregation on the planned layout.

    ``out[r] = Σ_e softmax_r(bounded_logits(α_s[s_e]+α_r[r_e])) h[s_e]``
    — numerically identical to the unfused pick/exp/den/aggregate chain
    (the oracle in tests).  ``edge_mask`` is the bool edge-validity mask
    (a constant of the graph — no cotangent).
    """
    out, _ = _att_fwd_impl(h, alpha_s, alpha_r, senders, receivers,
                           edge_mask, plan, num_segments, agg_dtype,
                           negative_slope)
    return out


def _att_fwd_impl(h, alpha_s, alpha_r, senders, receivers, edge_mask,
                  plan, num_segments, agg_dtype, negative_slope):
    from hyperspace_tpu.nn.gcn import bounded_att_logits

    pb, pc, pf = plan
    f = h.shape[-1]
    ha = jnp.concatenate([h, alpha_s[:, None].astype(h.dtype)], axis=1)
    hs_a = ha[senders]                       # the ONE random gather
    h_s, a_se = hs_a[:, :f], hs_a[:, f]
    a_re = alpha_r[receivers]                # sorted gather
    lm = bounded_att_logits(a_se + a_re, negative_slope)
    w = jnp.where(edge_mask, jnp.exp(lm), 0.0)
    h_in = h_s if agg_dtype is None else h_s.astype(agg_dtype)
    w_in = w if agg_dtype is None else w.astype(agg_dtype)
    # numerator and denominator ride ONE CSR pass: the messages carry a
    # constant 1-column, so segsum(w·[h | 1]) = [Σ w·h | Σ w]
    msgs = jnp.concatenate(
        [w_in[:, None] * h_in, w_in[:, None]], axis=1)
    agg = _sorted_segsum(msgs, receivers, pb, pc, pf,
                         num_segments).astype(jnp.float32)
    num, den = agg[:, :f], jnp.maximum(agg[:, f], 1e-15)
    out = (num / den[:, None]).astype(h.dtype)
    return out, (h_in, w_in, lm, den, out)


def _att_fwd(h, alpha_s, alpha_r, senders, receivers, rev_perm,
             edge_mask, plan, num_segments, agg_dtype, negative_slope):
    out, (h_in, w_in, lm, den, out_sv) = _att_fwd_impl(
        h, alpha_s, alpha_r, senders, receivers, edge_mask, plan,
        num_segments, agg_dtype, negative_slope)
    return out, (h_in, w_in, lm, den, out_sv, senders, receivers, rev_perm,
                 edge_mask, plan, jnp.zeros((0,), h.dtype))


def _att_bwd(num_segments, agg_dtype, negative_slope, res, g):
    from hyperspace_tpu.kernels.segment import (
        csr_att_bwd_edges,
        csr_segment_reduce_1d,
    )
    from hyperspace_tpu.nn.gcn import ATT_LOGIT_BOUND as B

    (h_in, w_in, lm, den, out, senders, receivers, rev_perm, edge_mask,
     plan, h_proto) = res
    h_dtype = h_proto.dtype
    f = out.shape[-1]
    pb, pc, pf = plan
    g32 = g.astype(jnp.float32)
    d_num = g32 / den[:, None]                       # [N, F]
    d_den = -jnp.sum(g32 * out.astype(jnp.float32), axis=-1) / den  # [N]

    # d(num)/d(den) ride together as [N, F+1] so ONE gather serves each
    # direction (mirrors the forward's fused num|den aggregation)
    dn_ext = jnp.concatenate([d_num, d_den[:, None]], axis=1)
    dn_dt = dn_ext if agg_dtype is None else dn_ext.astype(agg_dtype)
    dn_s = dn_dt[senders]                # the one random backward gather
    # dh via the involution: sender-scatter becomes a receiver-scatter
    # (the extra lane aggregates Σ w·d_den — sliced off)
    dh = _sorted_segsum(w_in[rev_perm][:, None] * dn_s, receivers,
                        pb, pc, pf, num_segments)[:, :f].astype(h_dtype)
    # dw + softmax chain + d_alpha_r: ONE fused CSR pass — the receiver-
    # side d_num|d_den rows are picked from the resident node block, the
    # ones-augmented residual rows stream by chunk, and the per-receiver
    # reduction accumulates in the same walk (kernels/segment.py)
    # keep the residual stream in its storage dtype (bf16 halves the
    # [E, F+1] HBM read; the kernel upcasts per tile, and a ones column
    # is exact in any float dtype)
    h1 = jnp.concatenate(
        [h_in, jnp.ones_like(w_in, h_in.dtype)[:, None]], axis=1)
    dpre, d_alpha_r = csr_att_bwd_edges(
        dn_ext, h1, jnp.where(edge_mask, w_in.astype(jnp.float32), 0.0),
        lm, receivers, (pb, pc, pf), num_segments, float(B),
        negative_slope)
    d_alpha_s = csr_segment_reduce_1d(dpre[rev_perm], receivers,
                                      (pb, pc, pf), num_segments, op="sum")
    return (dh, d_alpha_s, d_alpha_r, None, None, None, None, None)


att_aggregate_planned.defvjp(_att_fwd, _att_bwd)


# --- weighted (attention) aggregation on the cluster split --------------------
#
# Same two-path program, but the per-edge weights are RUNTIME values in
# the prepare layout (exp-ed attention logits).  The static c_map/s_map
# gathers route them into the split layouts ([E] scalars — cheap); the
# involution backward's reversed weights are one more static gather
# (c_map_rev = rev_perm∘c_map).  The dw backward — per-edge <ḡ[r], h[s]>
# — runs the cluster SDDMM kernel on the clustered set (two one-hot MXU
# matmuls per sub-chunk from VMEM-resident tiles) and the gathered row
# dot only on the stragglers, then reconstitutes the prepare-layout [E]
# gradient with the static inv_map GATHER (no scatter anywhere).


def _att_two_path(vals, w, agg: ClusterAgg, num_segments: int, rev: bool):
    from hyperspace_tpu.kernels.cluster import cluster_aggregate

    w = w.astype(jnp.float32)
    w_c = w[agg.c_map_rev if rev else agg.c_map]
    w_s = w[agg.s_map_rev if rev else agg.s_map] * agg.s_valid
    out = cluster_aggregate(vals, w_c, agg.c_recv, agg.c_send,
                            agg.c_plan, num_segments)
    msgs = w_s.astype(vals.dtype)[:, None] * vals[agg.s_send]
    out = out + _sorted_segsum(msgs, agg.s_recv, *agg.s_plan,
                               num_segments).astype(out.dtype)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def cluster_att_aggregate(h, w, agg: ClusterAgg, num_segments: int):
    """out[r] = Σ_e w_e · h[senders_e] with runtime per-edge weights
    ``w`` in the prepare layout (0 on padding edges), through the
    cluster-pair kernel + straggler CSR.  Requires ``agg.weighted_ok``.
    Twin/oracle: ``sym_segment_aggregate`` on the same (h, w).
    """
    return _att_two_path(h, w, agg, num_segments, rev=False)


def _caa_fwd(h, w, agg, num_segments):
    return _att_two_path(h, w, agg, num_segments, rev=False), (h, w, agg)


def _caa_bwd(num_segments, res, g):
    from hyperspace_tpu.kernels.cluster import cluster_sddmm

    h, w, agg = res
    dh = _att_two_path(g, w, agg, num_segments, rev=True).astype(h.dtype)
    # dw_e = <ḡ[r_e], h[s_e]>: SDDMM on the clustered set, row dot on
    # the stragglers, inv_map gather back to the prepare layout.  The
    # kernel output is padded/sliced to the slot count inv_map was built
    # against (agg.ec_pad) so a non-default split bk cannot misalign it.
    dw_c = cluster_sddmm(g, h, agg.c_recv, agg.c_send, agg.c_plan,
                         num_segments)
    pad = agg.ec_pad - dw_c.shape[0]
    dw_c = jnp.pad(dw_c, (0, max(pad, 0)))[: agg.ec_pad]
    dw_s = jnp.sum(g[agg.s_recv].astype(jnp.float32)
                   * h[agg.s_send].astype(jnp.float32), axis=-1)
    dw_all = jnp.concatenate([dw_c, dw_s, jnp.zeros((1,), jnp.float32)])
    dw = dw_all[agg.inv_map].astype(w.dtype)
    return dh, dw, None


cluster_att_aggregate.defvjp(_caa_fwd, _caa_bwd)
