"""Hysteresis-guarded degradation ladder (docs/resilience.md).

Under overload a serving system has exactly three honest options: make
callers wait (queue — bounded by the admission controller), refuse
(shed — the ``overloaded`` error), or *answer cheaper*.  The ladder is
the third: a small state machine whose levels order the system's
quality/cost modes best-first (for the k-NN engine: full ``nprobe``,
then ``nprobe`` halved toward its floor, then cache-only answering —
``serve/batcher.py`` owns that mapping; this module owns only the
level dynamics).

Transitions are hysteresis-guarded so the ladder never flaps at the
watermark: a step DOWN fires after ``down_after`` consecutive
observations at/above ``high`` pressure (default 1 — overload reaction
must be immediate), a step UP only after ``up_after`` consecutive
observations at/below ``low`` (default 8 — recovery waits for proof).
Pressure is the caller's normalized load signal in [0, 1] — the serve
batcher feeds admission-queue occupancy.  Mixed readings between the
watermarks reset both streaks (neither direction accumulates).

Thread-safe; ``observe`` is a few comparisons under one lock — hot-path
cheap, and not constructed at all when the feature is off.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class HysteresisLadder:
    """Pressure-driven level index in ``[0, levels-1]`` (0 = full
    quality).  ``on_change(old, new)`` fires outside no lock-ordering
    hazards (called while holding the ladder's own lock only)."""

    def __init__(self, levels: int, *, high: float = 0.75,
                 low: float = 0.25, down_after: int = 1,
                 up_after: int = 8,
                 on_change: Optional[Callable[[int, int], None]] = None):
        if levels < 1:
            raise ValueError(f"levels must be >= 1; got {levels}")
        if not 0.0 <= low < high <= 1.0:
            raise ValueError(
                f"want 0 <= low < high <= 1; got low={low} high={high}")
        if down_after < 1 or up_after < 1:
            raise ValueError("down_after/up_after must be >= 1")
        self.levels = int(levels)
        self.high, self.low = float(high), float(low)
        self.down_after, self.up_after = int(down_after), int(up_after)
        self.on_change = on_change
        self._lock = threading.Lock()
        self._level = 0
        self._hi_streak = 0
        self._lo_streak = 0

    @property
    def level(self) -> int:
        return self._level

    def observe(self, pressure: float) -> int:
        """Feed one pressure reading; returns the (possibly new) level."""
        with self._lock:
            old = self._level
            if pressure >= self.high:
                self._hi_streak += 1
                self._lo_streak = 0
                if (self._hi_streak >= self.down_after
                        and self._level < self.levels - 1):
                    self._level += 1
                    self._hi_streak = 0
            elif pressure <= self.low:
                self._lo_streak += 1
                self._hi_streak = 0
                if self._lo_streak >= self.up_after and self._level > 0:
                    self._level -= 1
                    self._lo_streak = 0
            else:
                # between the watermarks: evidence for neither direction
                self._hi_streak = self._lo_streak = 0
            new = self._level
            if new != old and self.on_change is not None:
                self.on_change(old, new)
            return new
