"""Failure-domain hardening: fault injection, divergence rollback,
overload-safe serving (docs/resilience.md).

Eight PRs in, the system was fast and observable but brittle by
construction: a NaN batch poisoned a training run with no rollback, a
failed checkpoint save had no retry anywhere, and the serve path would
queue unboundedly rather than shed load.  This package gives every
failure a *designed* outcome instead of an accidental one:

- :mod:`hyperspace_tpu.resilience.faults` — a process-wide,
  deterministic (seeded) fault registry.  Tests and the ``chaos=`` CLI
  flag arm named sites (``ckpt.save``, ``serve.dispatch``,
  ``data.next_batch``, ``train.step_nan``) with IOError, latency, or
  NaN payloads; disabled (the default) every site is one module-bool
  read — the same nullcontext discipline as telemetry.
- :mod:`hyperspace_tpu.resilience.guard` — the training divergence
  guard: on non-finite loss or a health-threshold violation the loop
  rewinds to the last COMMITTED checkpoint, re-seeds the data stream
  past the poisoned chunk, applies LR backoff under a capped retry
  budget, and records the incident in the JSONL manifest.
- :mod:`hyperspace_tpu.resilience.degrade` — the hysteresis-guarded
  degradation ladder the serve batcher steps down under pressure
  (IVF ``nprobe`` toward its floor, then cache-only answering).
"""

from hyperspace_tpu.resilience import faults
from hyperspace_tpu.resilience.degrade import HysteresisLadder
from hyperspace_tpu.resilience.faults import (FaultSpec, InjectedCrash,
                                              InjectedIOError, parse_chaos)
from hyperspace_tpu.resilience.guard import (DivergenceError,
                                             RollbackController,
                                             RollbackExhausted)

__all__ = [
    "faults",
    "FaultSpec",
    "InjectedCrash",
    "InjectedIOError",
    "parse_chaos",
    "DivergenceError",
    "RollbackController",
    "RollbackExhausted",
    "HysteresisLadder",
]
