"""Process-wide deterministic fault injection (docs/resilience.md).

Chaos testing needs faults that are (1) *named* — a test arms exactly
the failure it is about, (2) *deterministic* — a seeded schedule fires
the same faults on the same calls every run, so a chaos test is a
regression test and not a dice roll, and (3) *free when off* — the
sites live on the checkpoint-save, serve-dispatch and data paths, so
the disabled check must cost what a disabled trace span costs: one
module-global read.

Sites in the tree (the fault-site table in docs/resilience.md):

=================  ===========================  =======================
site               where                         kinds that make sense
=================  ===========================  =======================
``ckpt.save``      ``train/checkpoint.py``       ioerror, latency,
                                                 crash_staged
``serve.dispatch``  ``serve/batcher.py``         ioerror, latency
``data.next_batch`` ``data/prefetch.py``         ioerror, latency
``train.step_nan``  ``train/loop.py``            nan
=================  ===========================  =======================

Kinds:

- ``ioerror`` — raise :class:`InjectedIOError` (an ``IOError``
  subclass: the transient class retry loops are allowed to absorb).
- ``latency`` — ``time.sleep(ms / 1e3)``.
- ``nan`` — the site's :func:`poison` returns True; the *caller*
  poisons its payload (a batch, a loss) — the registry never touches
  device values itself.
- ``crash_staged`` — ``ckpt.save`` only: the manager materializes the
  exact on-disk shape a process killed between staging write and
  commit rename leaves (an uncommitted step dir + an orbax staging
  dir), then raises :class:`InjectedCrash` (NOT an ``OSError`` — a
  kill is not a transient the retry loop may absorb).

Scheduling: each spec fires on call indices ``after <= i < after +
times`` at its site (fully deterministic), or — when ``prob`` is set —
on a per-site seeded Bernoulli stream (deterministic for a fixed
``seed``, the chaos-bench mode).  Every armed spec counts into
``fault/armed`` and every fired fault into ``fault/fired``
(docs/observability.md); per-site detail is in :func:`stats`.

CLI grammar (the ``chaos=`` flag, shared by the train and serve CLIs)::

    chaos=site:kind[:key=value[:key=value...]][,site:kind...]
    chaos=ckpt.save:ioerror:times=2
    chaos=serve.dispatch:latency:ms=50:times=3
    chaos=train.step_nan:nan:after=4
    chaos=data.next_batch:ioerror:prob=0.05

Keys: ``times`` (default 1; ``0`` = every eligible call), ``after``
(skip the first N calls), ``ms`` (latency only), ``prob`` (overrides
the times/after window with seeded Bernoulli firing).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Optional

KINDS = ("ioerror", "latency", "nan", "crash_staged")


class InjectedIOError(IOError):
    """A transient injected IO failure (retry loops may absorb it)."""


class InjectedCrash(RuntimeError):
    """An injected process death (retry loops must NOT absorb it)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed fault: where, what, and on which calls it fires."""

    site: str
    kind: str
    times: int = 1       # fire on this many eligible calls (0 = all)
    after: int = 0       # skip the first `after` calls at the site
    ms: float = 0.0      # latency kind: injected delay
    prob: float = 0.0    # >0: seeded Bernoulli instead of the window

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"fault kind must be one of {KINDS}; got {self.kind!r}")
        if self.times < 0 or self.after < 0 or self.ms < 0:
            raise ValueError(f"times/after/ms must be >= 0: {self}")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1]: {self}")


class _Armed:
    """A spec plus its live firing state (calls seen, fires left)."""

    def __init__(self, spec: FaultSpec, seed: int):
        self.spec = spec
        self.calls = 0
        self.fired = 0
        # per-spec stream: site+kind fold into the seed so two specs on
        # one site draw independent (but reproducible) streams
        self._rng = random.Random((seed, spec.site, spec.kind))

    def due(self) -> bool:
        i = self.calls
        self.calls += 1
        s = self.spec
        if s.prob > 0.0:
            hit = i >= s.after and self._rng.random() < s.prob
        else:
            hit = s.after <= i and (s.times == 0
                                    or i < s.after + s.times)
        if hit:
            self.fired += 1
        return hit


class _Registry:
    def __init__(self, specs: list[FaultSpec], seed: int):
        self._lock = threading.Lock()
        self._armed = [_Armed(s, seed) for s in specs]
        self._by_site: dict[str, list[_Armed]] = {}
        for a in self._armed:
            self._by_site.setdefault(a.spec.site, []).append(a)

    def due(self, site: str) -> Optional[FaultSpec]:
        armed = self._by_site.get(site)
        if not armed:
            return None
        with self._lock:
            for a in armed:
                if a.due():
                    return a.spec
        return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "sites": sorted(self._by_site),
                "specs": [{"site": a.spec.site, "kind": a.spec.kind,
                           "calls": a.calls, "fired": a.fired}
                          for a in self._armed],
                "fired": sum(a.fired for a in self._armed),
            }


# the one module-global the disabled hot path reads (None = off) — the
# registry analog of the telemetry tracer's shared-nullcontext trick
_REGISTRY: Optional[_Registry] = None


def active() -> bool:
    """True when any fault is armed — THE cheap site guard."""
    return _REGISTRY is not None


def install(specs, *, seed: int = 0) -> None:
    """Arm ``specs`` (replacing any prior set).  Counts every armed
    spec into ``fault/armed``."""
    global _REGISTRY
    specs = list(specs)
    for s in specs:
        if not isinstance(s, FaultSpec):
            raise TypeError(f"want FaultSpec, got {type(s).__name__}")
    if not specs:
        _REGISTRY = None
        return
    _REGISTRY = _Registry(specs, int(seed))
    from hyperspace_tpu.telemetry import registry as telem

    telem.inc("fault/armed", len(specs))


def clear() -> None:
    """Disarm everything (tests; end of a chaos run)."""
    global _REGISTRY
    _REGISTRY = None


def due(site: str) -> Optional[FaultSpec]:
    """The consumed-one-firing core: the spec due at this call of
    ``site`` (its ``fault/fired`` already counted), or None.  Callers
    with site-specific interpretations (``ckpt.save``'s crash_staged)
    use this directly; plain sites use :func:`hit` / :func:`poison`."""
    reg = _REGISTRY
    if reg is None:
        return None
    spec = reg.due(site)
    if spec is not None:
        import sys

        from hyperspace_tpu.telemetry import registry as telem

        telem.inc("fault/fired")
        # stderr, NOT stdout: the serve loop's stdout is a strict
        # one-response-per-line protocol stream — a diagnostic line
        # there would corrupt a client's JSON parse
        print(f"[faults] fired {spec.kind} at {site}", file=sys.stderr,
              flush=True)
    return spec


def hit(site: str) -> None:
    """Error/latency site: raise :class:`InjectedIOError` or sleep when
    a fault is due; no-op otherwise (and when nothing is armed)."""
    spec = due(site)
    if spec is None:
        return
    if spec.kind == "latency":
        time.sleep(spec.ms / 1e3)
    elif spec.kind == "ioerror":
        raise InjectedIOError(f"injected IOError at {site}")
    else:
        raise InjectedCrash(f"injected {spec.kind} at {site}")


def poison(site: str) -> bool:
    """NaN site: True when THIS call's payload should be poisoned (the
    caller applies the NaN — the registry never touches device data)."""
    spec = due(site)
    return spec is not None and spec.kind == "nan"


def stats() -> dict:
    """Armed/fired detail for diagnostics ({} when nothing is armed)."""
    reg = _REGISTRY
    return {} if reg is None else reg.stats()


def parse_chaos(text: str) -> list[FaultSpec]:
    """Parse the ``chaos=`` CLI grammar (module docstring) into specs.

    Raises ``ValueError`` with a usage-shaped message on any malformed
    entry — the CLIs convert that to a clean ``SystemExit``."""
    specs = []
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"chaos entry {entry!r}: want site:kind[:key=value...]")
        site, kind = parts[0].strip(), parts[1].strip()
        kw: dict = {}
        for p in parts[2:]:
            if "=" not in p:
                raise ValueError(
                    f"chaos entry {entry!r}: want key=value, got {p!r}")
            k, v = (t.strip() for t in p.split("=", 1))
            if k in ("times", "after"):
                kw[k] = int(v)
            elif k in ("ms", "prob"):
                kw[k] = float(v)
            else:
                raise ValueError(
                    f"chaos entry {entry!r}: unknown key {k!r} "
                    "(want times/after/ms/prob)")
        try:
            specs.append(FaultSpec(site=site, kind=kind, **kw))
        except ValueError as e:
            raise ValueError(f"chaos entry {entry!r}: {e}") from None
    if not specs:
        raise ValueError(f"chaos={text!r}: no fault specs parsed")
    return specs


def install_chaos(text: Optional[str], seed: int = 0) -> bool:
    """CLI helper: parse + install ``chaos=`` (False when unset/empty).

    The two CLIs share this one entry so the grammar and the armed
    counter behave identically for train and serve."""
    if not text:
        return False
    install(parse_chaos(text), seed=seed)
    return True
