"""Training divergence guard: detect, rewind, back off, retry
(docs/resilience.md "Rollback semantics").

The PR 2 :class:`~hyperspace_tpu.telemetry.health.HealthMonitor` path
stops at warn-or-abort; this module extends it into *recover*.  When
the loop sees a non-finite loss at a metrics boundary, or the health
monitor flags a boundary-margin/constraint violation past tolerance,
the :class:`RollbackController`:

1. records the incident in the run's JSONL stream (a ``rollback``
   event: the step it fired at, the step it restored, the reason, the
   attempt number, the LR backoff scale) and counts
   ``resilience/rollbacks``;
2. rewinds the train state to the **last COMMITTED checkpoint** (the
   same commit test resume trusts — an interrupted save is never a
   rollback target), waiting out in-flight async saves first so the
   newest committed step is on disk before the scan;
3. re-projects the restored params onto their manifolds and copies
   the restored buffers (the donation-safety rule the resume path
   already follows);
4. hands ``(restored_step, attempt, lr_scale)`` to the caller's
   ``on_rollback`` hook — stream-fed runners re-seed their batch
   stream there so the poisoned chunk is *skipped*, never replayed,
   and runners whose optimizer exposes a scale apply the LR backoff
   (``lr_scale = lr_backoff ** attempt``; the hook receives it either
   way and the incident record carries it);
5. enforces the capped retry budget: past ``max_rollbacks`` the
   controller raises :class:`RollbackExhausted` — persistent
   divergence must kill the run loudly, not loop forever.

The guard costs nothing it wasn't already paying: detection reads the
``float(loss)`` the metrics boundary fetches anyway, plus (guard-only)
one fetch per crossed checkpoint boundary so a poisoned state is never
saved as a rollback target.  With the guard enabled and no fault, the
trajectory is bit-identical to an unguarded run (tested).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class DivergenceError(FloatingPointError):
    """Raised internally when a divergence signal fires with no guard
    budget left to absorb it (and by callers who want abort semantics)."""


class RollbackExhausted(RuntimeError):
    """Divergence persisted past the capped rollback budget."""


class RollbackController:
    """The run loop's rewind arm (constructed only when ``rollback>0``).

    ``ck`` is the loop's :class:`~hyperspace_tpu.train.checkpoint.
    CheckpointManager``; ``project`` the manifold re-projection restore
    applies; ``on_rollback(restored_step, attempt, lr_scale)`` the
    caller's re-seed/backoff hook (optional).
    """

    def __init__(self, ck, *, max_rollbacks: int = 1,
                 lr_backoff: float = 0.5,
                 project: Optional[Callable] = None,
                 on_rollback: Optional[Callable[[int, int, float],
                                               None]] = None):
        if max_rollbacks < 1:
            raise ValueError(
                f"max_rollbacks must be >= 1; got {max_rollbacks}")
        if not 0.0 < lr_backoff <= 1.0:
            raise ValueError(
                f"lr_backoff must be in (0, 1]; got {lr_backoff}")
        self.ck = ck
        self.max_rollbacks = int(max_rollbacks)
        self.lr_backoff = float(lr_backoff)
        self.project = project
        self.on_rollback = on_rollback
        self.rollbacks = 0

    @property
    def lr_scale(self) -> float:
        return self.lr_backoff ** self.rollbacks

    def divergent(self, loss_val: float) -> bool:
        """The loss-side trigger (the boundary's already-fetched float)."""
        return not math.isfinite(loss_val)

    def rollback(self, state: Any, step: int, log=None,
                 reason: str = "non-finite loss") -> tuple[Any, int]:
        """Rewind to the last committed checkpoint; returns
        ``(restored_state, restored_step)``.  Raises
        :class:`RollbackExhausted` past the budget and
        :class:`DivergenceError` when there is no committed step to
        rewind to."""
        from hyperspace_tpu.telemetry import registry as telem

        if self.rollbacks >= self.max_rollbacks:
            raise RollbackExhausted(
                f"divergence at step {step} persisted after "
                f"{self.rollbacks} rollback(s): {reason}")
        self.rollbacks += 1
        # async saves must land before the committed-step scan, or the
        # newest real checkpoint might still be a staging dir
        self.ck.wait()
        if self.ck.latest_committed_step() is None:
            raise DivergenceError(
                f"divergence at step {step} with no committed "
                f"checkpoint to roll back to: {reason}")
        state, restored = self.ck.restore(state, project=self.project)
        # donation-safety copy, same rationale as the resume path: the
        # next dispatch donates these buffers
        state = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).copy(), state)
        telem.inc("resilience/rollbacks")
        scale = self.lr_scale
        msg = (f"[resilience] rollback {self.rollbacks}/"
               f"{self.max_rollbacks}: step {step} -> {restored} "
               f"({reason}); lr_scale={scale:g}")
        print(msg, flush=True)
        if log is not None:
            log.event("rollback", step=int(step),
                      restored_step=int(restored), reason=reason,
                      attempt=self.rollbacks, lr_scale=scale)
        if self.on_rollback is not None:
            self.on_rollback(int(restored), self.rollbacks, scale)
        return state, int(restored)
