"""Access log + flight recorder: per-request records that survive.

PR 11's front door answers requests; nothing ties one HTTP request to
the collator flush, engine dispatch, and taxonomy outcome that served
it — a 504 is a counter tick, not an attributable event.  This module
is the request-addressable half of the observability plane:

- **Request ids** (:func:`new_request_id`): accept-or-generate per
  request (the HTTP server reads ``X-Request-Id``; the stdin loop a
  ``request_id`` field), threaded through the batcher/collator
  lifecycles into span args, echoed in the response, and stamped on
  the access record — the Dapper-style join key.
- :class:`AccessLog` — one structured JSONL line per request
  (``access_log=`` on the serve CLIs): request id, route, buckets
  dispatched, collator flush id, queue-wait/dispatch/e2e ms, cache
  hits/misses, degrade level, taxonomy outcome.  Thread-safe,
  line-buffered appends (the crashed-run prefix survives, same as the
  train JSONL); ``train/logging.read_jsonl`` reads it.
- :class:`FlightRecorder` — a bounded in-memory ring of the most
  recent access records.  On a **typed-error burst** (``burst_n``
  errors within ``burst_s`` seconds), a **degrade transition**, or
  **SIGTERM drain**, the ring plus a full counter snapshot dump to a
  timestamped incident JSONL under ``incident_dir=`` — a 429 storm or
  a rollback leaves evidence, not just monotone counters.  Dumps are
  cooldown-limited (one per ``cooldown_s`` per reason class) so a
  sustained storm writes one incident, not one per request.

Both are **off by default** and cost nothing when off: the batcher
holds a ``None`` sink and skips record assembly entirely
(``serve/batcher.py``).  ``serve/incidents`` counts dumps;
``serve/errors`` (bumped by the serving surfaces per error answer)
feeds the window's error rate.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Optional

from hyperspace_tpu.telemetry import registry as telem

DEFAULT_RING = 512
DEFAULT_BURST_N = 10
DEFAULT_BURST_S = 5.0
DEFAULT_COOLDOWN_S = 30.0


def new_request_id() -> str:
    """A fresh 16-hex request id (uuid4-derived — unique enough to join
    a response, an access-log line, and a flush id across hosts)."""
    return uuid.uuid4().hex[:16]


class AccessLog:
    """Append-only JSONL access log, thread-safe.

    ``emit(record)`` stamps ``ts`` (wall clock — log lines are joined
    with external systems, unlike the perf_counter lifecycle stamps),
    writes one line, and feeds the optional :class:`FlightRecorder`.
    Non-serializable values degrade per-record to ``repr`` — an odd
    field must never cost the request or the line."""

    def __init__(self, path: Optional[str] = None, *,
                 recorder: Optional["FlightRecorder"] = None):
        self._f = None
        self.path = path
        self.recorder = recorder
        self._lock = threading.Lock()
        self.lines = 0
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a", buffering=1, encoding="utf-8")

    def emit(self, record: dict) -> None:
        record = dict(record)
        record.setdefault("ts", time.time())
        try:
            line = json.dumps(record)
        except (TypeError, ValueError):
            line = json.dumps({k: v if _jsonable(v) else repr(v)
                               for k, v in record.items()})
        if self._f is not None:
            with self._lock:
                # re-checked INSIDE the lock: a concurrent close() may
                # have nulled the handle between the fast-path check
                # and acquiring the lock — a shutdown race must drop
                # the line, never raise into a live request
                if self._f is not None:
                    self._f.write(line + "\n")
                    self.lines += 1
        if self.recorder is not None:
            self.recorder.record(record)

    def close(self) -> None:
        if self._f is not None:
            with self._lock:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


class FlightRecorder:
    """Bounded ring of recent access records + incident dumps.

    Triggers (module docstring): :meth:`record` feeds the ring and the
    error-burst detector (any record whose ``outcome`` is not ``ok``);
    :meth:`note_degrade` fires on ladder transitions;
    callers invoke :meth:`dump` directly for drain/SIGTERM.  A dump
    writes ``incident_<utc-stamp>_<reason>.jsonl``: one header line
    (``event: incident``, the reason, and a full counter/gauge
    snapshot — the counter marks) followed by the ring's records,
    oldest first."""

    def __init__(self, incident_dir: str, *, capacity: int = DEFAULT_RING,
                 burst_n: int = DEFAULT_BURST_N,
                 burst_s: float = DEFAULT_BURST_S,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        if burst_n < 1 or burst_s <= 0:
            raise ValueError(
                f"bad burst spec n={burst_n} within {burst_s}s")
        self.incident_dir = incident_dir
        os.makedirs(incident_dir, exist_ok=True)
        self.burst_n = int(burst_n)
        self.burst_s = float(burst_s)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=int(capacity))
        self._error_ts: collections.deque = collections.deque(
            maxlen=int(burst_n))
        self._last_dump: dict[str, float] = {}  # reason class -> t
        self._writers: list[threading.Thread] = []
        self.dumps: list[str] = []

    def record(self, record: dict) -> None:
        outcome = record.get("outcome", "ok")
        now = time.monotonic()
        with self._lock:
            self._ring.append(dict(record))
            if outcome == "ok":
                return
            self._error_ts.append(now)
            burst = (len(self._error_ts) == self.burst_n
                     and now - self._error_ts[0] <= self.burst_s)
        if burst:
            # the triggering record rides the header: with spans on it
            # carries its full span tree, so the incident names WHICH
            # stage blew the budget, not just the flush id
            self.dump(f"error_burst_{outcome}", _cls="error_burst",
                      trigger=record)

    def note_degrade(self, old: int, new: int) -> None:
        """Ladder transition hook (both directions — a recovery's ring
        shows what the degraded interval looked like)."""
        self.dump(f"degrade_{old}_to_{new}", _cls="degrade")

    def dump(self, reason: str, _cls: Optional[str] = None,
             wait: bool = False,
             trigger: Optional[dict] = None) -> Optional[str]:
        """Snapshot the ring and hand the file write to a background
        thread; returns the incident path (None when the reason class
        is inside its cooldown).  The triggers fire on the SERVING
        path — burst detection inside a request coroutine on the
        asyncio event loop, degrade transitions inside ``_admit`` —
        and a synchronous multi-hundred-line write to a contended disk
        there would stall every in-flight request (the exact hazard
        the ``blocking-call-in-async`` lint documents).  Only the
        in-memory snapshot + thread handoff happen in the caller;
        ``wait=True`` (the drain paths — the process is about to exit)
        joins the write.  Write failures drop the file silently —
        evidence loss only, never a serving failure; the path lands in
        :attr:`dumps` (and ``serve/incidents`` ticks) only once the
        write succeeded."""
        cls = _cls or reason
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(cls)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_dump[cls] = now
            records = list(self._ring)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)
        path = os.path.join(self.incident_dir,
                            f"incident_{stamp}_{safe}.jsonl")
        header = {"event": "incident", "reason": reason,
                  "ts": time.time(), "ring_len": len(records),
                  "counters": telem.default_registry().snapshot("ctr/")}
        if trigger is not None:
            # attribution: the request that tripped the trigger, and —
            # when the span layer is on — its full span tree (the
            # batcher attaches "span" to every non-ok record)
            header["trigger_request_id"] = trigger.get("request_id")
            if "span" in trigger:
                header["trigger_span"] = trigger["span"]
        t = threading.Thread(target=self._write_dump,
                             args=(path, header, records),
                             name="flightrec-dump", daemon=True)
        with self._lock:
            self._writers = [w for w in self._writers if w.is_alive()]
            self._writers.append(t)
        t.start()
        if wait:
            t.join()
        return path

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for outstanding incident writes (tests; shutdown)."""
        with self._lock:
            writers = list(self._writers)
        for t in writers:
            t.join(timeout)

    def _write_dump(self, path: str, header: dict,
                    records: list) -> None:
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(header) + "\n")
                for rec in records:
                    try:
                        f.write(json.dumps(rec) + "\n")
                    except (TypeError, ValueError):
                        f.write(json.dumps(
                            {k: v if _jsonable(v) else repr(v)
                             for k, v in rec.items()}) + "\n")
        except OSError:
            return  # evidence loss only, never a serving failure
        telem.inc("serve/incidents")
        self.dumps.append(path)
