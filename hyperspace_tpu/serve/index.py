"""IVF index for sub-linear hyperbolic retrieval (docs/serving.md).

The exact engine scans every table row per query — O(N) per query, fine
at bench scale, hopeless at the millions-of-nodes tables the ROADMAP
north star implies.  This module builds the classic inverted-file (IVF)
two-level index of Jégou et al. 2011, with *geodesic* geometry
throughout:

- **Coarse quantizer: hyperbolic k-means.**  ``ncells`` centroids over
  the table, seeded k-means++-style (D² sampling under the manifold's
  own geodesic distance), refined by a fixed-iteration jitted Lloyd
  loop.  The centroid update is exact per manifold family, computed
  from ONE linear pass because each family has a lift in which the
  Fréchet-style mean is a normalized sum:

  - *lorentz*: the Lorentz centroid of Law et al. 2019 —
    ``μ = s / (√c·√(−⟨s,s⟩_L))`` for the per-cell point sum ``s``
    (``manifolds/lorentz.py:centroid``, reused verbatim);
  - *poincare*: lift to the hyperboloid (``maps.ball_to_lorentz``),
    Lorentz centroid there, project back — the two models are isometric
    so this IS the ball's Law-et-al centroid;
  - *sphere*: normalized per-cell mean (the spherical Fréchet mean's
    classical estimator: project the Euclidean mean to the sphere);
  - *euclidean*: the plain mean;
  - *product*: per-factor slices, each by its own rule (Gu et al. 2019
    products are metric products, so the squared-distance objective
    separates per factor).

  Empty cells keep their previous centroid (a zero sum must never
  normalize into garbage).
- **Cell layout: dense, static-shaped.**  Per-cell row ids are packed
  into a ``[ncells, max_cell]`` int32 array padded with ``-1`` — the
  CSR idea with a dense pitch, so probing is a fixed-shape gather and
  the whole query path stays jittable (one executable per
  (bucket, k, nprobe), same compile contract as the exact engine).
  Every table row lands in exactly one cell (assignment totality —
  tested).

The probing query program itself lives in ``serve/engine.py``
(``_topk_ivf``): score queries against the centroids, take the nearest
``nprobe`` cells, and run the existing two-stage chunk scan (threshold
prune + per-chunk ``lax.top_k`` + one merge) over the gathered
candidate rows — with the bf16-scan + f32-rescore path composing
unchanged.  ``build_index`` here is the offline half; the index
serializes into the :class:`~hyperspace_tpu.serve.artifact.ServingArtifact`
(``index.npz`` + a meta block, covered by the artifact fingerprint).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.manifolds import Lorentz, Sphere
from hyperspace_tpu.manifolds.maps import ball_to_lorentz, lorentz_to_ball
from hyperspace_tpu.serve.artifact import manifold_from_spec

INDEX_VERSION = 1

# tables smaller than this answer faster by exact scan than by probing
# (the gather + centroid pass overhead dominates) — engines fall back
# to the exact program below it, whatever nprobe says (docs/serving.md
# "exact-fallback rules")
IVF_MIN_TABLE_ROWS = 2048

# Lloyd assignment walks the table this many rows at a time so the
# [chunk, ncells] distance tile (plus [chunk, D] lift) stays bounded
# whatever N is
_BUILD_CHUNK = 4096

# at or above this many rows the builder switches to the HOST-STREAMED
# path (also forced for a HostEmbedTable source): the table never sits
# device-resident — the device sees one [_BUILD_CHUNK, D] block at a
# time (index/build_device_rows_peak gauge), and k-means++ seeding runs
# on a bounded uniform subsample (`seed_sample`).  Below it the
# fully-resident build keeps its structure and seeding stream; note
# that r15 ALSO sped up both paths' shared assignment/fold numerics
# (reduced argmin key, segment-sum folds), so rebuilt indexes can
# differ from pre-r15 artifacts at floating-point near-ties — builds
# stay deterministic per (inputs, platform, version).
HOST_BUILD_ROWS = 1 << 20
# default seeding-subsample cap for the streamed path; D² seeding is
# O(ncells · sample) distance evals, so an unbounded sample at 10M rows
# would dominate the whole build
SEED_SAMPLE_DEFAULT = 1 << 17


def auto_ncells(n: int) -> int:
    """Default cell count: ~√N (the classical IVF balance point where
    centroid scoring and in-cell scanning cost the same), clamped."""
    return max(2, min(4096, int(round(float(n) ** 0.5))))


@dataclasses.dataclass(frozen=True)
class ServingIndex:
    """A built (or loaded) IVF index over one frozen table."""

    centroids: np.ndarray  # [ncells, D] f32, rows ON the manifold
    cells: np.ndarray      # [ncells, max_cell] int32, -1 padded
    counts: np.ndarray     # [ncells] int32 real rows per cell
    num_nodes: int         # table rows the index was built over
    iters: int             # Lloyd iterations used
    seed: int              # k-means++ seeding RNG seed
    fingerprint: str       # content hash (arrays + build params)

    @property
    def ncells(self) -> int:
        return int(self.cells.shape[0])

    @property
    def max_cell(self) -> int:
        return int(self.cells.shape[1])

    @property
    def dim(self) -> int:
        return int(self.centroids.shape[1])


def index_fingerprint_of(centroids: np.ndarray, cells: np.ndarray,
                         counts: np.ndarray, *, num_nodes: int,
                         iters: int, seed: int) -> str:
    """Content identity of an index: sha256 over the arrays (bytes +
    shape/dtype) and the build parameters — the batcher's cache key
    ingredient, so two engines probing DIFFERENT indexes over the same
    table can never serve each other's rows."""
    centroids = np.ascontiguousarray(centroids)
    cells = np.ascontiguousarray(cells)
    counts = np.ascontiguousarray(counts)
    h = hashlib.sha256()
    h.update(json.dumps({
        "version": INDEX_VERSION,
        "num_nodes": int(num_nodes), "iters": int(iters), "seed": int(seed),
        "centroids": [list(centroids.shape), str(centroids.dtype)],
        "cells": [list(cells.shape), str(cells.dtype)],
        "counts": [list(counts.shape), str(counts.dtype)],
    }, sort_keys=True).encode())
    h.update(centroids.tobytes())
    h.update(cells.tobytes())
    h.update(counts.tobytes())
    return h.hexdigest()


# --- per-family centroid lifts ------------------------------------------------


def _lift_dim(spec: tuple, dim: int) -> int:
    """Width of the lifted coordinates (poincare lifts to d+1)."""
    if spec[0] == "poincare":
        return dim + 1
    if spec[0] == "product":
        return sum(_lift_dim((fk, c), d) for fk, d, c in spec[1])
    return dim


def _lift(spec: tuple, x: jax.Array) -> jax.Array:
    """Coordinates in which the family's centroid is a normalized SUM."""
    kind = spec[0]
    if kind == "poincare":
        return ball_to_lorentz(x, spec[1])
    if kind == "product":
        parts, o = [], 0
        for fk, d, c in spec[1]:
            xi = jax.lax.slice_in_dim(x, o, o + d, axis=-1)
            parts.append(_lift((fk, c), xi))
            o += d
        return jnp.concatenate(parts, axis=-1)
    return x


def _unlift(spec: tuple, s: jax.Array, cnt: jax.Array) -> jax.Array:
    """Per-cell lifted sums ``s`` [ncells, DL] + counts → centroids
    [ncells, D] (garbage on empty cells — the caller masks those)."""
    kind = spec[0]
    denom = jnp.maximum(cnt, 1.0)[:, None]
    if kind == "lorentz":
        # Law et al. 2019: normalize the (weighted) sum back onto the
        # sheet — scale-invariant, so counts drop out
        return Lorentz(float(spec[1])).centroid(s[:, None, :])
    if kind == "poincare":
        mu = Lorentz(float(spec[1])).centroid(s[:, None, :])
        return lorentz_to_ball(mu, spec[1])
    if kind == "sphere":
        return Sphere(float(spec[1])).proj(s / denom)
    if kind == "euclidean":
        return s / denom
    if kind == "product":
        parts, o = [], 0
        for fk, d, c in spec[1]:
            dl = _lift_dim((fk, c), d)
            si = jax.lax.slice_in_dim(s, o, o + dl, axis=-1)
            parts.append(_unlift((fk, c), si, cnt))
            o += dl
        return jnp.concatenate(parts, axis=-1)
    raise ValueError(f"no centroid rule for manifold kind {kind!r}")


# --- the jitted Lloyd loop ----------------------------------------------------


def _nearest_centroid(cent: jax.Array, rows: jax.Array, *, spec: tuple,
                      ncells: int) -> jax.Array:
    """Per-row nearest-centroid id [rows] int32 — nearest-centroid
    assignment IS a k=1 scan-top-k with the centroids as the slab: on a
    kernel backend the fused Pallas kernel (kernels/scan_topk.py)
    serves it without materializing the [chunk, ncells] distance tile.
    The XLA path argmins a **monotone-reduced distance key** instead of
    the full geodesic chain: for a fixed query row, dropping strictly
    increasing maps (arcosh1p, /√c) and POSITIVE per-row factors
    preserves the argmin —

    - poincare:  argmin_y  d²(x,y) / (1 − c‖y‖²)   (the (1 − c‖x‖²)
      factor is a per-row positive constant);
    - lorentz:   argmin_y  −⟨x, y⟩_L ;
    - euclidean: argmin_y  ‖x − y‖² ;
    - others (sphere, product): the full :func:`_tile_dist`.

    At 10M × 1024 cells the arcosh/rsqrt elementwise chain over the
    [chunk, ncells] tile WAS the build (measured ~5× of the Gram on
    the CPU twin); the reduced key keeps the Gram and drops the chain.
    Assignments can differ from the full-distance argmin only at
    floating-point near-ties (harmless to k-means; builds stay
    deterministic per platform).  The ONE assignment body the resident
    Lloyd loop, the host-streamed loop and the final passes all trace.
    """
    from hyperspace_tpu.kernels import _support as KS
    from hyperspace_tpu.kernels import scan_topk as fused_kernel
    from hyperspace_tpu.manifolds import smath
    from hyperspace_tpu.serve.engine import _tile_dist

    if (KS.mode() != "xla"
            and fused_kernel.supports(spec, k=1, dim=rows.shape[1])):
        _, ids = fused_kernel.scan_topk(
            cent, rows, jnp.zeros((rows.shape[0],), jnp.int32), 0,
            spec=spec, k=1, n=ncells, exclude_self=False)
        return ids[:, 0]
    kind = spec[0]
    prec = jax.lax.Precision.HIGHEST
    if kind in ("poincare", "euclidean"):
        gram = jnp.einsum("rd,cd->rc", rows, cent, precision=prec)
        xx = smath.sq_norm(rows)                          # [rows, 1]
        yy = smath.sq_norm(cent)[:, 0][None, :]           # [1, ncells]
        d2 = smath.clamp_min(xx - 2.0 * gram + yy, 0.0)
        if kind == "poincare":
            c = jnp.asarray(spec[1], rows.dtype)
            den_y = smath.clamp_min(1.0 - c * yy,
                                    smath.eps_for(rows.dtype))
            d2 = d2 / den_y
        key = d2
    elif kind == "lorentz":
        lane0 = jnp.concatenate(
            [-cent[:, :1], cent[:, 1:]], axis=1)          # flip time
        key = -jnp.einsum("rd,cd->rc", rows, lane0, precision=prec)
    else:
        key = _tile_dist(spec, rows, cent)                # [rows, ncells]
    return jnp.argmin(key, axis=1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("spec", "chunk", "iters", "ncells"))
def _lloyd(tpad: jax.Array, cent0: jax.Array, n, *, spec: tuple,
           chunk: int, iters: int, ncells: int):
    """Fixed-iteration Lloyd over a chunk-padded table.

    Returns ``(centroids [ncells, D], assign [npad] int32)`` — the
    assignment is the FINAL pass against the returned centroids, so the
    cell layout matches them exactly.  Assignment is a k=1 fused
    scan-top-k on kernel backends (kernels/scan_topk.py — no
    [chunk, ncells] tile in HBM) and the historical [chunk, ncells]
    argmin on CPU/XLA; the centroid update accumulates per-cell lifted sums with
    a one-hot matmul per chunk, so the whole loop is one executable and
    deterministic for a fixed seed/platform.
    """
    nchunks = tpad.shape[0] // chunk
    dl = _lift_dim(spec, tpad.shape[1])

    def assign_chunk(cent, i):
        rows = jax.lax.dynamic_slice_in_dim(tpad, i * chunk, chunk)
        a = _nearest_centroid(cent, rows, spec=spec, ncells=ncells)
        valid = (i * chunk + jnp.arange(chunk)) < n
        return rows, a, valid

    def iter_body(cent, _):
        def chunk_body(carry, i):
            sums, cnts = carry
            rows, a, valid = assign_chunk(cent, i)
            # segment-sum fold: no [chunk, ncells] one-hot float matrix
            # (at 10M × 1024 cells that matrix WAS half the build's
            # memory traffic); masked rows add zeros to cell 0
            lifted = jnp.where(valid[:, None], _lift(spec, rows), 0.0)
            seg = jnp.where(valid, a, 0)
            sums = sums + jax.ops.segment_sum(lifted, seg, ncells)
            cnts = cnts + jax.ops.segment_sum(
                valid.astype(jnp.float32), seg, ncells)
            return (sums, cnts), None

        (sums, cnts), _ = jax.lax.scan(
            chunk_body,
            (jnp.zeros((ncells, dl), jnp.float32),
             jnp.zeros((ncells,), jnp.float32)),
            jnp.arange(nchunks))
        new = _unlift(spec, sums, cnts)
        # empty cells keep their centroid — a zero sum must never
        # normalize into a garbage point that then captures rows
        return jnp.where(cnts[:, None] > 0, new, cent), None

    cent, _ = jax.lax.scan(iter_body, cent0, None, length=iters)

    def final_chunk(_, i):
        _rows, a, valid = assign_chunk(cent, i)
        return None, jnp.where(valid, a, -1)

    _, assign = jax.lax.scan(final_chunk, None, jnp.arange(nchunks))
    return cent, assign.reshape(-1)


# --- host-streamed build (HOST_BUILD_ROWS and up / HostEmbedTable) ------------


def _src_rows(table) -> tuple[int, int]:
    """(rows, width) of an ndarray or HostEmbedTable source."""
    from hyperspace_tpu.parallel.host_table import HostEmbedTable

    if isinstance(table, HostEmbedTable):
        return table.num_rows, table.width
    return int(table.shape[0]), int(table.shape[1])


def _src_iter(table, chunk: int):
    """Yield ``(start, np block)`` host views, <= ``chunk`` rows each."""
    from hyperspace_tpu.parallel.host_table import HostEmbedTable

    if isinstance(table, HostEmbedTable):
        yield from table.iter_chunks(chunk)
        return
    for lo in range(0, table.shape[0], chunk):
        yield lo, table[lo:lo + chunk]


def _src_gather(table, ids: np.ndarray) -> np.ndarray:
    from hyperspace_tpu.parallel.host_table import HostEmbedTable

    if isinstance(table, HostEmbedTable):
        return table.gather(ids)
    return table[ids]


def _device_block(block: np.ndarray, chunk: int) -> tuple[jax.Array, int]:
    """One streamed [chunk, D] device block (zero-padded tail) — the
    ONLY shape the streamed build ever puts on device; its row count
    feeds the ``index/build_device_rows_peak`` gauge."""
    from hyperspace_tpu.telemetry import registry as _telem

    rows = block.shape[0]
    if rows < chunk:
        block = np.concatenate(
            [block, np.zeros((chunk - rows, block.shape[1]),
                             block.dtype)], axis=0)
    _telem.set_gauge("index/build_device_rows_peak", chunk)  # hyperlint: disable=metric-unit-suffix — a peak ROW COUNT: the unit segment is mid-name, the suffix names the statistic
    return jnp.asarray(block), rows


@partial(jax.jit, static_argnames=("spec", "ncells"))
def _accum_chunk(cent, rows, nvalid, sums, cnts, *, spec: tuple,
                 ncells: int):
    """One streamed Lloyd chunk: assign + fold the lifted per-cell sums
    into the running accumulators (same segment-sum fold as the
    resident loop's scan body)."""
    a = _nearest_centroid(cent, rows, spec=spec, ncells=ncells)
    valid = jnp.arange(rows.shape[0]) < nvalid
    lifted = jnp.where(valid[:, None], _lift(spec, rows), 0.0)
    seg = jnp.where(valid, a, 0)
    return (sums + jax.ops.segment_sum(lifted, seg, ncells),
            cnts + jax.ops.segment_sum(valid.astype(jnp.float32), seg,
                                       ncells))


@partial(jax.jit, static_argnames=("spec", "ncells"))
def _assign_chunk_stream(cent, rows, nvalid, *, spec: tuple, ncells: int):
    a = _nearest_centroid(cent, rows, spec=spec, ncells=ncells)
    return jnp.where(jnp.arange(rows.shape[0]) < nvalid, a, -1)


def _lloyd_stream(table, cent0: jax.Array, *, spec: tuple, chunk: int,
                  iters: int, ncells: int):
    """Host-streamed Lloyd: same fixed-iteration update as
    :func:`_lloyd`, but the table stays on host — each pass walks it in
    [chunk, D] device blocks (one executable), accumulating the lifted
    per-cell sums on device.  Same per-chunk arithmetic in the same
    fold order as the resident scan — from equal seeds the two paths
    produce IDENTICAL assignments and float-tolerance-equal centroids
    (XLA schedules the jitted scan's accumulates differently than the
    eager chunk loop, so bitwise is not promised; regression-tested on
    a ~200k table)."""
    n, dim = _src_rows(table)
    dl = _lift_dim(spec, dim)
    cent = cent0
    for _ in range(int(iters)):
        sums = jnp.zeros((ncells, dl), jnp.float32)
        cnts = jnp.zeros((ncells,), jnp.float32)
        for _start, blk in _src_iter(table, chunk):
            rows, nvalid = _device_block(blk, chunk)
            sums, cnts = _accum_chunk(cent, rows, jnp.int32(nvalid),
                                      sums, cnts, spec=spec, ncells=ncells)
        new = _unlift(spec, sums, cnts)
        cent = jnp.where(cnts[:, None] > 0, new, cent)
    parts = []
    for _start, blk in _src_iter(table, chunk):
        rows, nvalid = _device_block(blk, chunk)
        parts.append(np.asarray(_assign_chunk_stream(
            cent, rows, jnp.int32(nvalid), spec=spec, ncells=ncells)))
    assign = np.concatenate(parts)
    assign = assign[assign >= 0]  # per-block padding tails drop out
    if len(assign) != n:
        raise AssertionError(
            f"streamed assignment covered {len(assign)} of {n} rows")
    return cent, assign


@partial(jax.jit, static_argnames=("spec",))
def _own_dist(rows: jax.Array, cent_rows: jax.Array, *, spec: tuple):
    """Per-row geodesic distance to the row's OWN centroid ([N])."""
    return manifold_from_spec(spec).dist(rows, cent_rows)


@partial(jax.jit, static_argnames=("spec",))
def _all_cell_dist(rows: jax.Array, cent: jax.Array, *, spec: tuple):
    """[S, ncells] geodesic distances rows × centroids."""
    from hyperspace_tpu.serve.engine import _tile_dist

    return _tile_dist(spec, rows, cent)


def _spill_balance(table: np.ndarray, centroids: np.ndarray,
                   assign: np.ndarray, spec: tuple, *,
                   cap: int) -> np.ndarray:
    """Cap every cell at ``cap`` rows (module docstring "Balancing").

    Oversized cells keep their ``cap`` closest members (by geodesic
    distance to the centroid); spilled rows re-assign by **rank
    rounds**: at round ``j`` every still-unplaced row bids for its
    ``j``-th-nearest centroid, and each cell grants its remaining room
    in spilled order — all vectorized, so the host cost is
    O(rounds × spilled log spilled), not an interpreted
    O(spilled × ncells) walk.  Deterministic, and total capacity
    ``ncells × cap >= N`` (``balance >= 1``, validated by the caller)
    guarantees every row lands: a cell with room left at the end never
    denied a bid, so no bidder can run out of ranks.  Memory stays
    bounded by processing spilled rows ``_BUILD_CHUNK`` at a time
    (the [chunk, ncells] distance tile, like the Lloyd loop).
    """
    ncells = int(centroids.shape[0])
    counts = np.bincount(assign, minlength=ncells)
    if counts.max() <= cap:
        return assign
    cdev = jnp.asarray(centroids)
    # own-centroid distances STREAMED per host chunk ([chunk] device
    # working set — at 10M rows the old one-shot put of the whole table
    # was itself the materialization this builder exists to avoid)
    parts = []
    for start, blk in _src_iter(table, _BUILD_CHUNK):
        ca = cdev[jnp.asarray(assign[start:start + blk.shape[0]])]
        parts.append(np.asarray(_own_dist(
            jnp.asarray(np.ascontiguousarray(blk)), ca, spec=spec)))
    d_own = np.concatenate(parts)
    assign = assign.copy()
    spilled = []
    for c in np.flatnonzero(counts > cap):
        members = np.flatnonzero(assign == c)
        order = members[np.argsort(d_own[members], kind="stable")]
        spilled.append(order[cap:])
    spilled = np.concatenate(spilled)
    room = (cap - np.minimum(counts, cap)).astype(np.int64)
    bs = _BUILD_CHUNK
    for s in range(0, len(spilled), bs):
        rows = spilled[s:s + bs]
        pd = np.asarray(_all_cell_dist(
            jnp.asarray(_src_gather(table, rows)), cdev, spec=spec))
        pref = np.argsort(pd, axis=1, kind="stable")
        left = np.arange(len(rows))
        for j in range(ncells):
            if not left.size:
                break
            want = pref[left, j]
            order = np.argsort(want, kind="stable")  # stable ⇒ spilled order
            w = want[order]
            uniq, starts, cnt = np.unique(w, return_index=True,
                                          return_counts=True)
            bid_rank = np.arange(len(w)) - np.repeat(starts, cnt)
            ok = bid_rank < room[w]
            granted = order[ok]
            assign[rows[left[granted]]] = want[granted]
            room -= np.bincount(w[ok], minlength=ncells)
            keep = np.ones(len(left), bool)
            keep[granted] = False
            left = left[keep]
    return assign


def build_index(table, manifold_spec: tuple, ncells: int, *,
                iters: int = 8, seed: int = 0,
                chunk: int = _BUILD_CHUNK,
                balance: float = 2.0,
                seed_sample: int = 0,
                host_resident: bool | None = None) -> ServingIndex:
    """Offline IVF build: hyperbolic k-means + dense cell layout.

    Deterministic for a fixed ``(table, spec, ncells, iters, seed)`` on
    a given platform: the seeding RNG is ``np.random.default_rng(seed)``
    and the Lloyd loop is one fixed-iteration jitted program.

    **Balancing (capacity-capped spill).**  The dense
    ``[ncells, max_cell]`` cell pitch makes the probe's work
    ``nprobe × max_cell`` — ONE oversized cell taxes every query,
    probed or not, and vanilla k-means on cluster-structured tables
    (i.e. real embedding tables) happily parks one centroid on several
    true clusters, inflating ``max_cell`` to >10× the mean.  So after
    Lloyd, cells are capped at ``balance × N/ncells`` rows: an
    oversized cell keeps its *closest* rows up to the cap and spills
    the rest, each spilled row re-assigning to its nearest centroid
    with room (deterministic rank-round bidding — `_spill_balance`).
    Totality is preserved
    (every row still lands in exactly one cell), ``max_cell ≤ cap`` by
    construction, and spilled rows sit in their second-choice cell —
    which multi-cell probes still find (the recall cost is measured,
    not assumed: ``bench_serve``'s recall leg).  ``balance=0`` disables
    the cap.

    **Scaling past HBM** (``host_resident`` — auto at
    ``HOST_BUILD_ROWS`` rows or for a
    :class:`~hyperspace_tpu.parallel.host_table.HostEmbedTable`
    source): the streamed build keeps the table on host — k-means++
    seeding runs on a bounded uniform subsample (``seed_sample``, auto
    ``min(n, 2^17)``; D² sampling over the full 10M-row table would be
    O(ncells·N) distance passes), Lloyd iterations and the final
    assignment walk [chunk, D] device blocks
    (``index/build_device_rows_peak`` gauge), and the spill pass
    gathers only the spilled rows.  Below the threshold the
    fully-resident build keeps its structure and full-table seeding
    stream (r15's shared assignment/fold speedups apply to BOTH paths
    — rebuilt indexes can shift vs pre-r15 artifacts at fp near-ties;
    determinism per build is unchanged).
    """
    from hyperspace_tpu.parallel.host_table import HostEmbedTable

    is_host_tab = isinstance(table, HostEmbedTable)
    if not is_host_tab:
        table = np.ascontiguousarray(np.asarray(table, np.float32))
        if table.ndim != 2:
            raise ValueError(
                f"index table must be [N, D]; got {table.shape}")
    n, dim = _src_rows(table)
    ncells = int(ncells)
    if not 2 <= ncells <= n:
        raise ValueError(
            f"ncells must be in [2, {n}] for a {n}-row table; got {ncells}")
    if balance and not balance >= 1.0:
        # below 1.0 total capacity ncells × cap can undershoot N and the
        # spill loop could not place every row — the cap guarantee the
        # docstring promises would silently break
        raise ValueError(
            f"balance must be 0 (disabled) or >= 1.0; got {balance}")
    spec = tuple(manifold_spec)
    m = manifold_from_spec(spec)
    stream = (host_resident if host_resident is not None
              else is_host_tab or n >= HOST_BUILD_ROWS)
    if is_host_tab and not stream:
        raise ValueError(
            "a HostEmbedTable source builds host-resident — drop "
            "host_resident=False (densifying it on device is the "
            "materialization this path exists to avoid)")

    # k-means++ seeding: D² sampling under the geodesic metric — each
    # new seed is drawn ∝ squared distance to the nearest chosen seed
    rng = np.random.default_rng(seed)
    dist_to = jax.jit(lambda t, c: m.dist(t, c[None, :]))  # hyperlint: disable=jit-cache-defeat — offline builder: one trace per build_index call, amortized over the whole k-means++/Lloyd loop
    use_sample = stream or (seed_sample and int(seed_sample) < n)
    if use_sample:
        ssize = min(int(seed_sample) or SEED_SAMPLE_DEFAULT, n)
        if ssize < ncells:
            raise ValueError(
                f"seed_sample={ssize} must hold at least ncells="
                f"{ncells} candidate rows")
        sample_ids = np.sort(rng.choice(n, size=ssize, replace=False))
        sdev = jnp.asarray(_src_gather(table, sample_ids))
        chosen = [int(rng.integers(ssize))]
        d2 = np.square(np.asarray(dist_to(sdev, sdev[chosen[0]])),
                       dtype=np.float64)
        for _ in range(ncells - 1):
            total = d2.sum()
            pick = (int(rng.choice(ssize, p=d2 / total)) if total > 0
                    else int(rng.integers(ssize)))
            chosen.append(pick)
            d2 = np.minimum(d2, np.square(
                np.asarray(dist_to(sdev, sdev[pick])), dtype=np.float64))
        cent0 = sdev[np.asarray(chosen)]
    else:
        tdev = jnp.asarray(table)
        chosen = [int(rng.integers(n))]
        d2 = np.square(np.asarray(dist_to(tdev, tdev[chosen[0]])),
                       dtype=np.float64)
        for _ in range(ncells - 1):
            total = d2.sum()
            if total > 0:
                pick = int(rng.choice(n, p=d2 / total))
            else:  # all remaining mass at distance 0 (duplicate points)
                pick = int(rng.integers(n))
            chosen.append(pick)
            d2 = np.minimum(
                d2, np.square(np.asarray(dist_to(tdev, tdev[pick])),
                              dtype=np.float64))
        cent0 = jnp.asarray(table[np.asarray(chosen)])

    if stream:
        cent, assign = _lloyd_stream(table, cent0, spec=spec, chunk=chunk,
                                     iters=int(iters), ncells=ncells)
        centroids = np.asarray(cent, np.float32)
        assign = np.asarray(assign)
    else:
        tdev = jnp.asarray(table)  # no-op if the seeding already put it
        npad = -(-n // chunk) * chunk
        tpad = (jnp.concatenate(
            [tdev, jnp.zeros((npad - n, dim), jnp.float32)]) if npad > n
            else tdev)
        cent, assign = _lloyd(tpad, cent0, jnp.int32(n), spec=spec,
                              chunk=chunk, iters=int(iters), ncells=ncells)
        centroids = np.asarray(cent, np.float32)
        assign = np.asarray(assign)[:n]

    if balance and balance > 0:
        assign = _spill_balance(table, centroids, assign, spec,
                                cap=int(np.ceil(float(balance) * n
                                                / ncells)))

    counts = np.bincount(assign, minlength=ncells).astype(np.int32)
    max_cell = int(max(counts.max(), 1))
    cells = np.full((ncells, max_cell), -1, np.int32)
    order = np.argsort(assign, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])
    for c in range(ncells):
        ids = order[starts[c]:starts[c + 1]]
        cells[c, :len(ids)] = ids

    fp = index_fingerprint_of(centroids, cells, counts, num_nodes=n,
                              iters=int(iters), seed=int(seed))
    return ServingIndex(centroids=centroids, cells=cells, counts=counts,
                        num_nodes=n, iters=int(iters), seed=int(seed),
                        fingerprint=fp)
