"""Multi-tenant engine registry: one front door, many artifacts.

A production serving host rarely owns one embedding table.  Multiple
models (per language, per surface, per A/B arm) each freeze their own
artifact, and giving every artifact its own process wastes the accel
(one table's traffic leaves the device idle while another process
queues) and multiplies the operational surface.  This module lets ONE
HTTP front door (``serve/server.py``) serve N artifacts:

- :class:`TenantStack` — one tenant's full serving stack: the frozen
  artifact (the host-resident master copy, mmapped), the
  :class:`~hyperspace_tpu.serve.engine.QueryEngine` (device tables —
  possibly paged out), a persistent
  :class:`~hyperspace_tpu.serve.batcher.RequestBatcher` (tenant-tagged
  LRU + admission + degradation ladder + per-tenant
  :class:`~hyperspace_tpu.telemetry.window.SloWindow`), and a
  :class:`~hyperspace_tpu.serve.collator.Collator` wired onto the
  registry's SHARED dispatch executor.
- :class:`EngineRegistry` — routes a request's ``tenant`` field (a
  tenant name OR an artifact fingerprint; absent = the default tenant,
  so every pre-existing client keeps working) to its stack, schedules
  the shared one-worker dispatch executor through a
  :class:`~hyperspace_tpu.serve.collator.FairDispatcher` (weighted
  deficit round robin — a hot tenant cannot starve the others), and
  **pages whole engines** under a device-memory budget.

**Engine paging** (``device_budget_mb=``): the artifact on disk is the
master copy — the device tables are a cache.  When resident engines
exceed the budget, the least-recently-used idle tenant's engine is
dropped (``batcher.engine = None``; JAX frees the device arrays by
refcount) and rebuilt on demand from its artifact on a dedicated
one-worker **paging executor**, so an admission storm on a cold tenant
never occupies the dispatch executor the hot tenants are answering on.
Re-admission re-runs the bucket-ladder prewarm (with the persistent
compilation cache armed this is deserialization, not compilation) and
is **coalesced**: concurrent requests for the same cold tenant await
one shared admit, not N rebuilds.  The batcher PERSISTS across paging —
its result cache is keyed by the artifact fingerprint + scan signature
(the cross-tenant-safety keys), so a re-admitted engine built from the
same artifact serves the cached rows bitwise-unchanged, and the
tenant's SLO window / ladder state survive the round trip.

Cross-tenant isolation is structural, not policed: every cache row is
keyed by the owning engine's fingerprint, every compiled program by the
engine's ``scan_signature``, and every metric/access record carries the
tenant label (``telemetry/exposition.py``) — tested bitwise against
solo engines in ``tests/serve/test_registry.py``.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.collator import (DEFAULT_MAX_WAIT_US, Collator,
                                           FairDispatcher)
from hyperspace_tpu.serve.errors import UnknownTenantError
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry.exposition import tenant_metric


def engine_device_bytes(engine) -> int:
    """Device bytes an engine's resident tables hold — the paging
    budget's unit.  Sums the table + scan-lane arrays (deduplicated:
    ``scan_table`` aliases ``table`` on the f32 lane); the IVF index
    payloads ride along when device-resident."""
    total = 0
    seen: set = set()
    arrays = [getattr(engine, name, None)
              for name in ("table", "scan_table", "scan_scale",
                           "_scan_aux")]
    for a in arrays:
        if a is None or id(a) in seen:
            continue
        seen.add(id(a))
        total += int(getattr(a, "nbytes", 0))
    return total


def _twrite(write, name: str, tenant, value) -> None:
    """One base + tenant-twin registry write through a DYNAMIC name —
    the per-tenant series the exposition folds into a ``tenant`` label.
    Names written through here are declared to the telemetry-catalog
    lint below (they are not literal call arguments)."""
    # telemetry-catalog: serve/tenant_admissions
    # telemetry-catalog: serve/tenant_evictions
    # telemetry-catalog: serve/tenant_admit_s
    write(name, value)
    if tenant:
        write(tenant_metric(name, tenant), value)


class TenantStack:
    """One tenant's serving stack (module docstring).  Built and owned
    by :class:`EngineRegistry`; everything mutable on it (residency,
    inflight, last_use) is touched on the event loop only."""

    __slots__ = ("name", "artifact", "art", "weight", "batcher",
                 "collator", "engine_kw", "fingerprint", "scan_signature",
                 "precision", "device_bytes", "resident", "last_use",
                 "inflight", "admit_future", "admissions", "evictions")

    def __init__(self, name: str, artifact: str, art, weight: float,
                 engine_kw: dict):
        self.name = name
        self.artifact = artifact      # path: the host-resident master
        self.art = art                # loaded (mmapped) ServingArtifact
        self.weight = float(weight)
        self.engine_kw = dict(engine_kw)
        self.batcher: Optional[RequestBatcher] = None
        self.collator: Optional[Collator] = None
        # identity captured at first build — /healthz for a paged-out
        # tenant still answers fingerprint/signature without a rebuild
        self.fingerprint: Optional[str] = None
        self.scan_signature: Optional[tuple] = None
        self.precision: Optional[str] = None
        self.device_bytes = 0         # last-known resident footprint
        self.resident = False
        self.last_use = 0             # registry use-sequence (LRU order)
        self.inflight = 0             # requests inside using() brackets
        self.admit_future: Optional[asyncio.Future] = None
        self.admissions = 0
        self.evictions = 0

    def summary(self) -> dict:
        """The per-tenant block /healthz and /v1/stats carry."""
        return {
            "tenant": self.name,
            "resident": self.resident,
            "weight": self.weight,
            "fingerprint": self.fingerprint,
            "scan_signature": (list(self.scan_signature)
                               if self.scan_signature else None),
            "precision": self.precision,
            "device_bytes": self.device_bytes if self.resident else 0,
            "degrade_level": (self.batcher.degrade_level
                              if self.batcher is not None else 0),
            "inflight": self.inflight,
            "admissions": self.admissions,
            "evictions": self.evictions,
        }


class EngineRegistry:
    """Tenant routing + weighted-fair dispatch + engine paging.

    Construct, :meth:`add_tenant` each artifact (the FIRST added tenant
    is the default — requests without a ``tenant`` field route there),
    then hand the registry to :class:`~hyperspace_tpu.serve.server.
    HttpFrontDoor`.  All post-construction mutation happens on the
    event loop; :meth:`add_tenant`/:meth:`prewarm` are construction-
    phase (blocking) calls made before the listeners open."""

    def __init__(self, *, device_budget_mb: float = 0.0,
                 max_wait_us: float = DEFAULT_MAX_WAIT_US,
                 quantum: int = 8, prewarm_ks=()):
        if device_budget_mb < 0:
            raise ValueError(
                f"device_budget_mb must be >= 0; got {device_budget_mb}")
        self.device_budget_bytes = int(device_budget_mb * (1 << 20))
        self.max_wait_us = float(max_wait_us)
        self.prewarm_ks = tuple(prewarm_ks)
        self._stacks: dict[str, TenantStack] = {}
        self._by_fp: dict[str, TenantStack] = {}
        self._default: Optional[TenantStack] = None
        # the ONE dispatch executor every tenant's device work rides —
        # serialization is preserved across tenants by construction
        self._exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-dispatch")
        # paging executor: engine rebuild + prewarm for cold tenants,
        # OFF the dispatch executor so an admission storm never blocks
        # the hot tenants' flushes
        self._pager = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-pager")
        self.dispatcher = FairDispatcher(self._exec, quantum=quantum)
        self._use_seq = 0
        self._closed = False
        # add_tenant runs pre-loop (CLI startup) but tests drive it
        # from threads; the stack maps get a lock for the build phase
        self._build_lock = threading.Lock()

    # --- construction ---------------------------------------------------------

    def add_tenant(self, name: str, artifact: str, *,
                   weight: float = 1.0, window_s: float = 60.0,
                   engine_kw: Optional[dict] = None,
                   batcher_kw: Optional[dict] = None) -> TenantStack:
        """Register one tenant: load its artifact, build the engine
        (eagerly — the fingerprint must be routable immediately), and
        assemble the persistent batcher + collator.  ``engine_kw`` goes
        to :meth:`QueryEngine.from_artifact` (precision/scan_mode/
        nprobe/chunk_rows), ``batcher_kw`` to :class:`RequestBatcher`
        (queue_max/deadline_ms/slo_ms/cache_size/buckets).  Raises
        ``ValueError`` on a duplicate name and on weights <= 0."""
        from hyperspace_tpu.serve.artifact import load_artifact

        if not name:
            raise ValueError("tenant name must be non-empty")
        if weight <= 0:
            raise ValueError(
                f"tenant {name!r}: weight must be > 0; got {weight}")
        with self._build_lock:
            if name in self._stacks:
                raise ValueError(f"duplicate tenant {name!r}")
            art = load_artifact(artifact)
            stack = TenantStack(name, artifact, art, weight,
                                engine_kw or {})
            eng = self._build_engine(stack)
            window = None
            if window_s:
                from hyperspace_tpu.telemetry.window import SloWindow

                window = SloWindow.for_tenant(name, window_s)
            stack.batcher = RequestBatcher(eng, tenant=name,
                                           window=window,
                                           **(batcher_kw or {}))
            stack.collator = Collator(stack.batcher,
                                      max_wait_us=self.max_wait_us,
                                      executor=self._exec,
                                      dispatcher=self.dispatcher,
                                      tenant=name)
            self._note_built(stack, eng)
            stack.resident = True
            self.dispatcher.set_weight(name, weight)
            self._stacks[name] = stack
            self._by_fp[stack.fingerprint] = stack
            if self._default is None:
                self._default = stack
            self._update_resident_gauge()
            # a fresh tenant may push the resident set past the budget:
            # evict idle LRU stacks (never the one just built)
            self._enforce_budget(keep=stack)
        return stack

    def _build_engine(self, stack: TenantStack):
        from hyperspace_tpu.serve.engine import QueryEngine

        return QueryEngine.from_artifact(stack.art, **stack.engine_kw)

    def _note_built(self, stack: TenantStack, eng) -> None:
        stack.fingerprint = eng.fingerprint
        stack.scan_signature = tuple(eng.scan_signature)
        stack.precision = eng.precision
        stack.device_bytes = engine_device_bytes(eng)

    # --- routing --------------------------------------------------------------

    @property
    def default(self) -> TenantStack:
        if self._default is None:
            raise UnknownTenantError(None)
        return self._default

    def tenants(self) -> list[TenantStack]:
        return list(self._stacks.values())

    def resolve(self, key=None) -> TenantStack:
        """The stack a request's ``tenant`` field routes to: ``None`` →
        the default tenant (back-compat — single-tenant clients send no
        field), else a tenant name or an artifact fingerprint.  An
        unresolvable key raises :class:`UnknownTenantError` (→ HTTP
        404, docs/serving.md "Error taxonomy")."""
        if key is None:
            return self.default
        if not isinstance(key, str) or not key:
            raise ValueError(
                f"tenant must be a non-empty string, got {key!r}")
        stack = self._stacks.get(key) or self._by_fp.get(key)
        if stack is None:
            raise UnknownTenantError(key)
        return stack

    @contextlib.asynccontextmanager
    async def using(self, stack: TenantStack):
        """Request-scope bracket: marks the stack busy (an in-use stack
        is never an eviction victim) and bumps its LRU stamp."""
        self._use_seq += 1
        stack.last_use = self._use_seq
        stack.inflight += 1
        try:
            yield stack
        finally:
            stack.inflight -= 1

    # --- engine paging --------------------------------------------------------

    async def ensure_resident(self, stack: TenantStack) -> None:
        """Make the stack's engine device-resident, rebuilding from the
        artifact if it was paged out.  Coalesced: every concurrent
        caller for one cold tenant awaits the SAME admit; the rebuild +
        prewarm run on the paging executor, so the dispatch executor
        keeps draining hot tenants meanwhile."""
        self._use_seq += 1
        stack.last_use = self._use_seq
        if stack.resident:
            return
        fut = stack.admit_future
        if fut is None:
            loop = asyncio.get_running_loop()
            fut = stack.admit_future = loop.create_future()
            asyncio.ensure_future(self._admit(stack, fut))
        await fut

    async def _admit(self, stack: TenantStack,
                     fut: asyncio.Future) -> None:
        loop = asyncio.get_running_loop()
        try:
            t0 = time.perf_counter()
            eng = await loop.run_in_executor(
                self._pager, functools.partial(self._build_engine, stack))
            stack.batcher.engine = eng
            self._note_built(stack, eng)
            stack.resident = True
            stack.admissions += 1
            if self.prewarm_ks:
                # re-warm the ladder OFF the hot path: with the
                # persistent compile cache this is deserialization
                await loop.run_in_executor(
                    self._pager, functools.partial(stack.batcher.prewarm,
                                                   self.prewarm_ks))
            _twrite(telem.inc, "serve/tenant_admissions", stack.name, 1)
            _twrite(telem.inc, "serve/tenant_admit_s", stack.name,
                    time.perf_counter() - t0)
            self._update_resident_gauge()
            # admitting this tenant may displace another idle one
            self._enforce_budget(keep=stack)
            fut.set_result(True)
        except (ValueError, KeyError, TypeError, OSError,
                RuntimeError) as e:
            # artifact unreadable / engine kwargs now invalid: every
            # coalesced awaiter gets the typed failure (→ the error
            # taxonomy), and the NEXT request retries a fresh admit
            fut.set_exception(e)
        finally:
            stack.admit_future = None

    def _evict(self, stack: TenantStack) -> None:
        """Drop the stack's device arrays; the artifact stays the
        master and the batcher (cache/ladder/window) persists — same
        artifact → same fingerprint → the cached rows stay valid."""
        stack.batcher.engine = None
        stack.resident = False
        stack.evictions += 1
        _twrite(telem.inc, "serve/tenant_evictions", stack.name, 1)
        self._update_resident_gauge()

    def _enforce_budget(self, keep: Optional[TenantStack] = None) -> None:
        """Evict idle LRU stacks until the resident set fits the
        budget.  A stack with requests in flight (or flushes queued in
        the fair dispatcher) is never a victim — over-budget with no
        safe victim simply stays over until the traffic passes."""
        if not self.device_budget_bytes:
            return
        while True:
            resident = [s for s in self._stacks.values() if s.resident]
            if sum(s.device_bytes
                   for s in resident) <= self.device_budget_bytes:
                return
            queued = self.dispatcher.pending()
            victims = [s for s in resident
                       if s is not keep and s.inflight == 0
                       and not queued.get(s.name)]
            if not victims:
                return
            self._evict(min(victims, key=lambda s: s.last_use))

    def _update_resident_gauge(self) -> None:
        telem.set_gauge(  # hyperlint: disable=tenant-unlabeled-metric — registry-global residency level, not per-tenant load
            "serve/tenants_resident",
            sum(1 for s in self._stacks.values() if s.resident))

    # --- lifecycle / observability --------------------------------------------

    def prewarm(self, ks) -> dict:
        """Warm every RESIDENT tenant's bucket ladder (startup, before
        the listeners open); returns {tenant: prewarm info}."""
        out = {}
        for stack in self._stacks.values():
            if stack.resident:
                out[stack.name] = stack.batcher.prewarm(list(ks))
        return out

    def stats(self) -> dict:
        """{tenant: full batcher stats + registry block} — the
        /v1/stats per-tenant payload.  A paged-out tenant carries only
        the registry block (its batcher stats dereference the engine,
        and rebuilding one for a stats scrape would defeat paging)."""
        out = {}
        for stack in self._stacks.values():
            s = (dict(stack.batcher.stats())
                 if stack.resident else {"tenant": stack.name})
            s["registry"] = stack.summary()
            out[stack.name] = s
        return out

    def close(self, wait: bool = True) -> None:
        """Shut down the shared executors; tenant collators only mark
        themselves closed (they never owned the executor)."""
        if self._closed:
            return
        self._closed = True
        for stack in self._stacks.values():
            if stack.collator is not None:
                stack.collator.close(wait=wait)
        self._exec.shutdown(wait=wait)
        self._pager.shutdown(wait=wait)
