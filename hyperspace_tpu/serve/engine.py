"""Jitted batched query engine over a frozen embedding table.

The inference workloads of the paper's retrieval models are two device
programs over an [N, D] table of manifold points:

- ``topk_neighbors(q_idx, k)`` — the k nearest table rows to each query
  row under the hyperbolic metric (Poincaré-embedding retrieval à la
  Nickel & Kiela 2017);
- ``score_edges(u_idx, v_idx)`` — per-pair distances (optionally pushed
  through the Fermi–Dirac link decoder) for edge scoring à la the HGCN
  LP head (Chami et al. 2019).

Mechanics:

- **Distance tiles come from the fused kernels.**  Poincaré/Lorentz
  tiles go through :func:`hyperspace_tpu.kernels.distmat.pdist` — the
  Pallas TPU kernel on a TPU backend, the XLA twin on CPU — so a [B, M]
  tile never materializes a [B, M, D] difference tensor.  Product
  manifolds use ``Product.dist`` broadcast per tile (exactly the trained
  geometry, learned curvatures frozen into the spec).
- **The table is chunked.**  The k-NN scan walks the table
  ``chunk_rows`` rows at a time, so the live distance working set is one
  [B, chunk] tile (plus [B, chunk, D] on the product path) regardless of
  N — ``tile_budget`` picks the chunk.  The table is zero-padded ONCE at
  engine build to a chunk multiple; padded rows are masked to +inf
  distance by index, so they can never appear in a result.
- **Three scan strategies** (``scan_mode``).  ``fused`` dispatches the
  chunk walk to the Pallas scan-top-k kernel
  (``kernels/scan_topk.py``; XLA twin on CPU): distance tiles are
  computed in-register and the running per-row top-k lives in the
  kernel carry, so the distance matrix never touches HBM and the
  per-chunk ``lax.top_k`` + post-scan merge disappear — the
  flash-attention trade applied to retrieval.  Results are
  rank-identical to the default (tested on every supported spec);
  product manifolds and oversized k/dim fall back to the two-stage
  path bit-identically.  The default ``two_stage``
  takes a per-chunk ``lax.top_k`` over the [B, chunk] tile only (k
  candidates per chunk, stacked by the scan) and merges the
  [B, nchunks·k] candidate buffer ONCE after the scan — the per-step
  sort never sees the carried candidates, so each step sorts chunk rows
  instead of chunk+k.  A running per-row k-th-distance bound lets a tile
  whose row-minimum already exceeds it skip its sort entirely (the
  threshold-prune fast path — a big win on locality-ordered tables
  where late chunks are all far).  ``carry`` is the original variant —
  the scan carries a running [B, k] top-k and re-sorts [B, chunk+k]
  every step — kept selectable for A/B timing and as the low-memory
  fallback when nchunks·k is large.
- **The table shards across the device mesh** (``mesh=``).  With a mesh
  whose ``model`` axis has S > 1 devices, the padded table is laid out
  ``P("model", None)`` (``parallel/sharded_embed.table_sharding``) —
  each device holds N/S rows, so tables larger than one chip's HBM
  serve fine and the scan walks only the local shard (per-device work
  cut by S).  Inside one ``shard_map`` program: query rows are
  assembled by the same gather-owned-rows + psum trick the training
  lookup uses, each device runs the chunked scan over its shard with
  shard-local column offsets, then one all-gather of the per-shard
  [B, k] candidates and a final merge top-k.  A mesh whose model axis
  has ONE device falls back to the single-device program — bit-compatible
  by construction (same executable).
- **Optional bf16 table scan** (``precision="bf16"``; docs/precision.md).
  A bf16 copy of the padded table lives beside the f32 one and the scan
  runs over THAT (half the HBM traffic of the dominant pass), keeping
  ``k + max(k, 8)`` candidates; the merged candidates are re-scored
  with f32 manifold distances against the f32 table before the final
  top-k, so returned distances are always f32-accurate and rank
  agreement holds at ordinary point distributions.  ``"f32"`` (default)
  is the unchanged pre-policy executable.
- **Optional int8 table scan** (``precision="int8"``; docs/serving.md
  "Quantized scan lane") — the same scan-then-rescore shape at a
  QUARTER of the table bytes: a per-row symmetric int8 code + per-row
  f32 scale (``serve/quant.py``) live beside the f32 table, the coarse
  scan dequantizes tiles in-register (``q8.astype(f32) * scale`` —
  arithmetic stays f32) keeping ``k + max(4k, 32)`` candidates (a wider
  over-fetch than bf16: the quantization step is coarser), and the
  merged candidates are rescored with f32 manifold distances against
  the f32 master before the final top-k.  Queries are NOT quantized —
  they are f32 rows of the master table.  Composes with IVF probing,
  the fused kernel (int8 slabs stream at quarter bytes through the
  same carry), and mesh sharding; the scan signature and the batcher
  cache key carry the lane, so f32/bf16/int8 rows never cross.
- **Sub-int8 lanes** (``precision="int4"|"pq"``; ISSUE 16,
  docs/serving.md "Sub-int8 lanes") — the same scan-then-rescore shape
  below a quarter of the bytes.  int4 packs two signed nibbles per
  byte with a per-row f16 scale (tiles unpack in-register; the fused
  kernel streams the packed bytes through a double-buffered DMA
  pipeline).  PQ stores one uint8 code per subspace against codebooks
  trained by subspace k-means in the tangent/Lorentz lift
  (``serve/quant.py``); the fused kernel scores coded tiles by ADC
  (per-query lookup tables), the two-stage path decodes tiles to the
  lift and scores with the lift's closed forms.  Both keep the int8
  lane's over-fetch + f32-rescore shape at a wider ``k + max(16k,
  128)`` window (a 4-bit step / a 256-way codebook is far coarser than
  int8's per-element step), so final
  ranks come from full-precision manifold distances; product specs
  serve PQ through the two-stage decode path (their distance is not
  subspace-additive).
- **Optional IVF probing** (``index=`` + ``nprobe=``; docs/serving.md
  "Approximate retrieval", built by ``serve/index.py``).  Queries score
  against the index's hyperbolic-k-means centroids, gather the nearest
  ``nprobe`` cells' row ids from the dense ``[ncells, max_cell]`` cell
  layout, and run the SAME two-stage scan (threshold prune, per-chunk
  top-k, one merge) over the gathered candidates — sub-linear work per
  query instead of the O(N) slab walk, at a recall cost ``bench_serve``
  tracks (recall@10 vs the exact engine, qps at recall ≥ 0.99).  The
  bf16 scan-then-f32-rescore path composes unchanged.  Exact fallback:
  ``nprobe=0`` / ``nprobe >= ncells`` (degenerate probe = exact answer,
  served bit-identically by the exact program) / tables under
  ``IVF_MIN_TABLE_ROWS`` / sharded meshes (probing is single-device).
- **Compiles are keyed on (bucket, k, nprobe), never on request.**  The jitted
  programs hang everything shape-like on static arguments (batch size,
  k, chunk, N, the manifold spec tuple, the mesh); the request batcher
  (``serve/batcher.py``) pads incoming batches to a small set of
  power-of-two buckets, so the engine compiles once per (bucket, k) and
  then serves any request size out of the same executable —
  ``jax/recompiles`` stays flat (the e2e test asserts it).

Determinism: for a fixed (bucket, k, chunk, scan_mode, mesh) the
program is one fixed XLA executable — the same table bytes give
bitwise-identical results, which is what lets
``scripts/check_serve_artifact.py`` demand export → load → query equals
the live model bit-for-bit.  Across DIFFERENT shardings the distances
agree but tied distances may order differently (the merge concatenates
per-shard candidates, not global column order).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hyperspace_tpu import precision as precision_mod
from hyperspace_tpu.parallel.mesh import shard_map
from hyperspace_tpu.parallel.sharded_embed import local_gather, table_sharding
from hyperspace_tpu.serve.artifact import (ServingArtifact, fingerprint_of,
                                           manifold_from_spec)
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import spans

# f32 bytes a distance tile may occupy ([B, chunk] on the kernel path,
# [B, chunk, D] on the product path), per the nominal batch below.
DEFAULT_TILE_BUDGET = 8 * 1024 * 1024
# chunk sizing assumes batches up to this (the batcher's default
# max_bucket); bigger batches just run a proportionally bigger tile.
NOMINAL_BATCH = 1024
_ROW_ALIGN = 128

SCAN_MODES = ("two_stage", "carry", "fused")
# the serve table-scan lanes: the precision-policy presets plus the
# serve-only quantized lanes (serve/quant.py — not training policies,
# so they live here rather than in precision.PRESET_NAMES): int8
# (per-row symmetric code), int4 (two nibbles per byte, ISSUE 16) and
# pq (product-quantized codes + hyperbolic-aware codebooks)
QUANT_PRECISIONS = ("int8", "int4", "pq")
PRECISIONS = precision_mod.PRESET_NAMES + QUANT_PRECISIONS

# extra candidates the bf16 scan keeps beyond the requested k, so a
# near-tie the low-precision pass mis-ranks at the k-th boundary is still
# IN the candidate set when the f32 rescore re-ranks it (docs/precision.md
# "serving": the scan picks candidates, f32 picks the answer)
_RESCORE_PAD = 8
# the int8 lane's wider over-fetch: a quantization step is ~2⁻⁸ of the
# row's dynamic range (vs bf16's ~2⁻⁸ RELATIVE per element — similar
# magnitude but correlated per row), so the coarse ranking is noisier
# and the rescore margin scales with k (k + max(4k, 32) candidates)
_QUANT_RESCORE_MIN = 32
_QUANT_RESCORE_MULT = 4
# the int4 lane's wider-still over-fetch: a 4-bit step is 2^4 = 16×
# int8's, so the coarse ranking noise swamps neighbor gaps much sooner
# as table density grows — measured at 200k clustered rows (dim 8,
# bench_big_table's generator) the int8-width window plateaus at
# recall@10 ≈ 0.95 while k + max(16k, 128) holds 1.0; same budget as
# the pq window, so the fused-kernel liveness bound is unchanged
_INT4_RESCORE_MIN = 128
_INT4_RESCORE_MULT = 16
# the PQ lane's even-wider over-fetch: subspace codebooks quantize whole
# ds-wide blocks to one of 256 centers, so the coarse ADC ranking is far
# noisier than any per-element lane — the window must absorb coarse
# ranks a few hundred deep, while k + max(16k, 128) still keeps
# k_scan <= FUSED_MAX_K for k <= 8 so the fused ADC kernel stays live
_PQ_RESCORE_MIN = 128
_PQ_RESCORE_MULT = 16


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def auto_chunk_rows(dim: int, spec_kind: str, n: int,
                    tile_budget: int = DEFAULT_TILE_BUDGET, *,
                    scan_mode: str = "two_stage",
                    dtype=jnp.float32, lane: str = "dense",
                    pq_m: int = 0) -> int:
    """Table-chunk rows that keep one distance tile under the budget.

    For ``scan_mode="fused"`` on a fused-capable family the chunk IS the
    kernel's streamed tile height, so sizing delegates to
    :func:`hyperspace_tpu.kernels.scan_topk.fused_tile_rows` — a
    VMEM-footprint model over dim × dtype × k (worst-case ``k =
    FUSED_MAX_K``, so every supported per-call k fits), not the fixed
    HBM distance-tile byte budget the two-stage scan uses.  Unsupported
    families keep the default sizing (the engine then IS the default
    two-stage executable — the bit-identical fallback contract).

    ``lane``/``pq_m`` extend the fused sizing to the packed scan lanes
    (``"int4"``/``"pq"`` — kernels/scan_topk.py's footprint branches);
    the default ``"dense"`` covers f32/bf16/int8 unchanged."""
    if scan_mode == "fused":
        from hyperspace_tpu.kernels import scan_topk as fused_kernel

        if (fused_kernel.kind_supported((spec_kind,))
                and dim <= fused_kernel.FUSED_MAX_DIM):
            chunk = fused_kernel.fused_tile_rows(
                dim, dtype, fused_kernel.FUSED_MAX_K, lane=lane, pq_m=pq_m)
            return min(chunk, _round_up(max(n, 1), _ROW_ALIGN))
    per_row = 4 * NOMINAL_BATCH * (dim if spec_kind == "product" else 1)
    chunk = max(_ROW_ALIGN, (tile_budget // per_row) // _ROW_ALIGN * _ROW_ALIGN)
    return min(chunk, _round_up(max(n, 1), _ROW_ALIGN))


def _tile_dist(spec: tuple, q: jax.Array, rows: jax.Array) -> jax.Array:
    """[B, D] × [M, D] → [B, M] distances under the spec's manifold."""
    kind = spec[0]
    if kind in ("poincare", "lorentz"):
        from hyperspace_tpu.kernels.distmat import pdist

        return pdist(q, rows, spec[1], manifold=kind)
    m = manifold_from_spec(spec)
    return m.dist(q[:, None, :], rows[None, :, :])


def _int4_rows_f32(packed: jax.Array, scale: jax.Array,
                   dim: int) -> jax.Array:
    """Packed planar int4 rows [..., ceil(dim/2)] uint8 + per-row scale
    [..., 1] → dequantized f32 rows [..., dim] (serve/quant.py's layout:
    byte j = element j in the LOW nibble, element ceil(dim/2)+j in the
    HIGH one, two's complement) — the two-stage scan's in-register
    unpack; the fused kernel carries its own identical recipe
    (kernels/scan_topk.py ``_tile_rows_f32``)."""
    from hyperspace_tpu.serve.quant import unpack_int4_jnp

    rows = unpack_int4_jnp(packed, dim)
    return rows.astype(jnp.float32) * scale.astype(jnp.float32)


def _pq_decode_rows(cb: jax.Array, codes: jax.Array,
                    lift_dim: int) -> jax.Array:
    """PQ codes [..., m] uint8 + codebooks [m, 256, ds] f32 → the
    reconstructed LIFTED rows [..., lift_dim] (serve/quant.py trains the
    codebooks in the tangent/Lorentz lift; pad lanes beyond the lift
    width are exactly zero and are sliced off)."""
    m = int(cb.shape[0])
    sel = cb[jnp.arange(m), codes.astype(jnp.int32)]      # [..., m, ds]
    out = sel.reshape(codes.shape[:-1] + (m * int(cb.shape[2]),))
    return out[..., :lift_dim]


def _pq_lift_dist(spec: tuple, q_lift: jax.Array,
                  rows_lift: jax.Array) -> jax.Array:
    """Coarse scan distances in the LIFT space: lifted f32 queries
    [B, DL] × reconstructed lifted rows ([M, DL] shared, or [B, C, DL]
    per-query) → [B, M] / [B, C].

    The lift of a poincare/lorentz family is Lorentz coordinates at the
    same curvature, so the distance closed form is the Lorentz one —
    exactly what the fused PQ kernel's ADC sum closes over
    (kernels/scan_topk.py ``_pq_dist_from_sum``); euclidean lifts are
    the identity.  Product specs recurse per factor and combine like
    ``Product.dist`` (root of summed squares).  Reconstructions sit
    slightly off the manifold — the same clamps the kernel tiles use
    keep the math finite, and the f32 rescore against the master table
    picks the final ranks anyway."""
    from hyperspace_tpu.manifolds import smath

    kind = spec[0]
    prec = jax.lax.Precision.HIGHEST
    shared = rows_lift.ndim == 2
    if kind == "product":
        from hyperspace_tpu.serve.index import _lift_dim

        o, acc = 0, 0.0
        for fk, d, c in spec[1]:
            dl = _lift_dim((fk, c), d)
            df = _pq_lift_dist((fk, c), q_lift[:, o:o + dl],
                               rows_lift[..., o:o + dl])
            acc = acc + jnp.square(df)
            o += dl
        return smath.safe_sqrt(acc)
    if kind in ("poincare", "lorentz"):
        c = jnp.asarray(spec[1], q_lift.dtype)
        if shared:
            gram = (jnp.einsum("bd,md->bm", q_lift[:, 1:], rows_lift[:, 1:],
                               precision=prec)
                    - q_lift[:, :1] * rows_lift[None, :, 0])
        else:
            gram = (jnp.einsum("bd,bcd->bc", q_lift[:, 1:],
                               rows_lift[..., 1:], precision=prec)
                    - q_lift[:, :1] * rows_lift[..., 0])
        u = smath.clamp_min(-c * gram - 1.0, 0.0)
        return smath.arcosh1p(u) / smath.clamp_min(
            smath.sqrt_c(c), smath.min_norm(q_lift.dtype))
    if kind == "euclidean":
        if shared:
            gram = jnp.einsum("bd,md->bm", q_lift, rows_lift,
                              precision=prec)
            yy = jnp.sum(rows_lift * rows_lift, axis=-1)[None, :]
        else:
            gram = jnp.einsum("bd,bcd->bc", q_lift, rows_lift,
                              precision=prec)
            yy = jnp.sum(rows_lift * rows_lift, axis=-1)
        xx = jnp.sum(q_lift * q_lift, axis=-1, keepdims=True)
        return smath.safe_sqrt(smath.clamp_min(xx - 2.0 * gram + yy, 0.0))
    # sphere (lift = identity): project the reconstruction back onto
    # the sphere and use the factor manifold's own distance
    m = manifold_from_spec(spec)
    rows = m.proj(rows_lift)
    if shared:
        return m.dist(q_lift[:, None, :], rows[None, :, :])
    return m.dist(q_lift[:, None, :], rows)


def _scan_topk(slab, q, q_idx, col0, *, spec: tuple, k: int, chunk: int,
               n: int, exclude_self: bool, mode: str, scale=None,
               lane: str = "dense", drop=None):
    """Chunked top-k over ``slab`` rows → ``(dists ascending, ids int32)``,
    each ``[B, min(k, slab_rows)]`` (a shard narrower than k contributes
    everything it has; the cross-shard merge restores the full k).

    ``slab`` is a chunk-multiple row block of the padded table whose
    global column ids start at ``col0`` (0 on the single-device path,
    ``axis_index * local_rows`` per shard on the sharded path — may be
    traced).  Rows at global index >= ``n`` are zero padding and are
    masked to +inf by index, as is each query's own row under
    ``exclude_self``.

    ``scale``/``lane`` (the quantized lanes, serve/quant.py): ``"int8"``
    — per-row [rows, 1] f32 dequant scales for an int8 ``slab``, tiles
    dequantize in-register before the distance math; ``"int4"`` — the
    slab is the planar packed [rows, ceil(D/2)] uint8 and ``scale`` its
    per-row (f16) scales, tiles unpack + dequantize in-register;
    ``"pq"`` — the slab is the [rows, m] uint8 code table and ``scale``
    carries the [m, 256, ds] codebooks, tiles decode to the LIFT space
    and score against the lifted query.  Every lane's scan arithmetic
    stays f32; only the table bytes shrink.

    ``drop`` (the live-index tombstone mask, serve/delta.py) is an
    optional ``[n_pad]`` f32 penalty row — 0 for live rows, ``+inf``
    for deleted or delta-superseded ones — ADDED to every tile's
    distances before the top-k, so a masked master row can never win a
    slot whatever its geometry.  The mask is a traced operand: its
    VALUES change per mutation generation without recompiling (the
    compile contract's shapes stay static).  The fused kernel has no
    mask lane, so a masked scan dispatches the two-stage path.
    """
    if drop is not None and mode == "fused":
        mode = "two_stage"  # the fused carry has no tombstone lane
    b = q.shape[0]
    dim = q.shape[1]
    nchunks = slab.shape[0] // chunk
    # per-chunk candidate count: a chunk narrower than k keeps ALL its
    # rows (lax.top_k needs k <= the sorted width)
    kc = min(k, chunk)
    # a slab narrower than k (a small shard under a large k) contributes
    # every row it has; the cross-shard merge restores the full k
    ko = min(k, nchunks * chunk)
    # distances of a quantized scan are f32 (dequantize-then-f32-math);
    # float slabs keep their own dtype (the bf16 scan's tiles are bf16)
    ddt = jnp.float32 if lane != "dense" or scale is not None \
        else slab.dtype
    q_lift = None
    if lane == "pq":
        from hyperspace_tpu.serve.index import _lift, _lift_dim

        lift_dim = _lift_dim(spec, dim)
        q_lift = _lift(spec, q).astype(jnp.float32)

    if mode == "fused":
        from hyperspace_tpu.kernels import scan_topk as fused_kernel

        if (lane == "pq"
                and fused_kernel.supports_pq(spec, k=k, m=slab.shape[1])
                and chunk % 128 == 0):
            # ADC in the kernel: per-query LUTs over the codebooks, the
            # coded tiles never decode to full rows (kernels/scan_topk)
            lut = fused_kernel.pq_lut(q_lift, scale, kind=spec[0])
            d, i = fused_kernel.scan_topk_pq(
                slab, lut, q_idx, col0, spec=spec, k=k, n=n,
                exclude_self=exclude_self, tile_rows=chunk)
            return d[:, :ko], i[:, :ko]
        if (lane != "pq"
                and fused_kernel.supports(spec, k=k, dim=dim)
                and chunk % 128 == 0):
            # the fused Pallas kernel (XLA twin on CPU): distance tiles
            # stay in-register, the running top-k lives in the kernel
            # carry — no [B, chunk] HBM tile, no per-chunk lax.top_k,
            # no post-scan merge (kernels/scan_topk.py)
            d, i = fused_kernel.scan_topk(
                slab, q, q_idx, col0, spec=spec, k=k, n=n,
                exclude_self=exclude_self, tile_rows=chunk, scale=scale,
                packed=(lane == "int4"))
            return d[:, :ko], i[:, :ko]
        # capability fallback (product spec, oversized k/dim/m): the
        # two-stage path below, bit-identical to scan_mode="two_stage"
        mode = "two_stage"

    def masked_tile(i):
        rows = jax.lax.dynamic_slice_in_dim(slab, i * chunk, chunk)
        if lane == "int4":
            s = jax.lax.dynamic_slice_in_dim(scale, i * chunk, chunk)
            rows = _int4_rows_f32(rows, s, dim)
        elif scale is not None and lane != "pq":
            rows = rows.astype(jnp.float32) * jax.lax.dynamic_slice_in_dim(
                scale, i * chunk, chunk)
        if lane == "pq":
            recon = _pq_decode_rows(scale, rows, lift_dim)
            d = _pq_lift_dist(spec, q_lift, recon)        # [B, chunk]
        else:
            d = _tile_dist(spec, q, rows)                 # [B, chunk]
        # pin int32: under x64 the traced chunk offset would promote the
        # index dtype and break the scan carry/stack contract
        cols = (col0 + i * chunk + jnp.arange(chunk)).astype(jnp.int32)
        mask = cols[None, :] >= n                         # zero-padded rows
        if exclude_self:
            mask = mask | (cols[None, :] == q_idx[:, None])
        if drop is not None:
            # tombstone/supersede penalty for this tile's global rows
            d = d + jax.lax.dynamic_slice_in_dim(
                drop, col0 + i * chunk, chunk).astype(d.dtype)[None, :]
        return jnp.where(mask, jnp.inf, d), cols

    if mode == "carry":
        def body(carry, i):
            best_d, best_i = carry
            d, cols = masked_tile(i)
            cat_d = jnp.concatenate([best_d, d], axis=1)
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(cols, d.shape)], axis=1)
            top_negd, sel = jax.lax.top_k(-cat_d, ko)
            return (-top_negd, jnp.take_along_axis(cat_i, sel, axis=1)), None

        init = (jnp.full((b, ko), jnp.inf, ddt),
                jnp.full((b, ko), -1, jnp.int32))
        (dist, idx), _ = jax.lax.scan(body, init, jnp.arange(nchunks))
        return dist, idx

    # two_stage: per-chunk top-kc over [B, chunk] only (never chunk+k),
    # candidates stacked by the scan, ONE [B, nchunks*kc] merge after it.
    def tile2d(i):
        d, cols = masked_tile(i)
        return d, jnp.broadcast_to(cols, d.shape)

    return _two_stage_core(tile2d, b=b, nchunks=nchunks, k=k, kc=kc, ko=ko,
                           dtype=ddt)


def _two_stage_core(masked_tile, *, b: int, nchunks: int, k: int, kc: int,
                    ko: int, dtype):
    """The ONE two-stage scan body — shared by the slab walk
    (:func:`_scan_topk` ``two_stage``) and the IVF candidate scan
    (:func:`_scan_topk_cand`), which differ only in where a tile's rows
    come from.  ``masked_tile(i)`` → ``(d [B, chunk], ids [B, chunk]
    int32)`` with masked slots at ``+inf``.  Returns
    ``(dists ascending, ids)``, each ``[B, ko]``.
    """
    def body(kth, i):
        d, ids = masked_tile(i)

        def sort_tile(_):
            top_negd, sel = jax.lax.top_k(-d, kc)
            return -top_negd, jnp.take_along_axis(ids, sel, axis=1)

        def skip_tile(_):
            return (jnp.full((b, kc), jnp.inf, d.dtype),
                    jnp.full((b, kc), -1, jnp.int32))

        # threshold prune: ``kth`` is an upper bound on the true running
        # k-th distance (the k-th smallest of a union is <= the k-th of
        # any member chunk), so a tile whose per-row minimum meets it on
        # EVERY row cannot change the result — skip its sort outright
        cd, ci = jax.lax.cond(
            jnp.all(jnp.min(d, axis=1) >= kth), skip_tile, sort_tile, None)
        if kc == k:  # narrower chunks (kc < k) have no k-th to tighten with
            kth = jnp.minimum(kth, cd[:, k - 1])  # inf when skipped: no-op
        return kth, (cd, ci)

    kth0 = jnp.full((b,), jnp.inf, dtype)
    _, (cd, ci) = jax.lax.scan(body, kth0, jnp.arange(nchunks))
    cat_d = jnp.moveaxis(cd, 0, 1).reshape(b, nchunks * kc)
    cat_i = jnp.moveaxis(ci, 0, 1).reshape(b, nchunks * kc)
    top_negd, sel = jax.lax.top_k(-cat_d, ko)
    return -top_negd, jnp.take_along_axis(cat_i, sel, axis=1)


@partial(jax.jit, static_argnames=("spec", "k", "chunk", "n", "exclude_self",
                                   "mode"))
def _topk_chunked(table: jax.Array, q_idx: jax.Array, drop=None,
                  q_rows=None, *, spec: tuple,
                  k: int, chunk: int, n: int, exclude_self: bool,
                  mode: str = "two_stage"):
    """Single-device chunked top-k; one fixed program per
    (batch, k, chunk, n, spec, mode).  ``drop``/``q_rows`` are the live
    subsystem's traced hooks (serve/delta.py): the tombstone penalty
    row, and explicit f32 query rows gathered from the MUTABLE master
    (a superseded id's frozen device row must never be the query)."""
    q = table[q_idx] if q_rows is None else q_rows        # [B, D]
    dist, idx = _scan_topk(table, q, q_idx, 0, spec=spec, k=k, chunk=chunk,
                           n=n, exclude_self=exclude_self, mode=mode,
                           drop=drop)
    return idx, dist


@partial(jax.jit, static_argnames=("spec", "k", "chunk", "n", "exclude_self",
                                   "mode", "mesh", "axis"))
def _topk_sharded(table: jax.Array, q_idx: jax.Array, drop=None,
                  q_rows=None, *, spec: tuple,
                  k: int, chunk: int, n: int, exclude_self: bool,
                  mode: str, mesh, axis: str):
    """Mesh-sharded top-k: per-shard chunked scan + one merge.

    ``table`` is the padded table laid out ``P(axis, None)`` (each of
    the S devices owns ``padded/S`` rows — a chunk multiple).  Per
    device: assemble the [B, D] query rows with the gather-owned-rows +
    psum trick (``parallel/sharded_embed.local_gather`` — one B×D
    all-reduce), scan the LOCAL shard with shard-local column offsets,
    then all-gather the per-shard [B, k] winners (S·k·B elements — tiny
    next to the table) and take the final merge top-k everywhere, so
    the output is replicated.
    """
    npad = table.shape[0]
    has_drop, has_q = drop is not None, q_rows is not None

    def local(tloc, qi, *extra):
        dr = extra[0] if has_drop else None
        q = (extra[-1] if has_q
             else local_gather(tloc, qi, npad, axis))     # [B, D]
        lo = (jax.lax.axis_index(axis) * tloc.shape[0]).astype(jnp.int32)
        d, i = _scan_topk(tloc, q, qi, lo, spec=spec, k=k, chunk=chunk,
                          n=n, exclude_self=exclude_self, mode=mode,
                          drop=dr)
        gd = jax.lax.all_gather(d, axis)                  # [S, B, k]
        gi = jax.lax.all_gather(i, axis)
        b = qi.shape[0]
        cat_d = jnp.moveaxis(gd, 0, 1).reshape(b, -1)     # [B, S*k]
        cat_i = jnp.moveaxis(gi, 0, 1).reshape(b, -1)
        top_negd, sel = jax.lax.top_k(-cat_d, k)
        return jnp.take_along_axis(cat_i, sel, axis=1), -top_negd

    # the live hooks ride replicated (the drop row and query rows are
    # B/N-scale vectors, tiny next to the sharded table)
    extras = ([drop] if has_drop else []) + ([q_rows] if has_q else [])
    run = shard_map(local, mesh=mesh,
                    in_specs=(P(axis, None), P()) + (P(),) * len(extras),
                    out_specs=(P(), P()), check_vma=False)
    return run(table, q_idx, *extras)


def _rescore_f32(spec: tuple, rows: jax.Array, q: jax.Array,
                 idx: jax.Array, scan_d: jax.Array) -> jax.Array:
    """f32 distances for gathered candidate rows ``rows`` [B, K, D]
    against f32 queries ``q`` [B, D].  Slots the low-precision scan
    filled with ``-1``/``inf`` (skipped tiles, narrow shards) stay
    ``+inf`` so they can never outrank a real candidate."""
    m = manifold_from_spec(spec)
    d = m.dist(q[:, None, :], rows)                       # [B, K] f32
    return jnp.where((idx < 0) | ~jnp.isfinite(scan_d), jnp.inf, d)


def _merge_rescored(d32: jax.Array, idx: jax.Array, k: int):
    """Final ranking: top-k of the f32-rescored candidate buffer."""
    top_negd, sel = jax.lax.top_k(-d32, k)
    return jnp.take_along_axis(idx, sel, axis=1), -top_negd


@partial(jax.jit, static_argnames=("spec", "k", "k_scan", "chunk", "n",
                                   "exclude_self", "mode", "lane"))
def _topk_chunked_mixed(table: jax.Array, scan_table: jax.Array,
                        scan_aux, q_idx: jax.Array, drop=None,
                        q_rows=None, *, spec: tuple,
                        k: int, k_scan: int, chunk: int, n: int,
                        exclude_self: bool, mode: str,
                        lane: str = "dense"):
    """Low-precision table-scan variant of :func:`_topk_chunked`: the
    chunked scan runs over ``scan_table`` (the bf16 copy, the int8/int4
    code, or the PQ code table — half / a quarter / an eighth-and-below
    of the HBM traffic of the dominant pass; ``scan_aux`` is the lane's
    companion: per-row dequant scales for int8/int4, the codebooks for
    pq, ``None`` for bf16) keeping ``k_scan >= k`` candidates, then the
    candidates are gathered from the f32 ``table`` and rescored with
    full-precision manifold distances before the final top-k — so
    returned distances carry f32 accuracy and the boundary-sensitive
    math never runs in low precision on anything that reaches the
    caller.  A ``drop``-masked candidate's scan distance is ``+inf``,
    which :func:`_rescore_f32` preserves — a tombstoned row can never
    re-enter through the rescore."""
    q = table[q_idx] if q_rows is None else q_rows        # [B, D] f32
    # quantized scans keep f32 queries (the table is quantized, not the
    # query rows); the bf16 scan casts them to the scan dtype
    q_scan = q.astype(scan_table.dtype) if lane == "dense" else q
    sd, sidx = _scan_topk(scan_table, q_scan, q_idx, 0, spec=spec,
                          k=k_scan, chunk=chunk, n=n,
                          exclude_self=exclude_self, mode=mode,
                          scale=scan_aux, lane=lane, drop=drop)
    rows = table[jnp.maximum(sidx, 0)]                    # [B, K, D] f32
    d32 = _rescore_f32(spec, rows, q, sidx, sd)
    return _merge_rescored(d32, sidx, k)


@partial(jax.jit, static_argnames=("spec", "k", "k_scan", "chunk", "n",
                                   "exclude_self", "mode", "mesh", "axis",
                                   "lane"))
def _topk_sharded_mixed(table: jax.Array, scan_table: jax.Array,
                        scan_aux, q_idx: jax.Array, drop=None,
                        q_rows=None, *, spec: tuple,
                        k: int, k_scan: int, chunk: int, n: int,
                        exclude_self: bool, mode: str, mesh, axis: str,
                        lane: str = "dense"):
    """Mesh-sharded twin of :func:`_topk_chunked_mixed`: per-shard
    low-precision scan over the local slab (bf16 copy, int8/int4 code +
    per-row scale, or PQ code table — all laid out ``P(axis, None)``
    like the master; PQ codebooks are replicated, they are KB-scale),
    all-gather + merge of the per-shard candidates, then an f32 rescore
    of the merged ``k_scan`` winners (candidate rows assembled from the
    f32 shards by the same psum gather the query rows use) before the
    final top-k."""
    npad = table.shape[0]
    has_drop, has_q = drop is not None, q_rows is not None

    def local_body(tloc, sloc, scl, qi, *extra):
        dr = extra[0] if has_drop else None
        q = (extra[-1] if has_q
             else local_gather(tloc, qi, npad, axis))     # [B, D] f32
        lo = (jax.lax.axis_index(axis) * tloc.shape[0]).astype(jnp.int32)
        qs = q.astype(sloc.dtype) if lane == "dense" else q
        d, i = _scan_topk(sloc, qs, qi, lo, spec=spec,
                          k=k_scan, chunk=chunk, n=n,
                          exclude_self=exclude_self, mode=mode, scale=scl,
                          lane=lane, drop=dr)
        gd = jax.lax.all_gather(d, axis)                  # [S, B, <=k_scan]
        gi = jax.lax.all_gather(i, axis)
        b = qi.shape[0]
        cat_d = jnp.moveaxis(gd, 0, 1).reshape(b, -1)
        cat_i = jnp.moveaxis(gi, 0, 1).reshape(b, -1)
        km = min(k_scan, cat_d.shape[1])
        top_negd, sel = jax.lax.top_k(-cat_d, km)
        sd = -top_negd
        sidx = jnp.take_along_axis(cat_i, sel, axis=1)    # [B, km]
        rows = local_gather(tloc, jnp.maximum(sidx, 0), npad, axis)
        d32 = _rescore_f32(spec, rows, q, sidx, sd)
        idx, dist = _merge_rescored(d32, sidx, k)
        return idx, dist

    # the live hooks ride replicated, like the query ids
    extras = ([drop] if has_drop else []) + ([q_rows] if has_q else [])
    especs = (P(),) * len(extras)
    if scan_aux is None:
        run = shard_map(
            lambda t, s, qi, *ex: local_body(t, s, None, qi, *ex),
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P()) + especs,
            out_specs=(P(), P()), check_vma=False)
        return run(table, scan_table, q_idx, *extras)
    # the aux rides row-sharded beside the code table (per-row scales)
    # — except PQ codebooks, which every shard needs whole
    aux_spec = P() if lane == "pq" else P(axis, None)
    run = shard_map(local_body, mesh=mesh,
                    in_specs=(P(axis, None), P(axis, None),
                              aux_spec, P()) + especs,
                    out_specs=(P(), P()), check_vma=False)
    return run(table, scan_table, scan_aux, q_idx, *extras)


def _cand_dist(spec: tuple, q: jax.Array, rows: jax.Array) -> jax.Array:
    """[B, D] queries × per-query candidate rows [B, C, D] → [B, C].

    The batched form of the distmat closed expressions
    (``kernels/distmat.py`` twins — same math as the slab scan's
    tiles), so the IVF candidate scorer is one einsum Gram plus cheap
    elementwise work instead of an elementwise Möbius chain over
    [B, C, D] (measured ~3× on the CPU twin).  Product manifolds use
    ``Product.dist`` broadcast — the exact trained geometry, like the
    slab scan's product path."""
    from hyperspace_tpu.manifolds import smath

    kind = spec[0]
    prec = jax.lax.Precision.HIGHEST
    if kind == "poincare":
        c = jnp.asarray(spec[1], q.dtype)
        gram = jnp.einsum("bd,bcd->bc", q, rows, precision=prec)
        xx = smath.sq_norm(q)                             # [B, 1]
        yy = smath.sq_norm(rows)[..., 0]                  # [B, C]
        d2 = smath.clamp_min(xx - 2.0 * gram + yy, 0.0)
        den = smath.clamp_min((1.0 - c * xx) * (1.0 - c * yy),
                              smath.eps_for(q.dtype))
        u = 2.0 * c * d2 / den
        return smath.arcosh1p(u) / smath.clamp_min(
            smath.sqrt_c(c), smath.min_norm(q.dtype))
    if kind == "lorentz":
        c = jnp.asarray(spec[1], q.dtype)
        gram = (jnp.einsum("bd,bcd->bc", q[:, 1:], rows[..., 1:],
                           precision=prec)
                - q[:, :1] * rows[..., 0])                # ⟨x, y⟩_L
        u = smath.clamp_min(-c * gram - 1.0, 0.0)
        return smath.arcosh1p(u) / smath.clamp_min(
            smath.sqrt_c(c), smath.min_norm(q.dtype))
    return manifold_from_spec(spec).dist(q[:, None, :], rows)


def _scan_topk_cand(scan_table: jax.Array, q: jax.Array, cand: jax.Array,
                    q_idx: jax.Array, *, spec: tuple, k: int, chunk: int,
                    exclude_self: bool, mode: str = "two_stage",
                    scale=None, lane: str = "dense", drop=None):
    """Chunked top-k over per-query candidate ids — the IVF in-cell
    scorer.  The two-stage machinery of :func:`_scan_topk` (per-chunk
    ``lax.top_k`` over the tile only, one post-scan merge, the running
    k-th-distance threshold prune), re-aimed: instead of walking a
    shared table slab, each chunk gathers every query's OWN candidate
    rows (``cand`` [B, C] int32, a chunk multiple wide, ``-1`` =
    padding) and scores them with :func:`_cand_dist` (per-query rows
    can't use the shared-row kernel tiles).  Returns
    ``(dists ascending, ids int32)``, each ``[B, min(k, C)]``; padded /
    self slots are ``+inf``/``-1`` and can never outrank a real row.
    """
    b, ctot = cand.shape
    nchunks = ctot // chunk
    q_lift = None
    if lane == "pq":
        from hyperspace_tpu.serve.index import _lift, _lift_dim

        lift_dim = _lift_dim(spec, q.shape[1])
        q_lift = _lift(spec, q).astype(jnp.float32)

    # the packed lanes have no fused candidate variant (the per-query
    # gather dominates; unpack/decode rides the two-stage scorer); a
    # tombstone-masked scan likewise rides the two-stage scorer
    if mode == "fused" and lane in ("dense", "int8") and drop is None:
        from hyperspace_tpu.kernels import scan_topk as fused_kernel

        if fused_kernel.supports_cand(spec, k=k, dim=scan_table.shape[1],
                                      cand=ctot):
            d, i = fused_kernel.scan_topk_cand(
                scan_table, cand, q, q_idx, spec=spec, k=k,
                exclude_self=exclude_self, scale=scale)
            ko = min(k, ctot)
            return d[:, :ko], i[:, :ko]
    if mode == "fused":
        mode = "two_stage"  # capability fallback — bit-identical path

    def masked_tile(i):
        ids = jax.lax.dynamic_slice_in_dim(cand, i * chunk, chunk, axis=1)
        safe = jnp.maximum(ids, 0)
        rows = scan_table[safe]                 # [B, chunk, D|hw|m]
        if lane == "pq":
            recon = _pq_decode_rows(scale, rows, lift_dim)
            d = _pq_lift_dist(spec, q_lift, recon)        # [B, chunk]
        else:
            if lane == "int4":
                rows = _int4_rows_f32(rows, scale[safe], q.shape[1])
            elif scale is not None:
                # int8 lane: gather each candidate's dequant scale too
                rows = rows.astype(jnp.float32) * scale[safe]
            d = _cand_dist(spec, q, rows)                 # [B, chunk]
        mask = ids < 0
        if exclude_self:
            mask = mask | (ids == q_idx[:, None])
        if drop is not None:
            # tombstone/supersede penalty, gathered per candidate id
            d = d + drop[safe].astype(d.dtype)
        return jnp.where(mask, jnp.inf, d), ids

    return _two_stage_core(masked_tile, b=b, nchunks=nchunks, k=k,
                           kc=min(k, chunk), ko=min(k, ctot),
                           dtype=(jnp.float32
                                  if lane != "dense" or scale is not None
                                  else scan_table.dtype))


@partial(jax.jit, static_argnames=("spec", "k", "k_scan", "nprobe", "chunk",
                                   "exclude_self", "mixed", "mode", "lane"))
def _topk_ivf(table: jax.Array, scan_table: jax.Array,
              centroids: jax.Array,
              cells: jax.Array, q_idx: jax.Array, drop=None, q_rows=None,
              *, spec: tuple, k: int,
              k_scan: int, nprobe: int, chunk: int, exclude_self: bool,
              mixed: bool, mode: str = "two_stage", scan_scale=None,
              lane: str = "dense"):
    """IVF probing top-k: centroid scoring → nearest-``nprobe`` cell
    gather → two-stage candidate scan (docs/serving.md "Approximate
    retrieval").  One executable per (batch, k, nprobe, spec) — same
    compile contract as the exact programs.

    The candidate scan runs over ``scan_table`` (the bf16 copy when
    ``mixed``), and the merged ``k_scan`` winners are then rescored
    with f32 manifold distances against the f32 ``table`` before the
    final ranking — PR 5's scan-then-rescore, unchanged.  Since the
    cells partition the table, a probed candidate appears at most once:
    no dedup pass is needed.  Cells holding fewer than ``k`` reachable
    rows surface ``-1``/``+inf`` slots rather than wrong neighbors —
    the engine wrapper (:meth:`QueryEngine._probe_topk`) turns those
    into a loud ValueError, never a served answer.
    """
    q = table[q_idx] if q_rows is None else q_rows        # [B, D] f32
    dc = _tile_dist(spec, q, centroids)                   # [B, ncells]
    _, cell_sel = jax.lax.top_k(-dc, nprobe)              # [B, nprobe]
    cand = cells[cell_sel].reshape(q_idx.shape[0], -1)    # [B, nprobe*mc]
    pad = -cand.shape[1] % chunk
    if pad:
        cand = jnp.pad(cand, ((0, 0), (0, pad)), constant_values=-1)
    # quantized scans keep f32 queries (rows dequantize in the scorer)
    qs = q.astype(scan_table.dtype) if lane == "dense" else q
    sd, sidx = _scan_topk_cand(scan_table, qs, cand, q_idx, spec=spec,
                               k=(k_scan if mixed else k), chunk=chunk,
                               exclude_self=exclude_self, mode=mode,
                               scale=scan_scale, lane=lane, drop=drop)
    if not mixed:
        return sidx, sd
    rows = table[jnp.maximum(sidx, 0)]                    # [B, K, D] f32
    d32 = _rescore_f32(spec, rows, q, sidx, sd)
    return _merge_rescored(d32, sidx, k)


def _fermi_dirac(d: jax.Array, r, t) -> jax.Array:
    """The HGCN LP head's link decoder — the ONE definition both the
    single-device and sharded scoring programs trace, so the 1-device
    bitwise guarantee can never mask a divergence between copies."""
    return 1.0 / (jnp.exp((jnp.square(d) - r) / t) + 1.0)


@partial(jax.jit, static_argnames=("spec", "prob"))
def _edge_dist(table: jax.Array, u_idx: jax.Array, v_idx: jax.Array,
               fd_r, fd_t, *, spec: tuple, prob: bool) -> jax.Array:
    m = manifold_from_spec(spec)
    d = m.dist(table[u_idx], table[v_idx])
    if prob:
        # Fermi–Dirac decoder INSIDE the jitted program: one dispatch
        # per scoring request, not one per arithmetic op (fd_r/fd_t are
        # traced scalars — changing them never recompiles)
        d = _fermi_dirac(d, fd_r, fd_t)
    return d


@partial(jax.jit, static_argnames=("spec", "prob"))
def _edge_dist_rows(xu: jax.Array, xv: jax.Array, fd_r, fd_t, *,
                    spec: tuple, prob: bool) -> jax.Array:
    """Edge scoring over explicit endpoint rows (the live-index path:
    serve/delta.py gathers FRESH rows from the mutable master instead of
    the frozen device table, so post-upsert scores are current)."""
    m = manifold_from_spec(spec)
    d = m.dist(xu, xv)
    if prob:
        d = _fermi_dirac(d, fd_r, fd_t)
    return d


@partial(jax.jit, static_argnames=("spec", "prob", "mesh", "axis"))
def _edge_dist_sharded(table: jax.Array, u_idx: jax.Array, v_idx: jax.Array,
                       fd_r, fd_t, *, spec: tuple, prob: bool, mesh,
                       axis: str) -> jax.Array:
    """Edge scoring over a row-sharded table: two psum gathers assemble
    the endpoint rows, then the distance math runs replicated."""
    npad = table.shape[0]

    def local(tloc, u, v, r, t):
        xu = local_gather(tloc, u, npad, axis)
        xv = local_gather(tloc, v, npad, axis)
        m = manifold_from_spec(spec)
        d = m.dist(xu, xv)
        if prob:
            d = _fermi_dirac(d, r, t)
        return d

    run = shard_map(local, mesh=mesh,
                    in_specs=(P(axis, None), P(), P(), P(), P()),
                    out_specs=P(), check_vma=False)
    return run(table, u_idx, v_idx, jnp.asarray(fd_r), jnp.asarray(fd_t))


class QueryEngine:
    """Batched k-NN / edge-score queries over one frozen table.

    ``table`` is moved to device once (zero-padded to a chunk multiple;
    with a ``mesh`` it is row-sharded over ``mesh_axis`` and padded to a
    chunk-per-shard multiple); every query after that is a single jitted
    dispatch.  Construct via :meth:`from_artifact` for the serving path,
    or directly on a live table (tests, the round-trip lint).

    ``scan_mode`` picks the chunk-scan strategy (``"two_stage"``
    default, ``"carry"`` for the original running-top-k variant,
    ``"fused"`` for the Pallas scan-top-k kernel — rank-identical
    answers, no HBM distance tiles; unsupported specs/shapes fall back
    to two-stage bit-identically — see the module docstring).
    ``mesh=None`` (or a mesh whose model axis has one device) runs the
    single-device program.

    ``precision`` picks the table-scan dtype policy (docs/precision.md):
    ``"f32"`` (default) is the exact pre-policy program, bit-identical;
    ``"bf16"`` keeps a bf16 copy of the padded table beside the f32 one
    and scans THAT (half the HBM traffic of the dominant pass), keeping
    ``k + max(k, 8)`` candidates which are then rescored with f32
    manifold distances against the f32 table before the final ranking —
    returned distances are always f32-accurate, and a near-tie the bf16
    pass mis-ranks at the k-th boundary is recovered by the over-fetch.
    ``"int8"`` is the same shape at a quarter of the table bytes: a
    per-row symmetric int8 code + per-row f32 scale (``serve/quant.py``)
    replace the scan copy, tiles dequantize in-register, and the coarse
    pass keeps ``k + max(4k, 32)`` candidates for the f32 rescore
    (docs/serving.md "Quantized scan lane").  ``"int4"`` packs two
    signed nibbles per byte beside a per-row f16 scale (~an eighth of
    f32), and ``"pq"`` stores one byte per subspace against
    hyperbolic-aware codebooks trained in the tangent/Lorentz lift
    (serve/quant.py; ``quant=`` accepts a precomputed payload, e.g.
    from an artifact) — both serve through the same over-fetch +
    f32-rescore machinery at the wider ``k + max(16k, 128)``
    window, so returned ranks and distances
    always come from full-precision manifold math (docs/serving.md
    "Sub-int8 lanes").  Edge scoring
    (``score_edges``) is always f32: it is two cheap
    gathers plus one distance per pair, with no table scan to save.

    ``index=`` + ``nprobe=`` turn on **IVF probing** (docs/serving.md
    "Approximate retrieval"): queries score against the index's
    hyperbolic-k-means centroids, gather the nearest ``nprobe`` cells'
    rows, and run the two-stage candidate scan (+ f32 rescore under
    ``precision=bf16``) over those instead of the whole table —
    sub-linear work per query at a recall cost ``bench_serve`` tracks.
    Exact-fallback rules (the engine then IS the exact executable):
    ``nprobe=0``; ``nprobe >= ncells`` (degenerate probe — covering
    every cell is the exact answer, so the exact program serves it
    bit-identically); tables under ``IVF_MIN_TABLE_ROWS``; any mesh
    with >1 shard (probing is single-device — raise ``nprobe=`` there
    is an error, not a silent slowdown).  ``scan_strategy`` /
    ``scan_signature`` expose which program answers — the batcher's
    cache key and ``stats()`` carry them.
    """

    def __init__(self, table, manifold_spec: tuple, *,
                 fingerprint: Optional[str] = None,
                 chunk_rows: int = 0,
                 tile_budget: int = DEFAULT_TILE_BUDGET,
                 mesh=None, mesh_axis: str = "model",
                 scan_mode: str = "two_stage",
                 precision: str = "f32",
                 index=None, nprobe: int = 0,
                 quant=None, pq_m: int = 0):
        table = np.ascontiguousarray(np.asarray(table))
        if table.ndim != 2:
            raise ValueError(f"table must be [N, D]; got {table.shape}")
        if scan_mode not in SCAN_MODES:
            raise ValueError(
                f"scan_mode must be one of {SCAN_MODES}; got {scan_mode!r}")
        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS}; got {precision!r}")
        self.num_nodes, self.dim = (int(s) for s in table.shape)
        self.spec = tuple(manifold_spec)
        self.scan_mode = scan_mode
        self.precision = precision
        # int8/int4/pq are serve-only scan lanes (serve/quant.py), not
        # precision-policy presets: the policy object stays f32 (master
        # table, rescore math) and the quantized copy rides beside it
        self._quant = precision in QUANT_PRECISIONS
        self._policy = precision_mod.get_policy(
            "f32" if self._quant else precision)
        # the static lane tag the jitted programs key on ("dense" covers
        # f32 AND bf16 — the slab dtype distinguishes those)
        self._lane = precision if self._quant else "dense"
        # quant= accepts a serve/artifact.py QuantPayload (precomputed
        # codes, e.g. shipped inside an artifact); it is consulted only
        # when its lane matches the requested precision — an artifact
        # may carry an int4 payload while this engine serves f32
        self._payload = None
        if quant is not None and getattr(quant, "lane", None) == precision:
            if int(quant.num_nodes) != self.num_nodes:
                raise ValueError(
                    f"quant payload covers {quant.num_nodes} rows; table "
                    f"has {self.num_nodes} — re-export for THIS table")
            self._payload = quant
        self.fingerprint = fingerprint or fingerprint_of(table, self.spec)
        self.mesh, self.mesh_axis = mesh, mesh_axis
        shards = 1
        if mesh is not None:
            if mesh_axis not in mesh.axis_names:
                raise ValueError(
                    f"mesh has no {mesh_axis!r} axis (axes: "
                    f"{mesh.axis_names}); pass mesh_axis=")
            shards = int(mesh.shape[mesh_axis])
        self.shards = shards
        chunk_rows = int(chunk_rows)
        if chunk_rows < 0:
            # a negative chunk would make the scan run ZERO chunks and
            # silently answer every query with -1/inf
            raise ValueError(f"chunk_rows must be >= 0 (0 = auto); "
                             f"got {chunk_rows}")
        from hyperspace_tpu.kernels import scan_topk as fused_kernel

        # PQ geometry is fixed before chunk sizing: the fused gate and
        # the VMEM footprint depend on the subspace count m
        self._pq_m = 0
        if precision == "pq":
            from hyperspace_tpu.serve.index import _lift_dim
            from hyperspace_tpu.serve.quant import default_pq_m

            # a payload's trained geometry wins; pq_m= retunes the
            # bytes/fidelity trade only when the engine trains fresh
            self._pq_m = (int(self._payload.params["m"])
                          if self._payload is not None
                          else int(pq_m)
                          or default_pq_m(_lift_dim(self.spec, self.dim)))
        # fused-capable = the family/dim the fused kernel can serve; k-
        # level fallback (oversized k per call) is decided per dispatch.
        # An engine whose spec is NOT fused-capable keeps the default
        # two-stage chunk sizing and executable — bit-identical fallback
        self._fused_kind = (scan_mode == "fused"
                            and fused_kernel.kind_supported(self.spec)
                            and self.dim <= fused_kernel.FUSED_MAX_DIM)
        if precision == "pq" and self._fused_kind:
            # the PQ kernel is gated on the subspace count, not the dim
            # (its tiles are [bm, m] codes, never [bm, D] rows)
            self._fused_kind = self._pq_m <= fused_kernel.FUSED_MAX_PQ_M
        scan_dtype = (jnp.uint8 if precision in ("int4", "pq")
                      else jnp.int8 if self._quant
                      else self._policy.compute if self._policy.mixed
                      else jnp.float32)
        # the packed lanes size their fused tiles off their own VMEM
        # footprint branches (packed width / code+LUT blocks)
        sizing_dim = 128 if precision == "pq" else self.dim
        self.chunk_rows = chunk_rows or auto_chunk_rows(
            sizing_dim, self.spec[0], self.num_nodes, tile_budget,
            scan_mode=("fused" if self._fused_kind else "two_stage"),
            dtype=scan_dtype, lane=self._lane, pq_m=self._pq_m)
        if self._fused_kind and (
                self.chunk_rows % 128
                or self.chunk_rows > fused_kernel.fused_tile_rows(
                    sizing_dim, scan_dtype, fused_kernel.FUSED_MAX_K,
                    allow_tuned=False, lane=self._lane, pq_m=self._pq_m)):
            # allow_tuned=False: this check is the VMEM-FIT bound (what
            # a real chip's Mosaic would accept), not the autotuner's
            # speed preference — a tuned table picking a SMALLER tile
            # must not demote an explicit chunk_rows the model fits
            # a user chunk_rows off the 128 grid can never stream, and
            # one past the kernel's VMEM footprint model would compile
            # only on the CPU twin (Mosaic would reject the tile on a
            # real chip) — demote the ENGINE: it must advertise itself
            # as what it actually serves (scan_signature without the
            # "fused" marker) and dispatch two-stage everywhere, IVF
            # probes included, not just where a per-call gate happens
            # to catch it
            self._fused_kind = False
        # the mode every dispatch actually uses: a demoted fused engine
        # IS the two-stage executable (bit-identical fallback contract)
        self._scan_mode_eff = (scan_mode
                               if scan_mode != "fused" or self._fused_kind
                               else "two_stage")
        # each shard's slab must itself be a chunk multiple, so the
        # padded table is a (chunk × shards) multiple (shards=1: the
        # original chunk-multiple padding, bit-identical layout)
        padded = _round_up(self.num_nodes, self.chunk_rows * shards)
        if padded > self.num_nodes:
            table = np.concatenate(
                [table, np.zeros((padded - self.num_nodes, self.dim),
                                 table.dtype)], axis=0)
        if shards > 1:
            # [padded, D] row-sharded: each device holds padded/S rows
            self.table = jax.device_put(
                table, table_sharding(mesh, mesh_axis))
        else:
            self.table = jnp.asarray(table)  # [padded, D] device-resident
        # the low-precision scan copy lives beside the f32 table (same
        # layout/sharding) — built ONCE here, not per query; the f32
        # policy aliases the table so the default path holds one array
        self.scan_scale = None
        self.pq_codebooks = None
        self._pq_fp = None
        if self._quant:
            put = ((lambda a: jax.device_put(
                a, table_sharding(mesh, mesh_axis)))
                if shards > 1 else jnp.asarray)
            pad_rows = padded - self.num_nodes

            def _pad0(a):
                # payload arrays cover the UNPADDED table; grow them
                # with zero rows (zero codes/scales dequantize to exact
                # zeros — and padded rows are masked by index anyway)
                if not pad_rows:
                    return np.ascontiguousarray(a)
                return np.concatenate(
                    [a, np.zeros((pad_rows,) + a.shape[1:], a.dtype)],
                    axis=0)

            if precision == "int8":
                from hyperspace_tpu.serve.quant import quantize_rows

                # quantize the PADDED table: zero padding rows get scale
                # 0 and dequantize to exact zeros, like the f32 padding
                q8, sc = quantize_rows(table)
                self.scan_table, self.scan_scale = put(q8), put(sc)
            elif precision == "int4":
                from hyperspace_tpu.serve.quant import pack_int4_rows

                if self._payload is not None:
                    pk = _pad0(self._payload.arrays["packed"])
                    sc = _pad0(self._payload.arrays["scale"])
                else:
                    pk, sc = pack_int4_rows(table)
                # the scale stays f16 resident (the lane's byte budget);
                # both scan paths cast to f32 at the point of use
                self.scan_table, self.scan_scale = put(pk), put(sc)
            else:  # pq
                from hyperspace_tpu.serve.quant import (build_pq,
                                                        pq_fingerprint_of)

                if self._payload is not None:
                    codes = _pad0(self._payload.arrays["codes"])
                    cb = np.asarray(self._payload.arrays["codebooks"],
                                    np.float32)
                    pp = self._payload.params
                    self._pq_fp = pq_fingerprint_of(
                        cb, lift_dim=int(pp["lift_dim"]),
                        iters=int(pp["iters"]), seed=int(pp["seed"]))
                else:
                    # train on the UNPADDED rows (pad rows would skew
                    # the subspace k-means), pad the codes after
                    codes, cbk = build_pq(table[:self.num_nodes],
                                          self.spec, m=self._pq_m)
                    codes, cb = _pad0(codes), cbk.codebooks
                    self._pq_fp = cbk.fingerprint
                self.scan_table = put(codes)
                # codebooks are KB-scale: replicated, never sharded
                self.pq_codebooks = jnp.asarray(cb, jnp.float32)
        elif self._policy.mixed:
            scan_np = table.astype(self._policy.compute)
            self.scan_table = (
                jax.device_put(scan_np, table_sharding(mesh, mesh_axis))
                if shards > 1 else jnp.asarray(scan_np))
        else:
            self.scan_table = self.table

        # --- IVF probing (docs/serving.md "Approximate retrieval") ---
        from hyperspace_tpu.serve.index import IVF_MIN_TABLE_ROWS
        self.index, self.nprobe = index, int(nprobe)
        if self.nprobe < 0:
            raise ValueError(f"nprobe must be >= 0; got {nprobe}")
        if self.nprobe > 0 and index is None:
            raise ValueError(
                "nprobe > 0 needs an IVF index (build one with "
                "serve.index.build_index, or export with index=1)")
        if index is not None:
            if int(index.num_nodes) != self.num_nodes:
                raise ValueError(
                    f"index was built over {index.num_nodes} rows; "
                    f"table has {self.num_nodes}")
            if int(index.centroids.shape[1]) != self.dim:
                raise ValueError(
                    f"index centroid width {index.centroids.shape[1]} "
                    f"!= table width {self.dim}")
            if self.nprobe > 0 and shards > 1:
                raise ValueError(
                    "IVF probing is single-device; drop mesh= or nprobe= "
                    "(a sharded table answers by exact scan)")
        self._ivf = (index is not None and 0 < self.nprobe < index.ncells
                     and self.num_nodes >= IVF_MIN_TABLE_ROWS)
        if self._ivf:
            self._centroids = jnp.asarray(index.centroids, jnp.float32)
            self._cells = jnp.asarray(index.cells, jnp.int32)
            # candidate chunks gather [B, chunk, D] rows per tile — the
            # product-path footprint whatever the family — but unlike
            # the slab scan there is no resident table sharing the
            # budget, so the tile gets 4× of it; measured sweet spot on
            # the CPU twin (chunk 512 at D=16: 1.5× over 128)
            self._cand_chunk = auto_chunk_rows(
                self.dim, "product", self.nprobe * index.max_cell,
                4 * tile_budget)

    @property
    def scan_strategy(self) -> str:
        """``"ivf"`` when queries probe the index, else ``"exact"``
        (covers every fallback rule — what `batcher.stats()` reports)."""
        return "ivf" if self._ivf else "exact"

    @property
    def scan_signature(self) -> tuple:
        """Result-identity of the scan path: ``("exact",)`` or
        ``("ivf", nprobe, index fingerprint)`` — a batcher cache-key
        element, so exact and probed rows (or rows probed through two
        different indexes) never cross-contaminate.  A fused-capable
        engine appends ``"fused"``: fused answers are rank-identical to
        the two-stage scan but only ulp-close in distance, so its cached
        rows must never be served back as two-stage rows (or vice
        versa) over the same table."""
        sig = (("ivf", self.nprobe, self.index.fingerprint) if self._ivf
               else ("exact",))
        return sig + self._lane_markers()

    def scan_signature_for(self, nprobe: int) -> tuple:
        """The signature :attr:`scan_signature` would have at an
        overridden probe width — the degradation ladder's cache-key hook
        (``serve/batcher.py``): narrowed-width rows carry the narrowed
        signature, fused and lane markers included."""
        sig = ("ivf", int(nprobe), self.index.fingerprint)
        return sig + self._lane_markers()

    def _lane_markers(self) -> tuple:
        """Result-identity suffixes shared by every signature variant:
        ``"fused"`` (rank-identical but only ulp-close distances) and
        the quantized scan lane (``"int8"``/``"int4"``, or ``("pq",
        codebook fingerprint)`` — different candidate sets than the f32
        or bf16 scans, and two PQ engines with different codebooks
        produce different candidate sets, so the fingerprint rides in
        the key; quantized rows must never be served back as
        full-precision rows, whatever else the cache key carries)."""
        lane = ()
        if self._quant:
            lane = (("pq", self._pq_fp) if self.precision == "pq"
                    else (self.precision,))
        return (("fused",) if self._fused_kind else ()) + lane

    def _k_scan(self, k: int, cap: int) -> int:
        """Over-fetch width of the low-precision coarse scan: the f32
        rescore can only repair a k-th-boundary mis-rank that is IN the
        candidate set.  int8 gets a wider margin than bf16 (coarser
        quantization step), int4/pq wider still — a 4-bit step / a
        per-subspace codebook error dominates neighbor gaps at serve
        densities (docs/serving.md)."""
        if self.precision == "pq":
            return min(k + max(_PQ_RESCORE_MULT * k,
                               _PQ_RESCORE_MIN), cap)
        if self.precision == "int4":
            return min(k + max(_INT4_RESCORE_MULT * k,
                               _INT4_RESCORE_MIN), cap)
        if self._quant:
            return min(k + max(_QUANT_RESCORE_MULT * k,
                               _QUANT_RESCORE_MIN), cap)
        return min(k + max(k, _RESCORE_PAD), cap)

    @classmethod
    def from_artifact(cls, art: ServingArtifact, **kw) -> "QueryEngine":
        kw.setdefault("index", art.index)
        kw.setdefault("quant", getattr(art, "quant", None))
        return cls(art.table, art.manifold_spec,
                   fingerprint=art.fingerprint, **kw)

    @property
    def _scan_aux(self):
        """The scan lane's traced companion operand: per-row dequant
        scales (int8/int4), the PQ codebooks, or None (f32/bf16)."""
        return (self.pq_codebooks if self.precision == "pq"
                else self.scan_scale)

    # --- queries --------------------------------------------------------------

    def topk_neighbors(self, q_idx, k: int, *, exclude_self: bool = True,
                       nprobe: int | None = None, q_rows=None, drop=None,
                       allow_underfill: bool = False):
        """``(neighbors [B, k] int32, dists [B, k])`` for query row ids.

        Results are sorted ascending by distance.  ``k`` must leave room
        in the table (``k <= N - exclude_self``); ids are validated on
        host — a bad id must fail the request, not gather a clipped row.

        ``nprobe`` (probing engines only) overrides the configured probe
        width for THIS call, within ``[1, self.nprobe]`` — the
        degradation ladder's lever (docs/resilience.md): under pressure
        the batcher steps the width down toward its floor without
        rebuilding the engine.  Each distinct width is one extra
        compiled program (bounded by the ladder's few levels); answers
        at a narrower width are coarser, and the batcher's cache key
        carries the effective width so they never mix with full-width
        rows.  Exact engines reject an override — a silent ignore would
        misreport the quality served.

        ``q_rows`` / ``drop`` / ``allow_underfill`` are the live-index
        hooks (serve/delta.py).  ``q_rows`` ([B, D] f32) supplies the
        query vectors explicitly — fresh post-upsert rows from the
        mutable master — instead of gathering the (possibly stale)
        frozen device rows by id; ids are then used only for the
        exclude-self mask and may exceed this engine's row range.
        ``drop`` ([npad] f32, 0 = live / +inf = tombstoned) is a TRACED
        penalty row added to every scan tile before top-k so a deleted
        or superseded master row can never win — values change per
        mutation generation without recompiling.  ``allow_underfill``
        lets a probing engine return +inf filler rows instead of
        raising, so the caller's merge with a delta segment can repair
        them (and raise only if the MERGED top-k is still under-filled).
        """
        if q_rows is None:
            q_idx = self._check_ids(q_idx, "q_idx")
        else:
            arr = np.asarray(q_idx)
            if arr.ndim != 1 or arr.size == 0:
                raise ValueError("q_idx must be a non-empty 1-D id array")
            if not np.issubdtype(arr.dtype, np.integer):
                raise ValueError(
                    f"q_idx must be integer ids; got {arr.dtype}")
            q_rows = jnp.asarray(q_rows, self.table.dtype)
            if q_rows.ndim != 2 or q_rows.shape[0] != arr.size:
                raise ValueError(
                    f"q_rows {q_rows.shape} must be [B, D] aligned with "
                    f"q_idx (B={arr.size})")
            q_idx = jnp.asarray(arr, jnp.int32)
        if drop is not None:
            drop = jnp.asarray(drop, self.table.dtype)
            if drop.shape != (self.table.shape[0],):
                raise ValueError(
                    f"drop mask shape {drop.shape} must match the padded "
                    f"table rows ({self.table.shape[0]},)")
        k = int(k)
        limit = self.num_nodes - (1 if exclude_self else 0)
        if not 1 <= k <= limit:
            raise ValueError(
                f"k={k} out of range [1, {limit}] for a {self.num_nodes}-row "
                f"table (exclude_self={exclude_self})")
        if nprobe is not None and not self._ivf:
            raise ValueError(
                "nprobe override needs a probing engine (this one "
                "answers by exact scan)")
        # the "device_compute" span stage: the whole fused program —
        # scan + f32 rescore + merge run inside ONE jit executable, so
        # this window is the engine's full device dispatch; inside a
        # span scope the results are forced before the stage closes, so
        # the window times execution, not async enqueue (spans off:
        # a shared no-op context manager, nothing blocks)
        with spans.stage("device_compute",
                         metric="serve/stage/device_compute_ms"):
            if self._ivf:
                out = self._probe_topk(q_idx, k, exclude_self=exclude_self,
                                       nprobe=nprobe, drop=drop,
                                       q_rows=q_rows,
                                       allow_underfill=allow_underfill)
            elif self._policy.mixed or self._quant:
                # over-fetch margin: the low-precision scan keeps k_scan
                # candidates so the f32 rescore can repair k-th-boundary
                # near-ties (wider for int8 — coarser quantization)
                k_scan = self._k_scan(k, self.num_nodes)
                if self.shards > 1:
                    out = _topk_sharded_mixed(
                        self.table, self.scan_table, self._scan_aux, q_idx,
                        drop, q_rows,
                        spec=self.spec, k=k, k_scan=k_scan,
                        chunk=self.chunk_rows,
                        n=self.num_nodes, exclude_self=exclude_self,
                        mode=self._scan_mode_eff, mesh=self.mesh,
                        axis=self.mesh_axis, lane=self._lane)
                else:
                    out = _topk_chunked_mixed(
                        self.table, self.scan_table, self._scan_aux, q_idx,
                        drop, q_rows,
                        spec=self.spec, k=k,
                        k_scan=k_scan, chunk=self.chunk_rows,
                        n=self.num_nodes,
                        exclude_self=exclude_self, mode=self._scan_mode_eff,
                        lane=self._lane)
            elif self.shards > 1:
                out = _topk_sharded(
                    self.table, q_idx, drop, q_rows, spec=self.spec, k=k,
                    chunk=self.chunk_rows, n=self.num_nodes,
                    exclude_self=exclude_self, mode=self._scan_mode_eff,
                    mesh=self.mesh, axis=self.mesh_axis)
            else:
                out = _topk_chunked(
                    self.table, q_idx, drop, q_rows, spec=self.spec, k=k,
                    chunk=self.chunk_rows,
                    n=self.num_nodes, exclude_self=exclude_self,
                    mode=self._scan_mode_eff)
            if spans.active():
                jax.block_until_ready(out)
        return out

    def _probe_topk(self, q_idx: jax.Array, k: int, *, exclude_self: bool,
                    nprobe: int | None = None, drop=None, q_rows=None,
                    allow_underfill: bool = False):
        """The probing path: validate capacity, dispatch
        :func:`_topk_ivf`, record the probe telemetry
        (``serve/index_probe_ms``: host wall-clock around the dispatch —
        on CPU, execution; ``serve/recall_candidates``: candidate slots
        gathered, the work the probe actually did vs the exact scan's
        ``B × N``).  ``nprobe`` narrows the probe for this call (the
        ladder's lever; validated against the configured width)."""
        p = self.nprobe if nprobe is None else int(nprobe)
        if not 1 <= p <= self.nprobe:
            raise ValueError(
                f"nprobe override {p} out of range [1, {self.nprobe}] "
                "(wider than configured would gather rows the resident "
                "chunking was not sized for)")
        capacity = p * self.index.max_cell
        if capacity < k:
            raise ValueError(
                f"k={k} exceeds the probe capacity nprobe×max_cell = "
                f"{p}×{self.index.max_cell} = {capacity}; "
                "raise nprobe=")
        k_scan = k
        if self._policy.mixed or self._quant:
            k_scan = self._k_scan(k, capacity)
        t0 = time.perf_counter()
        idx, dist = _topk_ivf(
            self.table, self.scan_table,
            self._centroids, self._cells,
            q_idx, drop, q_rows, spec=self.spec, k=k, k_scan=k_scan,
            nprobe=p,
            chunk=self._cand_chunk, exclude_self=exclude_self,
            mixed=self._policy.mixed or self._quant,
            mode=self._scan_mode_eff, scan_scale=self._scan_aux,
            lane=self._lane)
        telem.observe("serve/index_probe_ms",
                      (time.perf_counter() - t0) * 1e3)
        telem.inc("serve/recall_candidates", int(q_idx.shape[0]) * capacity)
        # under-filled probe: some query's nprobe nearest cells held
        # fewer than k reachable rows, so filler reached the top-k —
        # not an answer (docs/serving.md), and +inf would break the
        # serve protocol's JSON.  The distance is the reliable tell
        # (a padded slot carries -1 OR a masked self id, but always
        # +inf).  Fail loudly like the capacity check (a scalar fetch;
        # callers fetch these results next anyway, and the serve loop
        # isolates it per request)
        if not allow_underfill and \
                bool(jax.device_get(jnp.any(jnp.isinf(dist)))):
            raise ValueError(
                f"IVF probe under-filled: some query's {p} "
                f"nearest cell(s) hold fewer than k={k} reachable rows "
                "(sparse/empty cells, or exclude_self masking one) — "
                "raise nprobe= or rebuild the index with more balance")
        return idx, dist

    def score_edges(self, u_idx, v_idx, *, prob: bool = False,
                    fd_r: float = 2.0, fd_t: float = 1.0):
        """Per-pair manifold distances ``d(table[u], table[v])`` ([B]).

        ``prob=True`` maps distances through the Fermi–Dirac link
        decoder ``1 / (exp((d² − r)/t) + 1)`` (the HGCN LP head's form)
        — monotone decreasing in distance, so rankings agree.
        """
        u_idx = self._check_ids(u_idx, "u_idx")
        v_idx = self._check_ids(v_idx, "v_idx")
        if u_idx.shape != v_idx.shape:
            raise ValueError(
                f"u_idx {u_idx.shape} and v_idx {v_idx.shape} must match")
        with spans.stage("device_compute",
                         metric="serve/stage/device_compute_ms"):
            if self.shards > 1:
                out = _edge_dist_sharded(
                    self.table, u_idx, v_idx, fd_r, fd_t,
                    spec=self.spec, prob=bool(prob),
                    mesh=self.mesh, axis=self.mesh_axis)
            else:
                out = _edge_dist(self.table, u_idx, v_idx, fd_r, fd_t,
                                 spec=self.spec, prob=bool(prob))
            if spans.active():
                jax.block_until_ready(out)
        return out

    def _check_ids(self, ids, name: str) -> jax.Array:
        arr = np.asarray(ids)
        if arr.ndim != 1 or arr.size == 0:
            raise ValueError(f"{name} must be a non-empty 1-D id array")
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError(f"{name} must be integer ids; got {arr.dtype}")
        if arr.min() < 0 or arr.max() >= self.num_nodes:
            raise ValueError(
                f"{name} out of range [0, {self.num_nodes}): "
                f"min={arr.min()}, max={arr.max()}")
        return jnp.asarray(arr, jnp.int32)
