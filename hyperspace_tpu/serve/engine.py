"""Jitted batched query engine over a frozen embedding table.

The inference workloads of the paper's retrieval models are two device
programs over an [N, D] table of manifold points:

- ``topk_neighbors(q_idx, k)`` — the k nearest table rows to each query
  row under the hyperbolic metric (Poincaré-embedding retrieval à la
  Nickel & Kiela 2017);
- ``score_edges(u_idx, v_idx)`` — per-pair distances (optionally pushed
  through the Fermi–Dirac link decoder) for edge scoring à la the HGCN
  LP head (Chami et al. 2019).

Mechanics:

- **Distance tiles come from the fused kernels.**  Poincaré/Lorentz
  tiles go through :func:`hyperspace_tpu.kernels.distmat.pdist` — the
  Pallas TPU kernel on a TPU backend, the XLA twin on CPU — so a [B, M]
  tile never materializes a [B, M, D] difference tensor.  Product
  manifolds use ``Product.dist`` broadcast per tile (exactly the trained
  geometry, learned curvatures frozen into the spec).
- **The table is chunked.**  The k-NN scan walks the table
  ``chunk_rows`` rows at a time, carrying a running top-k, so the live
  distance working set is one [B, chunk] tile (plus [B, chunk, D] on
  the product path) regardless of N — ``tile_budget`` picks the chunk.
  The table is zero-padded ONCE at engine build to a chunk multiple;
  padded rows are masked to +inf distance by index, so they can never
  appear in a result.
- **Compiles are keyed on (bucket, k), never on request.**  The jitted
  programs hang everything shape-like on static arguments (batch size,
  k, chunk, N, the manifold spec tuple); the request batcher
  (``serve/batcher.py``) pads incoming batches to a small set of
  power-of-two buckets, so the engine compiles once per (bucket, k) and
  then serves any request size out of the same executable —
  ``jax/recompiles`` stays flat (the e2e test asserts it).

Determinism: for a fixed (bucket, k, chunk) the program is one fixed
XLA executable — the same table bytes give bitwise-identical results,
which is what lets ``scripts/check_serve_artifact.py`` demand
export → load → query equals the live model bit-for-bit.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.serve.artifact import (ServingArtifact, fingerprint_of,
                                           manifold_from_spec)

# f32 bytes a distance tile may occupy ([B, chunk] on the kernel path,
# [B, chunk, D] on the product path), per the nominal batch below.
DEFAULT_TILE_BUDGET = 8 * 1024 * 1024
# chunk sizing assumes batches up to this (the batcher's default
# max_bucket); bigger batches just run a proportionally bigger tile.
NOMINAL_BATCH = 1024
_ROW_ALIGN = 128


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def auto_chunk_rows(dim: int, spec_kind: str, n: int,
                    tile_budget: int = DEFAULT_TILE_BUDGET) -> int:
    """Table-chunk rows that keep one distance tile under the budget."""
    per_row = 4 * NOMINAL_BATCH * (dim if spec_kind == "product" else 1)
    chunk = max(_ROW_ALIGN, (tile_budget // per_row) // _ROW_ALIGN * _ROW_ALIGN)
    return min(chunk, _round_up(max(n, 1), _ROW_ALIGN))


def _tile_dist(spec: tuple, q: jax.Array, rows: jax.Array) -> jax.Array:
    """[B, D] × [M, D] → [B, M] distances under the spec's manifold."""
    kind = spec[0]
    if kind in ("poincare", "lorentz"):
        from hyperspace_tpu.kernels.distmat import pdist

        return pdist(q, rows, spec[1], manifold=kind)
    m = manifold_from_spec(spec)
    return m.dist(q[:, None, :], rows[None, :, :])


@partial(jax.jit, static_argnames=("spec", "k", "chunk", "n", "exclude_self"))
def _topk_chunked(table: jax.Array, q_idx: jax.Array, *, spec: tuple,
                  k: int, chunk: int, n: int, exclude_self: bool):
    """Running top-k over table chunks; one fixed program per
    (batch, k, chunk, n, spec)."""
    q = table[q_idx]  # [B, D]
    b = q_idx.shape[0]
    nchunks = table.shape[0] // chunk

    def body(carry, i):
        best_d, best_i = carry
        rows = jax.lax.dynamic_slice_in_dim(table, i * chunk, chunk)
        d = _tile_dist(spec, q, rows)                     # [B, chunk]
        # pin int32: under x64 the traced chunk offset would promote the
        # carried index dtype and break the scan carry contract
        cols = (i * chunk + jnp.arange(chunk)).astype(jnp.int32)
        mask = cols[None, :] >= n                         # zero-padded rows
        if exclude_self:
            mask = mask | (cols[None, :] == q_idx[:, None])
        d = jnp.where(mask, jnp.inf, d)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(cols, d.shape)], axis=1)
        top_negd, sel = jax.lax.top_k(-cat_d, k)
        return (-top_negd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (jnp.full((b, k), jnp.inf, table.dtype),
            jnp.full((b, k), -1, jnp.int32))
    (dist, idx), _ = jax.lax.scan(body, init, jnp.arange(nchunks))
    return idx, dist


@partial(jax.jit, static_argnames=("spec", "prob"))
def _edge_dist(table: jax.Array, u_idx: jax.Array, v_idx: jax.Array,
               fd_r, fd_t, *, spec: tuple, prob: bool) -> jax.Array:
    m = manifold_from_spec(spec)
    d = m.dist(table[u_idx], table[v_idx])
    if prob:
        # Fermi–Dirac decoder INSIDE the jitted program: one dispatch
        # per scoring request, not one per arithmetic op (fd_r/fd_t are
        # traced scalars — changing them never recompiles)
        d = 1.0 / (jnp.exp((jnp.square(d) - fd_r) / fd_t) + 1.0)
    return d


class QueryEngine:
    """Batched k-NN / edge-score queries over one frozen table.

    ``table`` is moved to device once (zero-padded to a chunk multiple);
    every query after that is a single jitted dispatch.  Construct via
    :meth:`from_artifact` for the serving path, or directly on a live
    table (tests, the round-trip lint).
    """

    def __init__(self, table, manifold_spec: tuple, *,
                 fingerprint: Optional[str] = None,
                 chunk_rows: int = 0,
                 tile_budget: int = DEFAULT_TILE_BUDGET):
        table = np.ascontiguousarray(np.asarray(table))
        if table.ndim != 2:
            raise ValueError(f"table must be [N, D]; got {table.shape}")
        self.num_nodes, self.dim = (int(s) for s in table.shape)
        self.spec = tuple(manifold_spec)
        self.fingerprint = fingerprint or fingerprint_of(table, self.spec)
        chunk_rows = int(chunk_rows)
        if chunk_rows < 0:
            # a negative chunk would make the scan run ZERO chunks and
            # silently answer every query with -1/inf
            raise ValueError(f"chunk_rows must be >= 0 (0 = auto); "
                             f"got {chunk_rows}")
        self.chunk_rows = chunk_rows or auto_chunk_rows(
            self.dim, self.spec[0], self.num_nodes, tile_budget)
        padded = _round_up(self.num_nodes, self.chunk_rows)
        if padded > self.num_nodes:
            table = np.concatenate(
                [table, np.zeros((padded - self.num_nodes, self.dim),
                                 table.dtype)], axis=0)
        self.table = jnp.asarray(table)  # [padded, D] device-resident

    @classmethod
    def from_artifact(cls, art: ServingArtifact, **kw) -> "QueryEngine":
        return cls(art.table, art.manifold_spec,
                   fingerprint=art.fingerprint, **kw)

    # --- queries --------------------------------------------------------------

    def topk_neighbors(self, q_idx, k: int, *, exclude_self: bool = True):
        """``(neighbors [B, k] int32, dists [B, k])`` for query row ids.

        Results are sorted ascending by distance.  ``k`` must leave room
        in the table (``k <= N - exclude_self``); ids are validated on
        host — a bad id must fail the request, not gather a clipped row.
        """
        q_idx = self._check_ids(q_idx, "q_idx")
        k = int(k)
        limit = self.num_nodes - (1 if exclude_self else 0)
        if not 1 <= k <= limit:
            raise ValueError(
                f"k={k} out of range [1, {limit}] for a {self.num_nodes}-row "
                f"table (exclude_self={exclude_self})")
        idx, dist = _topk_chunked(
            self.table, q_idx, spec=self.spec, k=k, chunk=self.chunk_rows,
            n=self.num_nodes, exclude_self=exclude_self)
        return idx, dist

    def score_edges(self, u_idx, v_idx, *, prob: bool = False,
                    fd_r: float = 2.0, fd_t: float = 1.0):
        """Per-pair manifold distances ``d(table[u], table[v])`` ([B]).

        ``prob=True`` maps distances through the Fermi–Dirac link
        decoder ``1 / (exp((d² − r)/t) + 1)`` (the HGCN LP head's form)
        — monotone decreasing in distance, so rankings agree.
        """
        u_idx = self._check_ids(u_idx, "u_idx")
        v_idx = self._check_ids(v_idx, "v_idx")
        if u_idx.shape != v_idx.shape:
            raise ValueError(
                f"u_idx {u_idx.shape} and v_idx {v_idx.shape} must match")
        return _edge_dist(self.table, u_idx, v_idx, fd_r, fd_t,
                          spec=self.spec, prob=bool(prob))

    def _check_ids(self, ids, name: str) -> jax.Array:
        arr = np.asarray(ids)
        if arr.ndim != 1 or arr.size == 0:
            raise ValueError(f"{name} must be a non-empty 1-D id array")
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError(f"{name} must be integer ids; got {arr.dtype}")
        if arr.min() < 0 or arr.max() >= self.num_nodes:
            raise ValueError(
                f"{name} out of range [0, {self.num_nodes}): "
                f"min={arr.min()}, max={arr.max()}")
        return jnp.asarray(arr, jnp.int32)
