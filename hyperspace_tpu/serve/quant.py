"""Sub-f32 quantization for the serve table's coarse-scan lanes
(docs/serving.md "Quantized scan lane"): per-row symmetric int8 and
int4, and hyperbolic-aware product quantization (PQ).

The bf16 scan-then-f32-rescore pattern (PR 5) and the fused kernel's
half-byte bf16 slab streaming (PR 10) both rest on one property: a
LOW-PRECISION coarse pass only has to keep the true top-k inside its
over-fetched candidate set — the exact f32 rescore picks the answer.
int8 is the same trick at 4× the capacity and bandwidth win: the scan
copy stores one signed byte per element plus one f32 scale per row,

    scale[i] = max(|table[i, :]|) / 127        (0 for an all-zero row)
    q[i, :]  = round(table[i, :] / scale[i])   clipped to [-127, 127]

and every consumer dequantizes **in-register** (``q.astype(f32) *
scale``) right before the distance math, so the arithmetic of the
coarse pass is still f32 — the int8 cost is the table quantization
error only, and it never reaches a returned distance (those come from
the f32 rescore against the f32 master table).

Per-ROW scaling matters for the hyperbolic families: a Lorentz row's
time coordinate (~1/√c + ‖x_s‖²-ish) dwarfs its spatial coordinates,
and a single per-table scale would crush the spatial lanes to a couple
of quantization levels.  Per-row, each row spends its 8 bits on its own
dynamic range.

Symmetric (zero-point-free) quantization keeps the dequantize a single
multiply — no add riding into the kernel's Gram matmuls — and maps
0 → 0 exactly, which the engine's zero-row padding relies on.

The two quarter-precision lanes (ISSUE 16) push below int8:

- **int4** packs two signed nibbles per byte in a *planar* layout:
  byte column ``j`` of a row holds element ``j`` in its low nibble and
  element ``hw + j`` (``hw = ceil(D/2)``) in its high nibble.  The
  unpacked element order is therefore ``concat(low_nibbles,
  high_nibbles)`` — a static lane permutation, never an interleave, so
  the kernel's in-register unpack is two shifts and a concatenate and
  element 0 stays in lane 0 (the Lorentz time flip keeps working on
  lane-padded tiles).  The per-row symmetric scale is stored
  **float16** (cast to f32 at use): at 10M×8 that is 4 B codes + 2 B
  scale per row = 60 MB vs int8's 114 MB.
- **PQ** splits the row into ``m`` subspaces of ``ds`` coordinates and
  stores one uint8 centroid code per subspace.  Codebooks are trained
  in the **lift** of ``serve/index.py``'s Lloyd loop (poincare rows
  lift to the Lorentz hyperboloid, product specs lift per factor), so
  the Euclidean per-subspace k-means respects the geometry the scan
  distance is computed in, and the ADC trick applies: for the
  lorentz-gram families the scan distance depends on a candidate only
  through the *additive* ``⟨q_L, y_L⟩_L``, so one per-query lookup
  table of subspace partial inner products replaces the Gram matmul.

Both lanes keep the int8 contract shape — the coarse pass only has to
keep the true top-k inside the over-fetch window, final ranks and
distances always come from the f32 rescore — at a wider ``k +
max(16k, 128)`` window: a 4-bit step (or a 256-way subspace codebook)
is far coarser than int8's per-element step, so the coarse ranking
noise swamps neighbor gaps much sooner as table density grows.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

# int8 levels per side: symmetric, so -128 is never produced and the
# dequantized range is exactly [-max|row|, +max|row|]
QLEVELS = 127


def quantize_rows(table: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int8 quantization.

    ``table`` [N, D] float → ``(q [N, D] int8, scale [N, 1] float32)``
    with ``q * scale ≈ table`` (max abs error ``scale/2`` per element).
    All-zero rows get scale 0 and q 0, so they dequantize to exactly 0
    (the engine's padding rows stay inert).
    """
    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        raise ValueError(f"table must be [N, D]; got {table.shape}")
    amax = np.max(np.abs(table), axis=1, keepdims=True)     # [N, 1]
    scale = (amax / QLEVELS).astype(np.float32)
    # guard the divide only — a zero scale still lands in the output so
    # dequantize(q, 0) == 0 without a special case anywhere downstream
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint(table / safe), -QLEVELS, QLEVELS).astype(np.int8)
    return q, scale


def dequantize_rows(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """The exact inverse the device paths apply in-register:
    ``q.astype(f32) * scale`` — host-side twin for tests/tools."""
    return q.astype(np.float32) * np.asarray(scale, np.float32)


def quant_error_bound(scale: np.ndarray) -> float:
    """Max per-element reconstruction error: half a quantization step
    of the worst row (``max(scale)/2``) — what the engine's over-fetch
    margin is sized against (docs/serving.md)."""
    s = np.asarray(scale, np.float32)
    return float(s.max() / 2.0) if s.size else 0.0


# --- int4 lane ----------------------------------------------------------------

# int4 levels per side: symmetric two's-complement nibbles in [-7, 7]
# (-8 is never produced, mirroring the int8 lane's -128 rule)
QLEVELS4 = 7


def int4_packed_width(dim: int) -> int:
    """Packed byte columns per row: two elements per byte, planar."""
    return (int(dim) + 1) // 2


def pack_int4_rows(table: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int4 quantization, two nibbles per byte.

    ``table`` [N, D] float → ``(packed [N, ceil(D/2)] uint8,
    scale [N, 1] float16)``.  Byte ``j`` holds element ``j`` (low
    nibble) and element ``hw + j`` (high nibble, ``hw = ceil(D/2)``;
    zero when past D).  The scale is quantized to float16 FIRST and the
    codes are fitted against the stored value, so the host twin
    (:func:`unpack_int4_rows` × ``scale``) and the device's in-register
    unpack reconstruct bit-identically.  All-zero rows get scale 0 and
    codes 0.
    """
    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        raise ValueError(f"table must be [N, D]; got {table.shape}")
    n, d = table.shape
    amax = np.max(np.abs(table), axis=1, keepdims=True)          # [N, 1]
    scale = (amax / QLEVELS4).astype(np.float16)                 # stored
    s32 = scale.astype(np.float32)
    safe = np.where(s32 > 0, s32, 1.0)
    q = np.clip(np.rint(table / safe), -QLEVELS4, QLEVELS4).astype(np.int8)
    hw = int4_packed_width(d)
    planar = np.zeros((n, 2 * hw), np.int8)
    planar[:, :d] = q
    lo = planar[:, :hw].astype(np.uint8) & 0xF
    hi = planar[:, hw:].astype(np.uint8) & 0xF
    return (lo | (hi << 4)).astype(np.uint8), scale


def unpack_int4_rows(packed: np.ndarray, dim: int) -> np.ndarray:
    """Host twin of the device unpack: ``packed`` [N, hw] uint8 →
    signed int8 codes [N, dim] (low nibbles first, then high)."""
    packed = np.asarray(packed, np.uint8)
    lo = (packed & 0xF).astype(np.int8)
    hi = (packed >> 4).astype(np.int8)
    lo = np.where(lo >= 8, lo - 16, lo)
    hi = np.where(hi >= 8, hi - 16, hi)
    return np.concatenate([lo, hi], axis=-1)[..., :int(dim)]


def unpack_int4_jnp(packed, dim: int):
    """Traced (jax.numpy) twin of :func:`unpack_int4_rows`: ``packed``
    [..., hw] uint8 → signed int32 codes [..., dim].  The ONE in-trace
    nibble-unpack recipe serve code may use — the ``packing-literal``
    lint fences the raw ``& 0xF`` / ``>> 4`` idiom into this module and
    ``kernels/`` so the planar layout can never fork silently."""
    import jax.numpy as jnp

    t = packed.astype(jnp.int32)
    lo = t & 0xF
    hi = t >> 4
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    return jnp.concatenate([lo, hi], axis=-1)[..., :int(dim)]


def dequantize_int4_rows(packed: np.ndarray, scale: np.ndarray,
                         dim: int) -> np.ndarray:
    """``unpack × scale`` in f32 — exactly what the scan paths apply."""
    codes = unpack_int4_rows(packed, dim).astype(np.float32)
    return codes * np.asarray(scale, np.float32)


# --- PQ lane ------------------------------------------------------------------

PQ_VERSION = 1
# centroids per subspace — one uint8 code
PQ_CENTERS = 256


def default_pq_m(lift_dim: int) -> int:
    """Default subspace count: ~4 lifted coordinates per byte of code
    (10M rows at the bench's poincare dim 8 → lift 9 → m 3 → 30 MB)."""
    return max(1, (int(lift_dim) + 3) // 4)


@dataclasses.dataclass(frozen=True)
class PQCodebook:
    """Per-subspace centroid tables, trained in the manifold lift."""

    codebooks: np.ndarray  # [m, PQ_CENTERS, ds] f32, lifted coords
    lift_dim: int          # true lifted width (m*ds - lift_dim pad lanes)
    iters: int             # Lloyd iterations used
    seed: int              # k-means++ seeding RNG seed
    fingerprint: str       # content hash (arrays + train params)

    @property
    def m(self) -> int:
        return int(self.codebooks.shape[0])

    @property
    def ds(self) -> int:
        return int(self.codebooks.shape[2])


def pq_fingerprint_of(codebooks: np.ndarray, *, lift_dim: int, iters: int,
                      seed: int) -> str:
    """Content identity of a codebook set (mirrors
    ``serve/index.py:index_fingerprint_of``): sha256 over the arrays
    and the train parameters — the lane marker / cache-key ingredient,
    so engines decoding through DIFFERENT codebooks can never serve
    each other's rows."""
    codebooks = np.ascontiguousarray(codebooks)
    h = hashlib.sha256()
    h.update(json.dumps({
        "version": PQ_VERSION, "lift_dim": int(lift_dim),
        "iters": int(iters), "seed": int(seed),
        "codebooks": [list(codebooks.shape), str(codebooks.dtype)],
    }, sort_keys=True).encode())
    h.update(codebooks.tobytes())
    return h.hexdigest()


def _sq_dists(x: np.ndarray, cent: np.ndarray) -> np.ndarray:
    """[n, ds] × [k, ds] → [n, k] squared distances (matmul form)."""
    xx = np.einsum("nd,nd->n", x, x)[:, None]
    cc = np.einsum("kd,kd->k", cent, cent)[None, :]
    return np.maximum(xx - 2.0 * (x @ cent.T) + cc, 0.0)


def _kmeans_subspace(data: np.ndarray, rng, iters: int) -> np.ndarray:
    """256-center Euclidean k-means on one lifted subspace: k-means++
    D² seeding + fixed-iteration Lloyd (empty cells keep their seed,
    like the IVF builder's rule)."""
    n = data.shape[0]
    k = PQ_CENTERS
    cent = np.empty((k, data.shape[1]), np.float32)
    cent[0] = data[int(rng.integers(n))]
    d2 = _sq_dists(data, cent[:1])[:, 0]
    for j in range(1, k):
        tot = float(d2.sum())
        if tot <= 0.0:
            # fewer distinct points than centers: duplicate uniformly
            cent[j:] = data[rng.integers(0, n, size=k - j)]
            break
        cent[j] = data[int(rng.choice(n, p=d2 / tot))]
        d2 = np.minimum(d2, _sq_dists(data, cent[j:j + 1])[:, 0])
    for _ in range(int(iters)):
        assign = np.argmin(_sq_dists(data, cent), axis=1)
        sums = np.zeros_like(cent)
        np.add.at(sums, assign, data)
        cnt = np.bincount(assign, minlength=k)
        nz = cnt > 0
        cent[nz] = sums[nz] / cnt[nz, None]
    return cent


def build_pq(table: np.ndarray, spec: tuple, *, m: int = 0,
             iters: int = 6, seed: int = 0,
             sample: int = 1 << 16) -> tuple[np.ndarray, PQCodebook]:
    """Train lifted-subspace codebooks and encode the whole table.

    ``table`` [N, D] rows on the manifold → ``(codes [N, m] uint8,
    :class:`PQCodebook`)``.  Rows are lifted exactly as the IVF
    builder's Lloyd loop lifts them (``serve/index.py:_lift``), the
    lift is zero-padded to ``m*ds`` lanes, and each ``ds``-wide
    subspace trains its own 256-center Euclidean k-means on a bounded
    ``sample`` (D² seeding, ``seed``-deterministic).  Encoding assigns
    every row to its nearest centroid per subspace, chunked so the
    [chunk, 256] distance tile stays bounded at any N.
    """
    import jax.numpy as jnp

    from hyperspace_tpu.serve.index import _lift, _lift_dim

    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        raise ValueError(f"table must be [N, D]; got {table.shape}")
    n, d = table.shape
    dl = _lift_dim(spec, d)
    m = int(m) if m else default_pq_m(dl)
    if not 1 <= m <= dl:
        raise ValueError(f"pq m={m} must be in [1, lift_dim={dl}]")
    ds = (dl + m - 1) // m
    lifted = np.asarray(_lift(spec, jnp.asarray(table)), np.float32)
    if m * ds > dl:
        lifted = np.concatenate(
            [lifted, np.zeros((n, m * ds - dl), np.float32)], axis=1)
    rng = np.random.default_rng(seed)
    train = lifted if n <= sample else \
        lifted[rng.choice(n, size=sample, replace=False)]
    cbs = np.stack([
        _kmeans_subspace(train[:, s * ds:(s + 1) * ds], rng, iters)
        for s in range(m)])
    codes = np.empty((n, m), np.uint8)
    chunk = 4096
    for lo in range(0, n, chunk):
        block = lifted[lo:lo + chunk]
        for s in range(m):
            codes[lo:lo + chunk, s] = np.argmin(
                _sq_dists(block[:, s * ds:(s + 1) * ds], cbs[s]),
                axis=1).astype(np.uint8)
    fp = pq_fingerprint_of(cbs, lift_dim=dl, iters=iters, seed=seed)
    return codes, PQCodebook(codebooks=cbs, lift_dim=dl, iters=int(iters),
                             seed=int(seed), fingerprint=fp)


def pq_decode(cb: PQCodebook, codes: np.ndarray) -> np.ndarray:
    """Host twin of the device decode: codes [N, m] → lifted
    reconstructions [N, m*ds] f32 (pad lanes included)."""
    codes = np.asarray(codes)
    parts = [cb.codebooks[s][codes[:, s]] for s in range(cb.m)]
    return np.concatenate(parts, axis=-1).astype(np.float32)
