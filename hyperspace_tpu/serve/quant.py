"""Per-row symmetric int8 quantization for the serve table's coarse-scan
lane (docs/serving.md "Quantized scan lane").

The bf16 scan-then-f32-rescore pattern (PR 5) and the fused kernel's
half-byte bf16 slab streaming (PR 10) both rest on one property: a
LOW-PRECISION coarse pass only has to keep the true top-k inside its
over-fetched candidate set — the exact f32 rescore picks the answer.
int8 is the same trick at 4× the capacity and bandwidth win: the scan
copy stores one signed byte per element plus one f32 scale per row,

    scale[i] = max(|table[i, :]|) / 127        (0 for an all-zero row)
    q[i, :]  = round(table[i, :] / scale[i])   clipped to [-127, 127]

and every consumer dequantizes **in-register** (``q.astype(f32) *
scale``) right before the distance math, so the arithmetic of the
coarse pass is still f32 — the int8 cost is the table quantization
error only, and it never reaches a returned distance (those come from
the f32 rescore against the f32 master table).

Per-ROW scaling matters for the hyperbolic families: a Lorentz row's
time coordinate (~1/√c + ‖x_s‖²-ish) dwarfs its spatial coordinates,
and a single per-table scale would crush the spatial lanes to a couple
of quantization levels.  Per-row, each row spends its 8 bits on its own
dynamic range.

Symmetric (zero-point-free) quantization keeps the dequantize a single
multiply — no add riding into the kernel's Gram matmuls — and maps
0 → 0 exactly, which the engine's zero-row padding relies on.
"""

from __future__ import annotations

import numpy as np

# int8 levels per side: symmetric, so -128 is never produced and the
# dequantized range is exactly [-max|row|, +max|row|]
QLEVELS = 127


def quantize_rows(table: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int8 quantization.

    ``table`` [N, D] float → ``(q [N, D] int8, scale [N, 1] float32)``
    with ``q * scale ≈ table`` (max abs error ``scale/2`` per element).
    All-zero rows get scale 0 and q 0, so they dequantize to exactly 0
    (the engine's padding rows stay inert).
    """
    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        raise ValueError(f"table must be [N, D]; got {table.shape}")
    amax = np.max(np.abs(table), axis=1, keepdims=True)     # [N, 1]
    scale = (amax / QLEVELS).astype(np.float32)
    # guard the divide only — a zero scale still lands in the output so
    # dequantize(q, 0) == 0 without a special case anywhere downstream
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint(table / safe), -QLEVELS, QLEVELS).astype(np.int8)
    return q, scale


def dequantize_rows(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """The exact inverse the device paths apply in-register:
    ``q.astype(f32) * scale`` — host-side twin for tests/tools."""
    return q.astype(np.float32) * np.asarray(scale, np.float32)


def quant_error_bound(scale: np.ndarray) -> float:
    """Max per-element reconstruction error: half a quantization step
    of the worst row (``max(scale)/2``) — what the engine's over-fetch
    margin is sized against (docs/serving.md)."""
    s = np.asarray(scale, np.float32)
    return float(s.max() / 2.0) if s.size else 0.0
