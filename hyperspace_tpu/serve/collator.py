"""Continuous-batching collator: fill a bucket or flush at T µs.

The blocking CLI loop feeds the batcher one request at a time, so every
dispatch carries exactly one request's ids (padded) — concurrency never
amortizes a device program across callers.  The collator is the piece
that makes the bucket ladder earn its keep under concurrent load
(docs/serving.md "HTTP front door"): requests arriving on the asyncio
event loop run the batcher's validation + cache pass immediately, and
their cold ids accumulate in a **pending bucket** per
``(k, exclude_self, effective-nprobe)`` group.  A group flushes when

- its unique pending ids **exactly fill a power-of-two bucket** of the
  batcher's ladder (zero padding — nothing is gained by waiting, the
  next arrivals seed the next batch), or reach the top bucket
  (slab-split handles the rest), or
- the **max-wait deadline** ``max_wait_us`` expires, counted from the
  moment the group became non-empty — a lone request is never held
  longer than T waiting for company.

Whichever comes first.  A flush is one
:meth:`~hyperspace_tpu.serve.batcher.RequestBatcher.dispatch_topk` call
on the **single dispatch executor** (a one-worker thread pool): device
work is serialized — one executable in flight, no device-side
contention — while independent groups' flushes queue behind each other
and their member coroutines stay concurrent.  The shared dispatch is
attributed to every member's lifecycle (``serve/dispatch_ms`` and
``serve/e2e_ms`` stay honest per request) while engine slots are
counted once; ``serve/collator_flushes`` counts flushes, so
``serve/cache_miss / serve/collator_flushes`` is the realized batching
factor.

**Deadline propagation**: lifecycles are constructed with the caller's
``t_enq`` (the HTTP server stamps socket-in time), so time spent queued
in the collator counts against ``deadline_ms``.  At flush time each
member is re-checked — an expired member answers ``deadline_exceeded``
and its ids are dropped from the union (never dispatched late), without
failing the members that still have budget.  A member that expires
mid-flight (the dispatch outran its remaining budget) still caches its
rows and answers ``deadline_exceeded`` at completion — the PR 9 batcher
semantics, through the collated path.

Thread-model: every structure here is touched ONLY on the event loop
(coroutines + ``call_later`` callbacks) — no locks; the batcher's
admission counter/ladder/LRU carry their own locks and are shared with
any sync callers.  Legacy ``telemetry/trace.py`` spans are NOT opened
on this path: they nest per-thread, and interleaved coroutines would
corrupt the nesting.  The contextvar span layer (``telemetry/spans.py``)
IS threaded through: each member lifecycle owns a span tree, and a
flush builds ONE shared ``flush`` span adopted into every member's
tree (N requests → 1 flush → the same subtree in N trees), carried
across the executor boundary explicitly with ``spans.use`` — the
run_in_executor hop does not propagate contextvars on its own.
"""

from __future__ import annotations

import asyncio
import collections
import functools
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from hyperspace_tpu.serve.batcher import (RequestBatcher, _CACHE_ONLY,
                                          _Lifecycle, bucket_for)
from hyperspace_tpu.serve.errors import (DeadlineExceededError,
                                         OverloadedError, ServeError,
                                         kind_of)
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import spans
from hyperspace_tpu.telemetry.exposition import tenant_metric

# default max-wait before a non-full pending bucket flushes (µs).  Small
# on purpose: T bounds the latency floor every collated request pays;
# 2 ms buys collation at a few hundred qps without moving a CPU-scale
# p50 (an engine dispatch is ≥ that).
DEFAULT_MAX_WAIT_US = 2000


class _Member:
    """One awaiting topk request's share of a pending bucket."""

    __slots__ = ("fut", "misses", "life")

    def __init__(self, fut: asyncio.Future, misses: list, life: _Lifecycle):
        self.fut = fut
        self.misses = misses
        self.life = life


class _Group:
    """The pending bucket for one (k, exclude_self, nprobe_ov) key."""

    __slots__ = ("members", "pending", "timer", "keyf")

    def __init__(self, keyf):
        self.members: list[_Member] = []
        self.pending: set = set()  # unique cold ids across members
        self.timer = None
        self.keyf = keyf


class FairDispatcher:
    """Deficit-round-robin scheduler for the shared dispatch executor.

    A multi-tenant front door (serve/registry.py) runs one collator per
    tenant but keeps the ONE one-worker dispatch executor — device work
    stays serialized.  Raw FIFO submission would let a hot tenant's
    bucket stream occupy every executor slot and starve the others'
    p99; this dispatcher interposes per-tenant job queues drained by
    classic deficit round robin (Shreedhar & Varghese): each visit to a
    tenant's non-empty queue adds ``weight × quantum`` to its deficit
    counter, and its head job dispatches once the deficit covers the
    job's COST (the flush's unique id count — the actual device work),
    paying the cost down.  Weights come from the tenant config; a
    tenant whose queue empties forfeits its leftover deficit, so idle
    tenants accrue no credit to burst with later.

    At most ONE job is in flight at a time (the executor has one worker
    anyway — queueing a second would just reorder inside the pool and
    bypass this policy); the done-callback re-pumps on the event loop.
    Every structure is event-loop-only, like the collator's groups —
    no locks.  Single-tenant collators (no dispatcher passed) keep the
    direct ``run_in_executor`` path, byte-identical behavior.
    """

    def __init__(self, executor: ThreadPoolExecutor, *,
                 weights: Optional[dict] = None, quantum: int = 8):
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1; got {quantum}")
        self._exec = executor
        self._weights = dict(weights or {})
        self._quantum = int(quantum)
        self._queues: dict = {}    # tenant -> deque[(cost, fn, fut)]
        self._deficit: dict = {}   # tenant -> accumulated credit
        self._rr: collections.deque = collections.deque()  # visit order
        self._busy = False

    def weight(self, tenant) -> float:
        """The tenant's configured share (default 1.0, floor > 0 so a
        misconfigured zero weight throttles hard instead of halting)."""
        return max(float(self._weights.get(tenant, 1.0)), 1e-6)

    def set_weight(self, tenant, weight: float) -> None:
        self._weights[tenant] = float(weight)

    def submit(self, loop: asyncio.AbstractEventLoop, tenant,
               cost: int, fn) -> asyncio.Future:
        """Enqueue ``fn`` for ``tenant`` at ``cost`` work units; returns
        a future resolved with ``fn()``'s result — the drop-in shape of
        ``loop.run_in_executor`` the collator chains ``_deliver`` onto."""
        fut = loop.create_future()
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = collections.deque()
            self._deficit.setdefault(tenant, 0.0)
            self._rr.append(tenant)
        q.append((max(1, int(cost)), fn, fut))
        self._pump(loop)
        return fut

    def _pump(self, loop) -> None:
        if self._busy:
            return
        # DRR scan: deficits strictly grow on every visit to a
        # non-empty queue, so this terminates at the first affordable
        # head job (or when every queue has drained)
        while self._rr:
            tenant = self._rr[0]
            q = self._queues.get(tenant)
            while q and q[0][2].done():
                q.popleft()  # caller gave up while queued: never run it
            if not q:
                # an emptied queue leaves the rotation and forfeits its
                # leftover deficit — idle tenants bank no burst credit
                self._rr.popleft()
                self._queues.pop(tenant, None)
                self._deficit[tenant] = 0.0
                continue
            self._deficit[tenant] += self.weight(tenant) * self._quantum
            cost, fn, fut = q[0]
            if self._deficit[tenant] < cost:
                self._rr.rotate(-1)
                continue
            q.popleft()
            self._deficit[tenant] -= cost
            self._rr.rotate(-1)
            self._busy = True
            telem.inc("serve/fair_dispatches")
            if tenant:
                telem.inc(tenant_metric("serve/fair_dispatches", tenant))
            efut = loop.run_in_executor(self._exec, fn)
            efut.add_done_callback(
                functools.partial(self._done, loop, fut))
            return

    def _done(self, loop, fut: asyncio.Future, efut) -> None:
        self._busy = False
        if not fut.done():
            if efut.cancelled():
                fut.cancel()
            elif efut.exception() is not None:
                fut.set_exception(efut.exception())
            else:
                fut.set_result(efut.result())
        self._pump(loop)

    def pending(self) -> dict:
        """{tenant: queued jobs} — introspection for stats/tests."""
        return {t: len(q) for t, q in self._queues.items() if q}


class Collator:
    """Continuous batching over a :class:`RequestBatcher` (module
    docstring).  One collator serves one batcher serves one engine;
    construct and use it on one event loop.

    ``executor=`` shares a dispatch executor owned by someone else (the
    multi-tenant registry: one worker serializing EVERY tenant's device
    work) — ``close()`` then leaves it running.  ``dispatcher=`` routes
    this collator's dispatch submissions through a
    :class:`FairDispatcher` under its ``tenant`` identity instead of
    straight FIFO ``run_in_executor``."""

    def __init__(self, batcher: RequestBatcher, *,
                 max_wait_us: float = DEFAULT_MAX_WAIT_US,
                 executor: Optional[ThreadPoolExecutor] = None,
                 dispatcher: Optional[FairDispatcher] = None,
                 tenant: Optional[str] = None):
        if max_wait_us < 0:
            raise ValueError(
                f"max_wait_us must be >= 0; got {max_wait_us}")
        self.batcher = batcher
        self.tenant = tenant if tenant is not None else batcher.tenant
        self.max_wait_s = float(max_wait_us) / 1e6
        self._groups: dict[tuple, _Group] = {}
        # the single dispatch executor: device work serialized, flushes
        # from independent groups queue here while their member
        # coroutines stay concurrent.  Shared (registry-owned) when
        # passed in; otherwise this collator owns one.
        self._owns_exec = executor is None
        self._exec = executor if executor is not None else (
            ThreadPoolExecutor(max_workers=1,
                               thread_name_prefix="serve-dispatch"))
        self._dispatcher = dispatcher
        self._closed = False
        # monotone flush id, stamped on every member lifecycle a flush
        # examines (expired ones included — a 504 must name the flush
        # that missed its deadline); rides the access log and stats
        self._flush_seq = 0

    def _submit(self, cost: int, fn) -> asyncio.Future:
        """One dispatch submission: through the fair dispatcher under
        this collator's tenant when armed, else straight to the
        executor — the single seam the weighted-fair policy hangs on."""
        loop = asyncio.get_running_loop()
        if self._dispatcher is not None:
            return self._dispatcher.submit(loop, self.tenant, cost, fn)
        return loop.run_in_executor(self._exec, fn)

    # --- public ops -----------------------------------------------------------

    async def topk(self, ids, k: int, *, exclude_self: bool = True,
                   deadline_ms: Optional[float] = None,
                   t_enq: Optional[float] = None,
                   request_id: Optional[str] = None
                   ) -> tuple[np.ndarray, np.ndarray]:
        """The batcher's ``topk`` contract, collated: same validation,
        cache, admission, deadline, telemetry, and access-log semantics
        — but cold ids ride a shared flush with whatever else is
        pending (``request_id`` joins the response to its flush via
        the lifecycle's ``flush_id``)."""
        b = self.batcher
        if deadline_ms is None:
            deadline_ms = b.default_deadline_ms
        if request_id is None and b.access_sink is not None:
            from hyperspace_tpu.serve.access import new_request_id

            request_id = new_request_id()
        life = b.new_lifecycle("topk", deadline_ms, t_enq=t_enq,
                               request_id=request_id)
        b.count_request()
        try:
            b._admit()
        except OverloadedError:
            b.emit_access(life, "overloaded")
            raise
        try:
            ids, k = b.validate_topk_request(ids, k)
            keyf, nprobe_ov, cache_only = b.plan_topk(k, exclude_self)
            rows, misses = b.cache_pass(ids, keyf, cache_only)
            life.cache_hits = len(rows)
            life.cache_misses = len(misses)
            life.check_deadline("after the cache pass")
            if misses:
                # collator hand-off stamp: host work done, about to
                # wait for the flush group — the collate_wait stage
                life.collated()
                computed = await self._enqueue(misses, k, exclude_self,
                                               nprobe_ov, keyf, life)
                life.result_ready()
                for qid in misses:
                    rows[qid] = computed[qid]
            else:
                # all-hit: the request never queues; batch-form is now
                life.formed()
                life.result_ready()
                b._update_gauges()
            out_i = np.stack([rows[qid][0] for qid in ids])
            out_d = np.stack([rows[qid][1] for qid in ids])
            # a result computed past the deadline is answered
            # deadline_exceeded, never returned as if on time (the
            # rows stay cached — the work is not wasted)
            life.check_deadline("at completion")
            life.finish()
            b.emit_access(life)
            return out_i, out_d
        except (ServeError, ValueError, KeyError, TypeError,
                OverflowError, OSError) as e:
            # the shared exception->taxonomy classification: access-log
            # outcomes track wire kinds by construction
            b.emit_access(life, kind_of(e))
            raise
        finally:
            b._release()

    async def score(self, u_ids, v_ids, *, prob: bool = False,
                    fd_r: float = 2.0, fd_t: float = 1.0,
                    deadline_ms: Optional[float] = None,
                    t_enq: Optional[float] = None,
                    request_id: Optional[str] = None) -> np.ndarray:
        """The batcher's ``score`` contract through the dispatch
        executor.  Edge scoring is uncached and pairs rarely repeat, so
        scores are not collated across requests — but they ARE admitted
        on arrival (the bounded queue sees them immediately, not when
        the executor gets around to them) and serialized through the
        same single executor as the topk flushes."""
        b = self.batcher
        if deadline_ms is None:
            deadline_ms = b.default_deadline_ms
        if request_id is None and b.access_sink is not None:
            from hyperspace_tpu.serve.access import new_request_id

            request_id = new_request_id()
        life = b.new_lifecycle("score", deadline_ms, t_enq=t_enq,
                               request_id=request_id)
        b.count_request()
        try:
            b._admit()
        except OverloadedError:
            b.emit_access(life, "overloaded")
            raise
        try:
            if b._mode() == _CACHE_ONLY:
                raise OverloadedError(
                    "cache-only degradation: edge scoring is uncached")
            u, v = b.validate_score_request(u_ids, v_ids)
            life.formed()
            life.check_deadline("after validation")
            out = await self._submit(
                len(u),
                functools.partial(b.dispatch_score, u, v, prob=prob,
                                  fd_r=fd_r, fd_t=fd_t, lives=(life,),
                                  deadline_life=life,
                                  span_parent=life.span))
            life.result_ready()
            life.check_deadline("at completion")
            life.finish()
            b.emit_access(life)
            return out
        except (ServeError, ValueError, KeyError, TypeError,
                OverflowError, OSError) as e:
            # the shared exception->taxonomy classification: access-log
            # outcomes track wire kinds by construction
            b.emit_access(life, kind_of(e))
            raise
        finally:
            b._release()

    # --- mutations (live engines — serve/delta.py) ----------------------------

    async def upsert(self, ids, rows, *,
                     deadline_ms: Optional[float] = None,
                     t_enq: Optional[float] = None,
                     request_id: Optional[str] = None) -> dict:
        """The batcher's ``upsert`` through the dispatch executor:
        mutations are serialized with the topk/score device work (one
        worker), so a flush never scans a half-applied generation —
        the delta swap it observes is whole, before or after."""
        if self._closed:
            raise OverloadedError("server draining: dispatch closed")
        return await self._submit(
            len(ids),
            functools.partial(self.batcher.upsert, ids, rows,
                              deadline_ms=deadline_ms, t_enq=t_enq,
                              request_id=request_id))

    async def delete(self, ids, *,
                     deadline_ms: Optional[float] = None,
                     t_enq: Optional[float] = None,
                     request_id: Optional[str] = None) -> dict:
        """The batcher's ``delete``, same executor serialization."""
        if self._closed:
            raise OverloadedError("server draining: dispatch closed")
        return await self._submit(
            len(ids),
            functools.partial(self.batcher.delete, ids,
                              deadline_ms=deadline_ms, t_enq=t_enq,
                              request_id=request_id))

    # --- pending-bucket machinery ---------------------------------------------

    def _enqueue(self, misses: list, k: int, exclude_self: bool,
                 nprobe_ov, keyf, life: _Lifecycle) -> asyncio.Future:
        loop = asyncio.get_running_loop()
        key = (k, exclude_self, nprobe_ov)
        g = self._groups.get(key)
        if g is None:
            g = _Group(keyf)
            self._groups[key] = g
            # the max-wait clock starts when the group becomes
            # non-empty — a lone request flushes within T
            g.timer = loop.call_later(self.max_wait_s, self._flush, key)
        m = _Member(loop.create_future(), misses, life)
        g.members.append(m)
        g.pending.update(misses)
        n = len(g.pending)
        # flush policy: an exactly-full power-of-two bucket never waits
        # (zero padding; more waiting only adds padding to a bigger
        # bucket), and past the top bucket there is nothing to wait for
        # (slab split).  A count that skips over a rung (7 → 9) keeps
        # waiting for the next rung or the deadline, whichever first.
        if n >= self.batcher.buckets[-1] or n == bucket_for(
                n, self.batcher.buckets):
            self._flush(key)
        return m.fut

    def _flush(self, key: tuple) -> None:
        """Form and dispatch one group's batch (timer or fill path)."""
        g = self._groups.pop(key, None)
        if g is None:
            return  # already flushed by the other trigger
        g.timer.cancel()
        self._flush_seq += 1
        flush_id = self._flush_seq
        alive: list[_Member] = []
        ids: list[int] = []
        seen: set = set()
        for m in g.members:
            # stamped BEFORE the deadline check: an expired member's
            # 504 access record names the flush that missed it
            m.life.flush_id = flush_id
            try:
                # expired while queued: answered deadline_exceeded,
                # never dispatched — and never fails the rest
                m.life.check_deadline("while queued in the collator")
            except DeadlineExceededError as e:
                if not m.fut.done():
                    m.fut.set_exception(e)
                continue
            m.life.formed()  # batch-form stamp: the batch exists now
            alive.append(m)
            for qid in m.misses:
                if qid not in seen:
                    seen.add(qid)
                    ids.append(qid)
        if not alive:
            return
        if self._closed:
            # a straggler flush after close (an abandoned connection's
            # timer firing mid-teardown) must resolve its members, not
            # die on the shut-down executor leaving futures hanging
            err = OverloadedError("server draining: dispatch closed")
            for m in alive:
                if not m.fut.done():
                    m.fut.set_exception(err)
            return
        telem.inc("serve/collator_flushes")
        k, exclude_self, nprobe_ov = key
        lives = [m.life for m in alive]
        # one shared flush span adopted into EVERY member's tree (the
        # batching boundary: N requests → 1 flush → the same subtree in
        # N trees); the dispatch thread scopes it via span_parent, so
        # the engine's device_compute/rescore stages land under it
        fspan = None
        if spans.enabled():
            fspan = spans.Span("flush", meta={
                "flush_id": flush_id, "members": len(alive),
                "ids": len(ids)})
            for m in alive:
                if m.life.span is not None:
                    m.life.span.adopt(fspan)
        fut = self._submit(
            len(ids),
            functools.partial(self.batcher.dispatch_topk, ids, k,
                              exclude_self=exclude_self,
                              nprobe_ov=nprobe_ov, keyf=g.keyf,
                              lives=lives, span_parent=fspan))
        fut.add_done_callback(
            functools.partial(self._deliver, alive, fspan))

    @staticmethod
    def _deliver(members: list, fspan, fut) -> None:
        if fspan is not None:
            fspan.close()
        exc = None if fut.cancelled() else fut.exception()
        for m in members:
            if m.fut.done():
                continue
            if fut.cancelled():
                m.fut.cancel()
            elif exc is not None:
                m.fut.set_exception(exc)
            else:
                m.fut.set_result(fut.result())

    # --- drain ----------------------------------------------------------------

    def flush_all(self) -> None:
        """Flush every pending group now (drain: queued work must not
        wait out its max-wait timer while the server is closing)."""
        for key in list(self._groups):
            self._flush(key)

    def close(self, wait: bool = True) -> None:
        """Release the dispatch executor; idempotent.  Sync callers
        (tests, the bench) keep the default ``wait=True``; the front
        door's drain passes ``wait=False`` — joining a running dispatch
        thread from inside the event loop would block every remaining
        in-flight response for its duration.  A SHARED executor
        (``executor=`` at construction) is the owner's to shut down —
        closing one tenant's collator must not kill every tenant's
        dispatch."""
        if not self._closed:
            self._closed = True
            if self._owns_exec:
                self._exec.shutdown(wait=wait)
