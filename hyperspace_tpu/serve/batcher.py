"""Request micro-batcher: bucket padding + per-query LRU result cache.

The engine compiles one XLA program per (batch size, k); letting raw
request sizes reach it would compile per request — the classic serving
failure where p99 latency is the compiler.  The batcher stands between
requests and the engine:

- **Bucketing.**  Query batches are padded (by repeating the last id —
  always a valid row) up to the smallest power-of-two bucket that fits,
  from ``min_bucket`` to ``max_bucket``; bigger requests are split into
  ``max_bucket`` slabs.  The engine therefore ever sees only
  ``log2(max/min)+1`` distinct batch shapes: compiles happen once per
  (bucket, k) at warmup and never again (``jax/recompiles`` is the
  regression alarm).  Padded slots are real-but-discarded work, counted
  in ``serve/padded_waste`` (with ``serve/slots`` the total dispatched)
  and summarized as the ``serve/padded_waste_ratio`` gauge, so an overly
  sparse bucket ladder shows up in telemetry rather than in a latency
  mystery.  Cache effectiveness is likewise a gauge
  (``serve/cache_hit_rate``) the bench's ``serve_qps`` leg reads.
- **Result cache.**  An LRU keyed ``(artifact fingerprint, query id,
  k)`` holding per-query top-k rows.  The fingerprint key means a
  reloaded (different) artifact can never serve another table's cached
  neighbors; per-ID granularity means a request mixing hot and cold ids
  only computes the cold ones.  ``serve/cache_hit`` / ``serve/cache_miss``
  count per id; edge scoring is uncached (pairs rarely repeat; the
  distance gather is already one cheap dispatch).

Every public entry wraps itself in a ``query`` trace span (carrying an
``args`` payload — op, request/batch sizes, buckets, cache hits — so
Perfetto correlates spans with load) and bumps ``serve/requests`` —
with telemetry enabled (docs/observability.md) a serving process's
JSONL/trace shows the same spans and counters a training run's does.

**Per-request lifecycle** (docs/observability.md "Histograms"): each
request is stamped with monotonic timestamps at enqueue (entry),
batch-form (validation + cache pass done, slabs about to dispatch),
dispatch, and complete, and observes three latency histograms —
``serve/queue_wait_ms`` (enqueue→batch-form: host-side time before any
device work; the name anticipates the async front door, where this
becomes real queueing), ``serve/dispatch_ms`` (engine dispatch + result
fetch, summed over the request's slabs; only observed when at least one
slab actually dispatched), and ``serve/e2e_ms`` (enqueue→complete).
These are what ``bench_serve`` reports p50/p95/p99 per bucket from, and
what the serve CLI's latency summary line reads.

**Overload safety** (docs/resilience.md): with ``queue_max=N`` the
batcher fronts a bounded admission counter — a request arriving while
``N`` are already in flight is SHED with a typed ``overloaded`` error
(``serve/shed``), never queued unboundedly.  Admission occupancy feeds
a hysteresis :class:`~hyperspace_tpu.resilience.degrade.
HysteresisLadder`: under sustained pressure the IVF probe width steps
down toward its floor of 1 (each step counted in ``serve/degraded``,
the level in the ``serve/degrade_level`` gauge), then the batcher
answers **cache-only** (cold ids shed with ``overloaded``); sustained
calm steps back up (``serve/degrade_recovered``).  Per-request
``deadline_ms`` is enforced at three points — after the cache pass,
before each slab dispatch (an expired request is never dispatched
late), and at completion (a result computed past the deadline is
answered ``deadline_exceeded``, not returned as if on time) — counted
in ``serve/deadline_exceeded``.  All of it is **off by default**:
``queue_max=0`` constructs none of the machinery and the hot path
gains two attribute checks.  Failed requests (shed/expired) observe no
latency histograms — ``serve/e2e_ms`` stays the distribution of
honestly answered requests.

Thread-safety: the LRU is lock-guarded; engine dispatches are jax-level
thread-safe; the admission counter and ladder carry their own locks
(concurrent callers — threads today, the async front door next — are
the population admission control exists for; the blocking CLI loop
never sheds).  One batcher serves one engine (one artifact).

**Pipeline stages** (the continuous-batching refactor): ``topk`` is a
composition of four callable stages — :meth:`RequestBatcher.
validate_topk_request` (host-side id/k validation), :meth:`~Request
Batcher.plan_topk` (ladder mode → effective nprobe + the cache key
function), :meth:`~RequestBatcher.cache_pass` (per-unique-id LRU
lookup + hit/miss counters + the cache-only shed), and :meth:`~Request
Batcher.dispatch_topk` (bucket-pad, chaos site, engine call, cache
put) — so the asyncio collator (``serve/collator.py``) can run the
same validation/cache/dispatch code with its OWN queueing between the
cache pass and the dispatch, instead of forking the pipeline.
``dispatch_topk`` takes a ``lives`` sequence: a collated flush
attributes the one shared device dispatch to every participating
request's lifecycle while counting the engine slots exactly once.
``t_enq=`` on the public entries backdates the lifecycle's enqueue
stamp (and therefore the deadline origin) to socket-accept time — in
the HTTP front door, queue time counts against the budget.
"""

from __future__ import annotations

import collections
import operator
import threading
import time
from typing import Optional, Sequence

import numpy as np

from hyperspace_tpu.resilience import faults
from hyperspace_tpu.serve.access import new_request_id
from hyperspace_tpu.serve.engine import QueryEngine
from hyperspace_tpu.serve.errors import (DeadlineExceededError,
                                         OverloadedError, ServeError,
                                         kind_of)
from hyperspace_tpu.telemetry import registry as telem
from hyperspace_tpu.telemetry import spans
from hyperspace_tpu.telemetry.exposition import tenant_metric
from hyperspace_tpu.telemetry.trace import span, tracing

DEFAULT_MIN_BUCKET = 8
DEFAULT_MAX_BUCKET = 1024
DEFAULT_CACHE_SIZE = 65536
_CACHE_ONLY = "cache_only"  # the ladder's terminal level


def bucket_sizes(min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_bucket: int = DEFAULT_MAX_BUCKET) -> tuple:
    """The power-of-two bucket ladder, smallest to largest."""
    if min_bucket < 1 or max_bucket < min_bucket:
        raise ValueError(f"bad bucket range [{min_bucket}, {max_bucket}]")
    out, b = [], 1
    while b < min_bucket:
        b *= 2
    while b < max_bucket:
        out.append(b)
        b *= 2
    out.append(max_bucket)
    return tuple(out)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (callers split requests bigger than the top
    bucket into top-bucket slabs first)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _checked_ids(ids, name: str, num_nodes: int) -> list[int]:
    """Validate a request's id list on host BEFORE any dtype cast.

    Every id must be integral — a float like 1.9 must fail, never
    silently truncate to another node's answer — and in
    [0, num_nodes), so a huge int can never wrap through the int32
    device cast into a valid-looking id.  Raises ValueError (the serve
    loop's per-line error path)."""
    if isinstance(ids, np.ndarray):
        ids = ids.reshape(-1).tolist()
    elif np.isscalar(ids):
        raise ValueError(f"{name} must be a list of ids")
    if not len(ids):
        raise ValueError(f"{name} must be a non-empty id list")
    out = []
    for i in ids:
        if isinstance(i, bool):  # bools index-coerce to 0/1 — reject
            raise ValueError(f"{name} must be integer ids; got bool")
        try:
            i = operator.index(i)
        except TypeError:
            raise ValueError(
                f"{name} must be integer ids; got "
                f"{type(i).__name__}") from None
        if not 0 <= i < num_nodes:
            raise ValueError(f"{name} id {i} out of range [0, {num_nodes})")
        out.append(i)
    return out


class _LRU:
    """Tiny lock-guarded LRU: (fingerprint, qid, k) -> (idx row, dist row)."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._d: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            try:
                self._d.move_to_end(key)
                return self._d[key]
            except KeyError:
                return None

    def put(self, key, value) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


class _Lifecycle:
    """One request's lifecycle stamps + the three ``serve/*`` histograms.

    Shared by ``topk`` and ``score`` so the stamping contract (module
    docstring, "Per-request lifecycle") lives in exactly one place:
    construct at enqueue, ``formed()`` once validation + cache pass are
    done, attribute each slab's device work via ``slab()`` +
    ``add_dispatch()`` (the result fetch belongs INSIDE the timed
    window — dispatch is async enqueue, the fetch is the completion
    wait), and ``finish()`` to observe.  ``serve/dispatch_ms`` is only
    observed when a slab actually dispatched, so all-cache-hit requests
    don't pull it toward zero.  ``info`` is the span's ``args`` dict
    (None when tracing is off — the disabled hot path stays
    allocation-free); it is read at span exit, so fields landing after
    ``span()`` entry still make the trace.

    ``t_enq=`` backdates the enqueue stamp (the HTTP front door stamps
    at socket accept, so collator queue time counts against both the
    latency histograms and the deadline); the ``serve/slots`` /
    ``serve/padded_waste`` counters moved to the dispatch helper — a
    collated flush shared by several lifecycles must count its engine
    slots exactly once.
    """

    __slots__ = ("t_enq", "t_form", "info", "buckets_used",
                 "dispatch_s", "t_deadline", "op", "request_id",
                 "flush_id", "cache_hits", "cache_misses", "t_done",
                 "t_coll", "t_result", "span", "tenant")

    def __init__(self, op: str, deadline_ms: Optional[float] = None,
                 t_enq: Optional[float] = None,
                 request_id: Optional[str] = None,
                 tenant: Optional[str] = None):
        self.t_enq = time.perf_counter() if t_enq is None else t_enq
        self.t_form = self.t_enq
        self.op = op
        # tenant this request belongs to (multi-tenant registry —
        # serve/registry.py); None on a single-tenant batcher.  Drives
        # the tenant-labeled metric twins and the access-log field.
        self.tenant = tenant
        # request-tracing fields (docs/observability.md "Live metrics,
        # access log, and the flight recorder"): the id joins the
        # response, the access-log line, the span args, and the
        # collator flush that served the request
        self.request_id = request_id
        self.flush_id: Optional[int] = None
        self.cache_hits = 0
        self.cache_misses = 0
        self.t_done: Optional[float] = None
        self.info: Optional[dict] = {"op": op} if tracing() else None
        if self.info is not None and request_id is not None:
            self.info["request_id"] = request_id
        self.buckets_used: list = []
        self.dispatch_s = 0.0
        # stage boundary stamps (docs/observability.md "Span-level
        # tracing"): t_coll marks collator hand-off (None on the sync
        # path — collate_wait collapses to zero), t_result marks
        # results materialized (serialize = the remainder).  Stages are
        # DIFFERENCES of consecutive stamps, so they sum to e2e exactly
        # by construction.
        self.t_coll: Optional[float] = None
        self.t_result: Optional[float] = None
        # the request's span tree root (None when spans are disabled —
        # the zero-cost default); the serve front door's request
        # envelope, if any, adopts it
        self.span = spans.root(op, request_id)
        if self.span is not None:
            self.span.t0 = self.t_enq  # align the tree to enqueue time
        # absolute expiry on the same monotonic clock as the stamps;
        # None = no deadline (the zero-cost default)
        self.t_deadline = (self.t_enq + deadline_ms / 1e3
                           if deadline_ms else None)

    def formed(self) -> None:
        self.t_form = time.perf_counter()

    def collated(self) -> None:
        """Stamp collator hand-off: host-side work (validation + cache
        pass) done, the request is about to wait for its flush group —
        everything between this and ``formed()`` is collate wait."""
        self.t_coll = time.perf_counter()

    def result_ready(self) -> None:
        """Stamp results materialized: device work (or the collated
        flush) delivered; the remainder to completion is serialize."""
        self.t_result = time.perf_counter()

    def check_deadline(self, where: str) -> None:
        """Raise ``deadline_exceeded`` when the request's budget is
        spent — called after the cache pass, before each slab dispatch
        (never dispatch late), and at completion (never answer a
        result as if it were on time)."""
        if (self.t_deadline is not None
                and time.perf_counter() > self.t_deadline):
            telem.inc("serve/deadline_exceeded")
            if self.tenant:
                telem.inc(tenant_metric("serve/deadline_exceeded",
                                        self.tenant))
            raise DeadlineExceededError(
                f"deadline_ms expired {where} "
                f"({(time.perf_counter() - self.t_enq) * 1e3:.1f} ms "
                "elapsed)")

    def slab(self, bucket: int) -> None:
        self.buckets_used.append(bucket)

    def add_dispatch(self, seconds: float) -> None:
        self.dispatch_s += seconds

    def finish(self) -> None:
        if self.info is not None:
            self.info["buckets"] = self.buckets_used
        self.t_done = time.perf_counter()
        telem.observe("serve/queue_wait_ms", (self.t_form - self.t_enq) * 1e3)
        if self.buckets_used:
            telem.observe("serve/dispatch_ms", self.dispatch_s * 1e3)
        telem.observe("serve/e2e_ms", (self.t_done - self.t_enq) * 1e3)
        if self.tenant:
            # the tenant-labeled twin (exposition renders it as a
            # ``tenant=`` label on the same family): per-tenant SLO
            # windows and the multitenant bench read per-tenant p99
            # from this series while the base keeps the aggregate
            telem.observe(tenant_metric("serve/e2e_ms", self.tenant),
                          (self.t_done - self.t_enq) * 1e3)
        if self.span is not None:
            st = self.stages_ms()
            telem.observe("serve/stage/queue_wait_ms", st["queue_wait"])
            telem.observe("serve/stage/collate_wait_ms", st["collate_wait"])
            telem.observe("serve/stage/dispatch_ms", st["dispatch"])
            telem.observe("serve/stage/serialize_ms", st["serialize"])
            t_coll = self.t_coll if self.t_coll is not None else self.t_form
            t_res = (self.t_result if self.t_result is not None
                     else self.t_done)
            self.span.add("queue_wait", self.t_enq, t_coll)
            self.span.add("collate_wait", t_coll, self.t_form)
            self.span.add("dispatch", self.t_form, t_res)
            self.span.add("serialize", t_res, self.t_done)
            self.span.t1 = self.t_done  # exact close, not close()'s now

    def stages_ms(self) -> dict:
        """The per-stage latency decomposition, in ms: consecutive-
        boundary differences that sum to ``e2e_ms`` exactly.  Computed
        from the stamps with defaults (a sync request has no collate
        wait; a failed request's serialize runs to its error time), so
        the access log carries it for every outcome."""
        end = self.t_done if self.t_done is not None else time.perf_counter()
        t_coll = self.t_coll if self.t_coll is not None else self.t_form
        t_res = self.t_result if self.t_result is not None else end
        return {
            "queue_wait": round((t_coll - self.t_enq) * 1e3, 3),
            "collate_wait": round((self.t_form - t_coll) * 1e3, 3),
            "dispatch": round((t_res - self.t_form) * 1e3, 3),
            "serialize": round((end - t_res) * 1e3, 3),
        }

    def access_record(self, outcome: str, degrade_level: int) -> dict:
        """One structured access-log line's payload (serve/access.py):
        the request id joined to its route, buckets, flush id, latency
        decomposition, cache outcome, degrade level, and taxonomy
        outcome.  Failed requests (no ``finish()``) still carry their
        elapsed time — a 504 must be attributable to the flush that
        missed its deadline."""
        end = self.t_done if self.t_done is not None else time.perf_counter()
        return {
            "request_id": self.request_id,
            "route": self.op,
            "tenant": self.tenant,
            "outcome": outcome,
            "bucket": list(self.buckets_used),
            "flush_id": self.flush_id,
            "queue_wait_ms": round((self.t_form - self.t_enq) * 1e3, 3),
            "dispatch_ms": round(self.dispatch_s * 1e3, 3),
            "e2e_ms": round((end - self.t_enq) * 1e3, 3),
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "degrade_level": degrade_level,
            # the per-stage decomposition (sums to e2e_ms exactly) —
            # what scripts/trace_report.py aggregates
            "stages": self.stages_ms(),
        }


class _Admission:
    """Bounded in-flight counter: the admission queue's whole state.

    ``try_admit`` returns the post-admit pressure in [0, 1) — the share
    of the bound OTHER callers hold, ``(inflight − 1) / queue_max`` —
    or None when full (the caller sheds, observing pressure 1.0).  A
    lone caller therefore exerts ZERO pressure: the blocking CLI loop
    (one request in flight, ever) can never walk the ladder down,
    whatever ``queue_max`` is — only genuine concurrency can."""

    def __init__(self, queue_max: int):
        self.queue_max = int(queue_max)
        self.inflight = 0
        self._lock = threading.Lock()

    def try_admit(self) -> Optional[float]:
        with self._lock:
            if self.inflight >= self.queue_max:
                return None
            self.inflight += 1
            return (self.inflight - 1) / self.queue_max

    def release(self) -> None:
        with self._lock:
            self.inflight -= 1


def _ladder_modes(engine: QueryEngine) -> list:
    """Quality modes best-first: full (None), IVF probe widths halving
    toward the floor of 1, then cache-only (docs/resilience.md
    "Degradation ladder")."""
    modes: list = [None]
    if engine.scan_strategy == "ivf":
        p = engine.nprobe // 2
        while p >= 1:
            modes.append(p)
            p //= 2
    modes.append(_CACHE_ONLY)
    return modes


class RequestBatcher:
    """Pads requests onto the bucket ladder and fronts the LRU cache.

    ``queue_max=N`` turns on overload safety (module docstring): the
    bounded admission counter, the degradation ladder (its hysteresis
    knobs ``ladder_high``/``ladder_low``/``ladder_down_after``/
    ``ladder_up_after`` — resilience/degrade.py), and per-request
    deadlines (``deadline_ms=`` here is the default applied when a
    request carries none; requests may override per call).  The
    default ``queue_max=0`` constructs none of it."""

    def __init__(self, engine: QueryEngine, *,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_bucket: int = DEFAULT_MAX_BUCKET,
                 cache_size: int = DEFAULT_CACHE_SIZE,
                 queue_max: int = 0,
                 deadline_ms: float = 0.0,
                 ladder_high: float = 0.75, ladder_low: float = 0.25,
                 ladder_down_after: int = 1, ladder_up_after: int = 8,
                 window=None, slo_ms: float = 0.0,
                 access_sink=None, recorder=None, slow_sink=None,
                 tenant: Optional[str] = None):
        self.engine = engine
        # multi-tenant identity (serve/registry.py): when set, the key
        # serve series (requests/e2e/shed/deadline/errors) double-write
        # a ``<name>@tenant=<t>`` twin the exposition renders as a
        # tenant label, and access records carry the tenant field.
        # None (the single-tenant default) adds nothing to the hot path.
        self.tenant = tenant
        self.buckets = bucket_sizes(min_bucket, max_bucket)
        self.cache = _LRU(cache_size)
        if queue_max < 0:
            raise ValueError(f"queue_max must be >= 0; got {queue_max}")
        if deadline_ms < 0:
            raise ValueError(
                f"deadline_ms must be >= 0; got {deadline_ms}")
        if slo_ms < 0:
            raise ValueError(f"slo_ms must be >= 0; got {slo_ms}")
        self.default_deadline_ms = float(deadline_ms) or None
        # --- observability plane (docs/observability.md "Live metrics,
        # access log, and the flight recorder"), all None/0 = off at
        # zero cost: `window` is a telemetry.window.SloWindow (ticked
        # per completed request; surfaces in stats()), `slo_ms` arms
        # the ladder's latency-aware pressure signal, `access_sink` is
        # a callable taking one access record (serve.access.AccessLog.
        # emit), `recorder` a FlightRecorder fed degrade transitions,
        # `slow_sink` the slow-query log — a second record sink fed
        # only by requests breaching slo_ms, each carrying its span
        # tree when spans are enabled
        self.window = window
        self.slo_ms = float(slo_ms)
        self.access_sink = access_sink
        self.recorder = recorder
        self.slow_sink = slow_sink
        self._admission = None
        self._ladder = None
        self._modes: list = [None]
        if queue_max > 0:
            from hyperspace_tpu.resilience.degrade import HysteresisLadder

            self._admission = _Admission(queue_max)
            self._modes = _ladder_modes(engine)
            self._ladder = HysteresisLadder(
                len(self._modes), high=ladder_high, low=ladder_low,
                down_after=ladder_down_after, up_after=ladder_up_after,
                on_change=self._on_ladder_change)

    def _on_ladder_change(self, old: int, new: int) -> None:
        if new > old:
            telem.inc("serve/degraded")
        else:
            telem.inc("serve/degrade_recovered")
        telem.set_gauge("serve/degrade_level", new)
        if self.recorder is not None:
            # a degrade transition is an incident trigger: the flight
            # recorder dumps the ring so the storm that caused it (or
            # the interval a recovery closes) leaves evidence
            self.recorder.note_degrade(old, new)

    def _admit(self) -> None:
        """Admission gate: shed with ``overloaded`` when the bounded
        queue is full; feed the ladder the post-admit occupancy — or,
        with ``slo_ms`` + a window armed, the latency pressure when it
        is the worse signal (a server slow without queueing must still
        walk the ladder down)."""
        if self._admission is None:
            return
        occ = self._admission.try_admit()
        if occ is None:
            # serve/shed ticks in emit_access (every overloaded answer
            # is a shed — admission, cache-only, drain alike; counting
            # here too would double-count this path)
            self._ladder.observe(1.0)
            raise OverloadedError(
                "admission queue full "
                f"(queue_max={self._admission.queue_max})")
        if self.window is not None and self.slo_ms > 0:
            occ = max(occ, self.window.latency_pressure(self.slo_ms))
        self._ladder.observe(occ)

    def _release(self) -> None:
        if self._admission is not None:
            self._admission.release()

    def count_request(self) -> None:
        """Bump ``serve/requests`` (+ the tenant twin) — the ONE place
        a request is counted, shared with the collator's async paths so
        a multi-tenant batcher's per-tenant rate can never drift from
        the aggregate."""
        telem.inc("serve/requests")
        if self.tenant:
            telem.inc(tenant_metric("serve/requests", self.tenant))

    def new_lifecycle(self, op: str, deadline_ms: Optional[float] = None,
                      t_enq: Optional[float] = None,
                      request_id: Optional[str] = None) -> "_Lifecycle":
        """A lifecycle stamped with this batcher's tenant (the collator
        constructs lifecycles for its async members through this, so
        tenant threading has one home)."""
        return _Lifecycle(op, deadline_ms, t_enq=t_enq,
                          request_id=request_id, tenant=self.tenant)

    def emit_access(self, life: _Lifecycle, outcome: str = "ok") -> None:
        """One request is DONE (any outcome): tick the SLO window,
        count taxonomy errors (parse/validation/internal — shed and
        deadline keep their own counters, so the window's three rates
        never double-count), and emit the access record when a sink is
        armed.  Shared by the sync paths here and the collator — the
        record-assembly contract lives once."""
        if self.window is not None:
            self.window.tick()
        if outcome == "overloaded":
            # EVERY overloaded answer is a shed — the admission queue,
            # cache-only degradation misses, drain refusals, degraded
            # under-filled probes.  Counting only the admission site
            # left the window's shed_rate reading 0.0 during exactly
            # the cache-only state degradation exists to expose; every
            # overloaded outcome funnels through here exactly once.
            telem.inc("serve/shed")
            if self.tenant:
                telem.inc(tenant_metric("serve/shed", self.tenant))
        elif outcome not in ("ok", "deadline_exceeded"):
            telem.inc("serve/errors")
            if self.tenant:
                telem.inc(tenant_metric("serve/errors", self.tenant))
        if life.span is not None:
            life.span.close()  # failed requests: stamp end at emit time
        breach = False
        if self.slo_ms > 0:
            end = (life.t_done if life.t_done is not None
                   else time.perf_counter())
            breach = (end - life.t_enq) * 1e3 > self.slo_ms
            if breach:
                telem.inc("serve/slow_queries")
        if self.access_sink is None and self.slow_sink is None:
            return
        level = self._ladder.level if self._ladder is not None else 0
        rec = life.access_record(outcome, level)
        if life.span is not None and (outcome != "ok" or breach):
            # incident/slow evidence: the full span tree rides the
            # record — the flight recorder's trigger and the slow-query
            # log read it; healthy fast requests stay one flat line
            rec["span"] = life.span.to_dict()
        if self.access_sink is not None:
            try:
                self.access_sink(rec)
            except OSError:
                pass  # a full disk is evidence loss, never a request failure
        if breach and self.slow_sink is not None:
            try:
                self.slow_sink(rec)
            except OSError:
                pass  # same policy as the access sink

    def emit_synthetic_access(self, op: str, *,
                              request_id: Optional[str] = None,
                              outcome: str = "ok",
                              t_enq: Optional[float] = None) -> None:
        """Access-account a request that never got a real lifecycle —
        the serving surfaces' entry point for failures upstream of the
        batcher (HTTP framing/parse/route errors, stdin pre-dispatch
        failures).  With a sink armed and no id, one is generated (a
        record is never anonymous).  Keeping this here — rather than
        having both surfaces construct bare ``_Lifecycle`` objects —
        pins the synthetic-record contract to the class that owns the
        real one."""
        if request_id is None and self.access_sink is not None:
            request_id = new_request_id()
        self.emit_access(self.new_lifecycle(op, t_enq=t_enq,
                                            request_id=request_id),
                         outcome)

    def _mode(self):
        """Current quality mode: ``None`` (full), an int nprobe
        override, or ``"cache_only"``."""
        if self._ladder is None:
            return None
        return self._modes[self._ladder.level]

    @property
    def degrade_level(self) -> int:
        """Current degradation-ladder level (0 = full quality, also
        when no ladder is armed) — the healthz/access-log field."""
        return self._ladder.level if self._ladder is not None else 0

    # --- startup prewarm (docs/serving.md "Warm starts") ----------------------

    def prewarm(self, ks: Sequence[int], *, buckets=None,
                exclude_self=(True, False)) -> dict:
        """Compile every (bucket, k, exclude_self, ladder-nprobe)
        executable BEFORE traffic, so the first real request on every
        bucket of the ladder is warm — BOTH ``exclude_self`` settings
        by default, since every serving surface accepts the request
        flag and a cold variant would re-open the p99 cliff for
        whichever flavor the warmup skipped — the cold-bucket p99 cliff the
        PR 7 histograms exposed, closed at startup instead of papered
        over by bench warmup.  With the persistent compilation cache on
        (hyperspace_tpu/compile_cache.py) a restarted server's prewarm
        is deserialization, not compilation — this is the blue-green
        warm path ROADMAP item 4 flips onto.

        Dispatches go STRAIGHT to the engine: no LRU writes, no
        request counters, no latency histograms — prewarm traffic must
        never masquerade as served requests (the only registry marks
        are ``serve/prewarmed`` — programs warmed — and
        ``serve/prewarm_s``).  The engine's own scan mode / precision /
        index are baked into its executables, so a prewarmed bf16 or
        fused or probing engine is warm for exactly the signature it
        serves (the batcher cache key's isolation contract, upheld by
        construction).  The IVF degradation ladder's narrowed widths
        (``_ladder_modes``) are warmed too — stepping down under
        pressure must not hand the compiler a fresh program mid-storm.

        ``ks`` are validated against the table like any request's k; an
        IVF probe combination the index cannot fill raises AFTER its
        executable compiled — those are swallowed here (the program is
        warm, which is all prewarm promises).  Returns
        ``{programs, seconds, buckets, ks}``.
        """
        import jax

        eng = self.engine
        ks = sorted({int(k) for k in ks})
        limit = eng.num_nodes - (1 if any(exclude_self) else 0)
        for k in ks:
            if not 1 <= k <= limit:
                raise ValueError(
                    f"prewarm k={k} out of range [1, {limit}] for a "
                    f"{eng.num_nodes}-row table")
        # full width (None) plus every ladder override the degradation
        # path can serve — deduped after the plan_topk clamp rule
        widths: list = [None]
        for m in self._modes:
            if isinstance(m, int) and m not in widths:
                widths.append(m)
        buckets = tuple(buckets or self.buckets)
        t0 = time.perf_counter()
        warmed = 0
        for b in buckets:
            q = np.arange(b, dtype=np.int64) % eng.num_nodes
            for k in ks:
                for ex in exclude_self:
                    seen_p = set()
                    for p in widths:
                        if p is not None:
                            # the ladder's clamp: the narrowed probe
                            # must still hold k rows (plan_topk)
                            mc = eng.index.max_cell
                            p = min(max(p, -(-k // mc)), eng.nprobe)
                            if p >= eng.nprobe or p in seen_p:
                                continue
                            seen_p.add(p)
                        try:
                            out = eng.topk_neighbors(
                                q, k, exclude_self=bool(ex), nprobe=p)
                            jax.block_until_ready(out)
                        except ValueError:
                            # an under-filled probe raises on the
                            # RESULTS — the executable is already warm,
                            # which is all prewarm promises; real
                            # traffic answers the same error per
                            # request
                            pass
                        warmed += 1
        dt = time.perf_counter() - t0
        telem.inc("serve/prewarmed", warmed)
        telem.inc("serve/prewarm_s", dt)
        return {"programs": warmed, "seconds": dt,
                "buckets": list(buckets), "ks": ks}

    # --- pipeline stages (module docstring, "Pipeline stages") ---------------

    def validate_topk_request(self, ids, k) -> tuple[list[int], int]:
        """Host-side request validation: the id list and k, reject-
        don't-coerce (same policy notes as :func:`_checked_ids`)."""
        ids = _checked_ids(ids, "ids", self.engine.num_nodes)
        if isinstance(k, bool):  # True would index-coerce to k=1
            raise ValueError("k must be an integer; got bool")
        try:  # same reject-don't-truncate policy as the ids
            k = operator.index(k)
        except TypeError:
            raise ValueError(
                f"k must be an integer; got {type(k).__name__}") from None
        return ids, k

    def plan_topk(self, k: int, exclude_self: bool):
        """``(keyf, nprobe_ov, cache_only)``: the ladder's current
        quality mode resolved into an effective nprobe override (or
        None = full width) and the cache key function for this
        (k, exclude_self) under that mode."""
        mode = self._mode()
        nprobe_ov = None
        if isinstance(mode, int):
            # degraded probe width, clamped so the narrowed
            # probe can still hold k rows (capacity = p×max_cell)
            mc = self.engine.index.max_cell
            nprobe_ov = min(max(mode, -(-k // mc)), self.engine.nprobe)
            if nprobe_ov >= self.engine.nprobe:
                nprobe_ov = None  # clamped back to full width
        fp = self.engine.fingerprint
        # cache keys carry exclude_self, the engine's precision
        # mode, AND the EFFECTIVE scan signature (("exact",) or
        # ("ivf", nprobe, index fingerprint) — the ladder's
        # narrowed width included): the same (fp, id, k) has
        # distinct answers per flag, a bf16-scan engine's rows
        # must never be served back by an f32 engine over the
        # same table (same fingerprint!), and an approximate
        # probed answer must never be served back as an exact
        # one — or at a different width, through a different
        # index, or vice versa
        prec = self.engine.precision
        scan = (self.engine.scan_signature_for(nprobe_ov)
                if nprobe_ov is not None
                else self.engine.scan_signature)
        keyf = lambda qid: (fp, qid, k, exclude_self, prec, scan)
        return keyf, nprobe_ov, mode == _CACHE_ONLY

    def cache_pass(self, ids: Sequence[int], keyf,
                   cache_only: bool) -> tuple[dict, list[int]]:
        """``(rows, misses)`` over the request's UNIQUE ids — a
        duplicate within the request is one compute (and one counter
        event), hot or cold.  Under cache-only degradation a cold id
        is shed (NOT counted as a cache miss — nothing was computed)
        rather than dispatched."""
        rows: dict[int, tuple] = {}
        misses: list[int] = []
        for qid in dict.fromkeys(ids):
            hit = self.cache.get(keyf(qid))
            if hit is not None:
                rows[qid] = hit
            else:
                misses.append(qid)
        telem.inc("serve/cache_hit", len(rows))
        if cache_only and misses:
            raise OverloadedError(
                f"cache-only degradation: {len(misses)} cold "
                "id(s) in the request")
        telem.inc("serve/cache_miss", len(misses))
        return rows, misses

    def dispatch_topk(self, misses: Sequence[int], k: int, *,
                      exclude_self: bool, nprobe_ov, keyf,
                      lives: Sequence[_Lifecycle],
                      deadline_life: Optional[_Lifecycle] = None,
                      span_parent=None) -> dict:
        """Dispatch ``misses`` through the engine in bucket-padded
        slabs; returns ``{qid: (idx row, dist row)}`` (rows also land
        in the LRU).  The one device dispatch is attributed to EVERY
        lifecycle in ``lives`` (a collated flush shares it) while the
        ``serve/slots``/``serve/padded_waste`` counters count each slab
        once.  ``deadline_life`` (the sync path's own request) enforces
        the before-dispatch deadline check per slab — an expired
        request is never dispatched late; a collated flush checks
        expiry per member at flush time instead, so one member's
        deadline cannot fail the whole batch.  ``span_parent`` scopes
        the engine's ``device_compute``/``rescore`` stages under the
        caller's span (the sync path passes its lifecycle span; the
        collator passes the shared flush span — contextvars don't
        cross its executor boundary on their own)."""
        rows: dict[int, tuple] = {}
        with spans.use(span_parent):
            rows.update(self._dispatch_topk_slabs(
                misses, k, exclude_self=exclude_self, nprobe_ov=nprobe_ov,
                keyf=keyf, lives=lives, deadline_life=deadline_life))
        self._update_gauges()
        return rows

    def _dispatch_topk_slabs(self, misses, k, *, exclude_self, nprobe_ov,
                             keyf, lives, deadline_life):
        rows: dict[int, tuple] = {}
        for s in range(0, len(misses), self.buckets[-1]):
            if deadline_life is not None:
                # the engine call is the unrecallable cost
                deadline_life.check_deadline("before dispatch")
            slab = list(misses[s : s + self.buckets[-1]])
            b = bucket_for(len(slab), self.buckets)
            telem.inc("serve/slots", b)
            telem.inc("serve/padded_waste", b - len(slab))
            for life in lives:
                life.slab(b)
            padded = slab + [slab[-1]] * (b - len(slab))
            if faults.active():
                faults.hit("serve.dispatch")  # chaos site
            t0 = time.perf_counter()
            try:
                idx, dist = self.engine.topk_neighbors(
                    np.asarray(padded, np.int32), k,
                    exclude_self=exclude_self, nprobe=nprobe_ov)
            except ValueError as e:
                if (nprobe_ov is not None
                        and "under-filled" in str(e)):
                    # the SERVER narrowed the probe, not the
                    # client: a width that under-fills at the
                    # degraded level is an overload symptom,
                    # never a fix-your-request validation error
                    raise OverloadedError(
                        f"degraded probe width {nprobe_ov} "
                        f"under-filled for k={k}; retry later"
                    ) from e
                raise
            # the "rescore" stage: forcing the dispatched program's
            # results to host arrays — on the fused lanes the f32
            # rescore itself runs inside the device_compute program,
            # so this window is the completion wait + materialization
            with spans.stage("rescore", metric="serve/stage/rescore_ms"):
                idx = np.asarray(idx)
                dist = np.asarray(dist)
            dt = time.perf_counter() - t0
            for life in lives:
                life.add_dispatch(dt)
            for j, qid in enumerate(slab):
                val = (idx[j].copy(), dist[j].copy())
                rows[qid] = val
                self.cache.put(keyf(qid), val)
        return rows

    # --- top-k ----------------------------------------------------------------

    def topk(self, ids, k: int, *, exclude_self: bool = True,
             deadline_ms: Optional[float] = None,
             t_enq: Optional[float] = None,
             request_id: Optional[str] = None
             ) -> tuple[np.ndarray, np.ndarray]:
        """``(neighbors [B, k] int32, dists [B, k] float)`` in request
        order; cache-aware, bucket-padded.  ``deadline_ms`` overrides
        the batcher default for this request (None = the default;
        module docstring, "Overload safety"); ``t_enq`` backdates the
        enqueue stamp to an earlier ``time.perf_counter()`` reading
        (socket-accept time — queue time counts against the deadline).
        ``request_id`` threads the caller's trace id into the span args
        and the access log; with a sink armed and no id given, one is
        generated — an access-log line is never anonymous."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if request_id is None and self.access_sink is not None:
            request_id = new_request_id()
        life = self.new_lifecycle("topk", deadline_ms, t_enq=t_enq,
                                  request_id=request_id)
        self.count_request()
        try:
            self._admit()
        except OverloadedError:
            # shed at admission: not admitted, so no _release — but the
            # shed IS a taxonomy outcome the access log must carry
            self.emit_access(life, "overloaded")
            raise
        try:
            with span("query", args=life.info):
                ids, k = self.validate_topk_request(ids, k)
                keyf, nprobe_ov, cache_only = self.plan_topk(
                    k, exclude_self)
                rows, misses = self.cache_pass(ids, keyf, cache_only)
                life.cache_hits = len(rows)
                life.cache_misses = len(misses)
                # batch-form stamp: validation + cache pass done, device
                # work (if any) starts now
                life.formed()
                life.check_deadline("after the cache pass")
                if life.info is not None:
                    life.info.update(requests=len(ids), k=k,
                                     cache_hits=len(rows),
                                     cache_misses=len(misses))
                rows.update(self.dispatch_topk(
                    misses, k, exclude_self=exclude_self,
                    nprobe_ov=nprobe_ov, keyf=keyf, lives=(life,),
                    deadline_life=life, span_parent=life.span))
                life.result_ready()
                out_i = np.stack([rows[qid][0] for qid in ids])
                out_d = np.stack([rows[qid][1] for qid in ids])
                # a result computed past the deadline is answered
                # deadline_exceeded, never returned as if on time (the
                # rows stay cached — the work is not wasted)
                life.check_deadline("at completion")
                life.finish()
                self.emit_access(life)
                return out_i, out_d
        except (ServeError, ValueError, KeyError, TypeError,
                OverflowError, OSError) as e:
            # kind_of is the one exception->taxonomy classification
            # (serve/errors.py): the access-log outcome can never
            # diverge from the wire response's kind
            self.emit_access(life, kind_of(e))
            raise
        finally:
            self._release()

    # --- edge scores ----------------------------------------------------------

    def validate_score_request(self, u_ids,
                               v_ids) -> tuple[np.ndarray, np.ndarray]:
        """Host-side score validation: matching int id arrays."""
        n = self.engine.num_nodes
        u = np.asarray(_checked_ids(u_ids, "u", n), np.int64)
        v = np.asarray(_checked_ids(v_ids, "v", n), np.int64)
        if u.shape != v.shape:
            raise ValueError(
                f"score: need matching id lists; got "
                f"{u.shape} vs {v.shape}")
        return u, v

    def dispatch_score(self, u: np.ndarray, v: np.ndarray, *,
                       prob: bool, fd_r: float, fd_t: float,
                       lives: Sequence[_Lifecycle],
                       deadline_life: Optional[_Lifecycle] = None,
                       span_parent=None) -> np.ndarray:
        """Slab-dispatch validated edge pairs (the score analog of
        :meth:`dispatch_topk`; same slot-counting, lifecycle-
        attribution, and span-scoping contract)."""
        out = np.empty((u.size,), np.float64)
        top = self.buckets[-1]
        with spans.use(span_parent):
            for s in range(0, u.size, top):
                if deadline_life is not None:
                    deadline_life.check_deadline("before dispatch")
                su, sv = u[s : s + top], v[s : s + top]
                b = bucket_for(su.size, self.buckets)
                telem.inc("serve/slots", b)
                telem.inc("serve/padded_waste", b - su.size)
                for life in lives:
                    life.slab(b)
                pu = np.concatenate([su, np.full(b - su.size, su[-1])])
                pv = np.concatenate([sv, np.full(b - sv.size, sv[-1])])
                if faults.active():
                    faults.hit("serve.dispatch")  # chaos site
                t0 = time.perf_counter()
                d = self.engine.score_edges(
                    pu.astype(np.int32), pv.astype(np.int32),
                    prob=prob, fd_r=fd_r, fd_t=fd_t)
                with spans.stage("rescore",
                                 metric="serve/stage/rescore_ms"):
                    out[s : s + su.size] = np.asarray(d)[: su.size]
                dt = time.perf_counter() - t0
                for life in lives:
                    life.add_dispatch(dt)
        self._update_gauges()
        return out

    def score(self, u_ids, v_ids, *, prob: bool = False,
              fd_r: float = 2.0, fd_t: float = 1.0,
              deadline_ms: Optional[float] = None,
              t_enq: Optional[float] = None,
              request_id: Optional[str] = None) -> np.ndarray:
        """Bucket-padded ``engine.score_edges`` ([B] in request order).

        Same admission/deadline/request-id contract as :meth:`topk`;
        edge scoring is uncached, so the cache-only degradation level
        sheds every score request (an uncached op has nothing cheaper
        to serve)."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if request_id is None and self.access_sink is not None:
            request_id = new_request_id()
        life = self.new_lifecycle("score", deadline_ms, t_enq=t_enq,
                                  request_id=request_id)
        self.count_request()
        try:
            self._admit()
        except OverloadedError:
            self.emit_access(life, "overloaded")
            raise
        try:
            with span("query", args=life.info):
                if self._mode() == _CACHE_ONLY:
                    raise OverloadedError(
                        "cache-only degradation: edge scoring is "
                        "uncached")
                u, v = self.validate_score_request(u_ids, v_ids)
                life.formed()
                life.check_deadline("after validation")
                if life.info is not None:
                    life.info["requests"] = int(u.size)
                out = self.dispatch_score(u, v, prob=prob, fd_r=fd_r,
                                          fd_t=fd_t, lives=(life,),
                                          deadline_life=life,
                                          span_parent=life.span)
                life.result_ready()
                life.check_deadline("at completion")
                life.finish()
                self.emit_access(life)
                return out
        except (ServeError, ValueError, KeyError, TypeError,
                OverflowError, OSError) as e:
            # kind_of is the one exception->taxonomy classification
            # (serve/errors.py): the access-log outcome can never
            # diverge from the wire response's kind
            self.emit_access(life, kind_of(e))
            raise
        finally:
            self._release()

    # --- mutations (live engines only — serve/delta.py) -----------------------

    def _live_engine(self):
        """The engine, checked mutable: a frozen engine answering an
        upsert with an AttributeError deep in the stack would classify
        ``internal`` — it is a validation failure (fix your request /
        serve with ``live=true``), and must say so."""
        if not hasattr(self.engine, "upsert"):
            raise ValueError(
                "engine is frozen: mutations need a live engine "
                "(serve with live=true, or wrap the base in "
                "serve.delta.LiveQueryEngine)")
        return self.engine

    def _mutate(self, op: str, apply, *, deadline_ms: Optional[float],
                t_enq: Optional[float],
                request_id: Optional[str]) -> dict:
        """The shared mutation envelope: same admission / deadline /
        access-log contract as :meth:`topk`; ``apply(engine)`` runs the
        validated mutation and returns the response dict.  On success
        the event→servable freshness (``serve/upsert_visible_ms``:
        enqueue stamp → generation bumped, mask uploaded on next sync)
        is observed — THE latency a live index is judged by."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if request_id is None and self.access_sink is not None:
            request_id = new_request_id()
        life = self.new_lifecycle(op, deadline_ms, t_enq=t_enq,
                                  request_id=request_id)
        self.count_request()
        try:
            self._admit()
        except OverloadedError:
            self.emit_access(life, "overloaded")
            raise
        try:
            with span("query", args=life.info):
                eng = self._live_engine()
                life.formed()
                life.check_deadline("before the mutation")
                out = apply(eng)
                life.result_ready()
                telem.observe("serve/upsert_visible_ms",
                              (time.perf_counter() - life.t_enq) * 1e3)
                # a mutation is never rolled back by its deadline: once
                # applied it is visible (the generation already moved),
                # so the late answer reports deadline_exceeded WITH the
                # mutation durable — like a cached row computed late
                life.check_deadline("at completion")
                life.finish()
                self.emit_access(life)
                return out
        except (ServeError, ValueError, KeyError, TypeError,
                OverflowError, OSError) as e:
            self.emit_access(life, kind_of(e))
            raise
        finally:
            self._release()

    def upsert(self, ids, rows, *,
               deadline_ms: Optional[float] = None,
               t_enq: Optional[float] = None,
               request_id: Optional[str] = None) -> dict:
        """Insert/update rows through the live engine's delta segment
        (``{"upserted", "inserted", "generation", "segment_rows"}``).
        Validation (id contiguity for inserts, row shapes,
        last-write-wins dedup) lives in
        :meth:`~hyperspace_tpu.serve.delta.LiveQueryEngine.upsert`."""
        return self._mutate(
            "upsert", lambda eng: eng.upsert(ids, rows),
            deadline_ms=deadline_ms, t_enq=t_enq, request_id=request_id)

    def delete(self, ids, *,
               deadline_ms: Optional[float] = None,
               t_enq: Optional[float] = None,
               request_id: Optional[str] = None) -> dict:
        """Tombstone rows (``{"deleted", "generation"}``) — the id
        space never shrinks; the rows become unreachable."""
        return self._mutate(
            "delete", lambda eng: eng.delete(ids),
            deadline_ms=deadline_ms, t_enq=t_enq, request_id=request_id)

    # --- introspection --------------------------------------------------------

    def _update_gauges(self) -> None:
        """Refresh the ratio gauges from the cumulative counters.

        The raw ``serve/padded_waste`` counter grows forever; the gauge
        forms (waste / engine slots dispatched, cache hits / lookups)
        are the levels a dashboard — and the bench's ``serve_qps`` leg —
        can read directly without differencing counters."""
        reg = telem.default_registry()
        slots = reg.get("serve/slots")
        if slots:
            telem.set_gauge("serve/padded_waste_ratio",
                            round(reg.get("serve/padded_waste") / slots, 4))
        lookups = reg.get("serve/cache_hit") + reg.get("serve/cache_miss")
        if lookups:
            telem.set_gauge("serve/cache_hit_rate",
                            round(reg.get("serve/cache_hit") / lookups, 4))

    def stats(self) -> dict:
        """Current serve counters + ratio gauges + cache occupancy (the
        `stats` op of the CLI loop).  ``latency_e2e_ms`` is the
        process-cumulative ``serve/e2e_ms`` histogram summary
        (count/sum/min/max/p50..p99) — None before the first request."""
        reg = telem.default_registry()
        gauges = reg.snapshot()
        return {
            "tenant": self.tenant,
            "latency_e2e_ms": gauges.get("hist/serve/e2e_ms"),
            # compile count beside the serve stats (the stdin loop's
            # analog of the HTTP stats field): the contract every smoke
            # and bench leg reads is recompiles FLAT once warm
            "recompiles": reg.get("jax/recompiles"),
            "prewarmed": reg.get("serve/prewarmed"),
            "requests": reg.get("serve/requests"),
            "cache_hit": reg.get("serve/cache_hit"),
            "cache_miss": reg.get("serve/cache_miss"),
            "cache_hit_rate": gauges.get("serve/cache_hit_rate", 0.0),
            "padded_waste": reg.get("serve/padded_waste"),
            "padded_waste_ratio": gauges.get("serve/padded_waste_ratio", 0.0),
            "slots": reg.get("serve/slots"),
            "cache_entries": len(self.cache),
            "buckets": list(self.buckets),
            "fingerprint": self.engine.fingerprint,
            "precision": self.engine.precision,
            # which engine answered: "exact" or "ivf" (+ nprobe) — the
            # serve CLI stats line must identify an approximate server
            "scan_strategy": self.engine.scan_strategy,
            "scan_mode": self.engine.scan_mode,
            "nprobe": self.engine.nprobe,
            # live-index identity (serve/delta.py): the segment
            # generation and current delta occupancy — None on a
            # frozen engine, so a stats consumer can tell the worlds
            # apart at a glance
            "generation": getattr(self.engine, "generation", None),
            "segment_rows": getattr(self.engine, "segment_rows", None),
            # overload safety (docs/resilience.md): queue bound, shed /
            # deadline counts, and the ladder's current level+mode —
            # a stats consumer must see a degraded server AS degraded
            "queue_max": (self._admission.queue_max
                          if self._admission else 0),
            "shed": reg.get("serve/shed"),
            "deadline_exceeded": reg.get("serve/deadline_exceeded"),
            "errors": reg.get("serve/errors"),
            "degrade_level": (self._ladder.level if self._ladder else 0),
            "degrade_mode": ("full" if self._mode() is None
                             else str(self._mode())),
            # rolling-window SLO view (docs/observability.md "Windowed
            # SLOs"): p50/p95/p99 + rates from ring DELTAS, None when
            # no window is armed — a stats consumer can tell "no
            # window" from "no traffic"
            "window": (self.window.report()
                       if self.window is not None else None),
        }
