"""Blue-green rollover: warm a standby engine, gate, flip, drain.

The zero-downtime half of the live-index subsystem (serve/delta.py is
the in-place half; docs/serving.md "Live index and rollover").  A
rollover replaces the WHOLE serving stack behind the front door — new
artifact, new engine, new batcher, new collator — without dropping or
slowing a single in-flight request:

1. **Prepare (blocking, off-loop).**  Build the standby engine +
   batcher from the target artifact and run the full
   :meth:`RequestBatcher.prewarm` ladder — every bucket × k ×
   exclude_self × degradation width is compiled BEFORE the standby can
   take traffic, so the first post-flip request lands on a warm
   executable (``recompiles_steady == 0`` across the flip is the
   ``bench_live_index`` acceptance gate).
2. **Gate.**  The flip is refused unless the standby's enriched
   health body — the same shape ``GET /healthz`` serves: ``ok`` /
   ``fingerprint`` / ``scan_signature`` / ``precision`` /
   ``degrade_level`` — is green: present, ok, and undegraded
   (:func:`gate_flip`).  A standby that would answer with a different
   precision lane than requested, or come up already shedding, must
   never take traffic silently.
3. **Flip (atomic, on-loop).**  The front door's ``batcher`` /
   ``collator`` attributes are reassigned in one event-loop step — a
   request routed before the step uses the old stack end-to-end, one
   routed after uses the new; there is no torn state to observe.  The
   batcher caches are keyed by fingerprint + scan signature, so the
   old engine's cached rows are unreachable by construction.
4. **Drain the old stack.**  Pending old-collator buckets are force-
   flushed (their requests answer from the OLD engine — consistent
   with the prefix they were admitted under) and its dispatch executor
   is released without blocking the loop.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional, Sequence

from hyperspace_tpu.serve.batcher import RequestBatcher
from hyperspace_tpu.serve.collator import Collator
from hyperspace_tpu.telemetry import registry as telem

# the enriched-healthz fields a flip inspects; all must be present —
# a builder handing back a batcher that cannot report one of these is
# a batcher whose identity the cache key cannot express
GATE_FIELDS = ("ok", "fingerprint", "scan_signature", "precision",
               "degrade_level")

DEFAULT_PREWARM_KS = (10,)


def standby_health(batcher: RequestBatcher) -> dict:
    """The enriched health body of a NOT-yet-serving batcher — the
    same identity fields ``GET /healthz`` exposes, minus the uptime
    (it has none): what :func:`gate_flip` inspects."""
    eng = batcher.engine
    return {
        "ok": True,
        "fingerprint": eng.fingerprint,
        "scan_signature": list(eng.scan_signature),
        "precision": eng.precision,
        "degrade_level": batcher.degrade_level,
    }


def gate_flip(body: dict) -> None:
    """Refuse a flip unless the standby's health body is green:
    every :data:`GATE_FIELDS` entry present, ``ok`` true, and
    ``degrade_level == 0`` (a standby that comes up already degraded
    would silently downgrade every post-flip answer)."""
    missing = [f for f in GATE_FIELDS if body.get(f) is None]
    if missing:
        raise ValueError(
            f"rollover gate: standby health body is missing {missing} "
            "— refusing to flip onto an engine whose identity the "
            "cache key cannot express")
    if body["ok"] is not True:
        raise ValueError("rollover gate: standby reports ok=false")
    if int(body["degrade_level"]) != 0:
        raise ValueError(
            f"rollover gate: standby is degraded "
            f"(level {body['degrade_level']}) — it must come up at "
            "full quality before taking traffic")


class RolloverCoordinator:
    """Drives blue-green flips for one :class:`~hyperspace_tpu.serve.
    server.HttpFrontDoor`.

    ``builder(target)`` constructs the standby ``RequestBatcher`` for a
    rollover target (the CLI passes its artifact loader; tests pass a
    closure).  It runs on the default executor — it is expected to
    block (artifact IO, device upload, prewarm compilation)."""

    def __init__(self, door, builder: Callable[[str], RequestBatcher], *,
                 prewarm_ks: Optional[Sequence[int]] = None):
        self.door = door
        self.builder = builder
        self.prewarm_ks = list(prewarm_ks or DEFAULT_PREWARM_KS)
        self.flips = 0
        self._busy = False  # one rollover at a time (loop-affine flag)

    def _prepare(self, target: str) -> tuple[RequestBatcher, dict]:
        """Blocking half: build + prewarm the standby, return it with
        its prewarm report.  Runs off-loop."""
        standby = self.builder(target)
        info = standby.prewarm(self.prewarm_ks)
        return standby, info

    async def rollover(self, target: str) -> dict:
        """Prepare → gate → flip → drain; returns the flip report.
        Raises ``ValueError`` when the gate refuses (the old stack
        keeps serving, untouched)."""
        if self._busy:
            raise ValueError(
                "rollover already in progress — one at a time (the "
                "standby build owns the device build bandwidth)")
        self._busy = True
        try:
            t0 = time.perf_counter()
            loop = asyncio.get_running_loop()
            old = self.door.batcher
            standby, info = await loop.run_in_executor(
                None, self._prepare, target)
            health = standby_health(standby)
            gate_flip(health)
            self.flip(standby)
            self.flips += 1
            telem.inc("serve/rollover_flips", 1)
            return {
                "flipped": True,
                "old_fingerprint": old.engine.fingerprint,
                "new_fingerprint": standby.engine.fingerprint,
                "scan_signature": health["scan_signature"],
                "prewarmed_programs": info["programs"],
                "seconds": round(time.perf_counter() - t0, 3),
            }
        finally:
            self._busy = False

    def flip(self, standby: RequestBatcher) -> None:
        """The atomic swap: one event-loop step reassigns the door's
        batcher + collator, then drains the old stack.  Also usable
        directly (tests, in-process benches) with a pre-built warmed
        standby."""
        door = self.door
        old_collator = door.collator
        # mirror the old collator's dispatch wiring: under a
        # multi-tenant registry the executor is SHARED (and closing the
        # old collator leaves it running), so the standby must keep
        # dispatching through the same executor + fair dispatcher —
        # two one-worker executors would race on the device
        new_collator = Collator(
            standby, max_wait_us=old_collator.max_wait_s * 1e6,
            executor=(None if old_collator._owns_exec
                      else old_collator._exec),
            dispatcher=old_collator._dispatcher,
            tenant=old_collator.tenant)
        # the swap itself: two attribute writes in one loop step — a
        # routed request observes either (old, old) or (new, new)
        door.batcher = standby
        door.collator = new_collator
        # old stack drains: queued buckets answer from the OLD engine
        # (consistent with the prefix they were admitted under), then
        # the executor is released without blocking the loop
        old_collator.flush_all()
        old_collator.close(wait=False)
