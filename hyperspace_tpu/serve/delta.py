"""Live mutable index: an LSM-style delta segment over a frozen engine.

The serving stack up to here is frozen-world: one immutable artifact,
one immutable IVF index, a cache keyed by fingerprint.  This module
adds the write path (ROADMAP item 2) with the classic LSM shape
(O'Neil et al. 1996; FreshDiskANN's fresh-list + merge): a small,
exact-scanned **delta segment** in front of the frozen base absorbs
insert / update / delete, and a **background compaction** folds the
accumulated mutations into a rebuilt base, atomically swapped.

Design invariants (docs/serving.md "Live index and rollover"):

- **Ids are row indices, forever.**  The whole stack (batcher cache,
  exclude-self masks, artifact layout) treats an id as a row number, so
  compaction may never renumber.  Inserts therefore land at the
  contiguous tail (``HostEmbedTable.append_rows``) and a deleted id's
  row is never reclaimed — it is *tombstoned*.
- **Tombstones live on device, as a traced penalty row.**  ``_drop``
  is an ``[npad] f32`` operand (0 = live, +inf = deleted or superseded
  by a delta write) added to every scan tile before top-k inside the
  frozen engine's jitted programs (``engine.topk_neighbors(drop=...)``)
  — so a dead base row can never win, the executable count never grows
  (the mask is traced, not static), and the f32 rescore preserves the
  +inf.  Unbounded tombstone counts would break any over-fetch scheme;
  the penalty row makes the cost O(1) per tile whatever the count.
- **Queries score FRESH vectors.**  The query rows are gathered from
  the mutable host master (``q_rows=``), not the frozen device table —
  a query *by* an updated id must rank against its post-upsert vector.
- **The generation makes staleness structural.**  Every mutation bumps
  a monotone ``generation`` which :attr:`scan_signature` folds into
  the batcher's cache key — a cached row from generation g can never
  answer a generation-g+1 request, by key inequality rather than by
  invalidation bookkeeping.

Write path per :meth:`LiveQueryEngine.upsert` (under the engine lock):
write-through to the host master (``write_back`` / ``append_rows``),
copy into a free delta slot (last-write-wins on re-upsert), tombstone
the superseded base row, bump the generation.  The delta segment is a
FIXED-capacity ``[cap, D]`` array — static shapes, so the merged query
path compiles once per bucket and ``recompiles_steady == 0`` holds
under a sustained upsert stream (the acceptance gate of
``bench.py bench_live_index``).

Compaction (:meth:`compact`, auto-triggered at ``compact_at``
occupancy) snapshots the master, re-clusters via the streaming
:func:`~hyperspace_tpu.serve.index.build_index` (beyond-HBM capable),
builds a fresh frozen engine, and swaps it in atomically — entries
written *after* the snapshot stay in the delta (per-entry sequence
numbers), deleted ids stay tombstoned (rows are never renumbered), and
the base fingerprint changes so fingerprint-keyed caches roll over.

This module and ``parallel/host_table.py`` are the ONE sanctioned home
of in-place writes to serving table state — the ``frozen-table-
mutation`` hyperlint rule errors on such writes anywhere else.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.parallel.host_table import HostEmbedTable
from hyperspace_tpu.serve.engine import (QueryEngine, _edge_dist_rows,
                                         _tile_dist)
from hyperspace_tpu.telemetry import registry as telem

DEFAULT_DELTA_CAP = 1024
DEFAULT_COMPACT_AT = 0.75


@partial(jax.jit, static_argnames=("spec", "exclude_self"))
def _delta_scan(q: jax.Array, rows: jax.Array, penalty: jax.Array,
                q_idx: jax.Array, ids: jax.Array, *, spec: tuple,
                exclude_self: bool) -> jax.Array:
    """Exact distances of ``q`` [B, D] against the delta segment
    ``rows`` [cap, D] → [B, cap].  ``penalty`` (+inf on free slots)
    and the optional self-mask ride inside the one jitted program;
    all operands are traced, so mutation never recompiles."""
    d = _tile_dist(spec, q, rows) + penalty[None, :]
    if exclude_self:
        d = jnp.where(ids[None, :] == q_idx[:, None], jnp.inf, d)
    return d


class LiveQueryEngine:
    """A mutable engine: frozen :class:`QueryEngine` base + host master
    + fixed-capacity delta segment.  Duck-types the ``QueryEngine``
    query surface (``topk_neighbors`` / ``score_edges`` / the batcher's
    attribute set), so ``RequestBatcher`` serves it unchanged.

    ``base`` must not be a fused-scan engine: the fused kernel has no
    tombstone lane, and an engine advertising ``"fused"`` in its
    signature while silently dispatching the two-stage fallback would
    lie to the cache key.  Construct the base with
    ``scan_mode="two_stage"`` (or ``"carry"``).
    """

    def __init__(self, base: QueryEngine, master: HostEmbedTable, *,
                 capacity: int = DEFAULT_DELTA_CAP,
                 compact_at: float = DEFAULT_COMPACT_AT,
                 auto_compact: bool = True):
        if base.scan_mode == "fused":
            raise ValueError(
                "LiveQueryEngine needs a two_stage/carry base: the fused "
                "kernel has no tombstone lane, and a silent fallback "
                "would desync the engine's scan_signature from the "
                "program that answers")
        if int(master.num_rows) != base.num_nodes:
            raise ValueError(
                f"master has {master.num_rows} rows; base engine was "
                f"built over {base.num_nodes} — they must start aligned")
        if int(master.width) != base.dim:
            raise ValueError(
                f"master width {master.width} != engine dim {base.dim}")
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        if not 0.0 < float(compact_at) <= 1.0:
            raise ValueError(
                f"compact_at must be in (0, 1]; got {compact_at}")
        self.base = base
        self.master = master
        self.capacity = capacity
        self.compact_at = float(compact_at)
        self.auto_compact = bool(auto_compact)
        # rebuild recipe for compaction: the swapped-in engine must be
        # the SAME serving configuration over the merged table
        self._ncells = int(base.index.ncells) if base.index is not None \
            else 0
        # delta state (host mirrors; device copies sync on mutation).
        # pen: 0 = live entry, +inf = free OR freed slot — free slots
        # can never win a top-k, so the scan needs no occupancy mask
        dim = base.dim
        self._rows = np.zeros((capacity, dim), np.float32)
        self._ids = np.full((capacity,), -1, np.int32)
        self._pen = np.full((capacity,), np.inf, np.float32)
        self._seq = np.zeros((capacity,), np.int64)  # write stamps
        self._slot_of: dict[int, int] = {}
        self._free = list(range(capacity - 1, -1, -1))
        self._deleted: set[int] = set()
        self._drop = np.zeros((base.table.shape[0],), np.float32)
        self._gen = 0
        self._next_seq = 1
        self._lock = threading.RLock()
        self._compact_lock = threading.Lock()
        self._dirty = True
        self._dev = None  # (rows, ids, pen, drop) jnp mirrors

    # --- QueryEngine duck-type surface ---------------------------------------

    @property
    def fingerprint(self) -> str:
        return self.base.fingerprint

    @property
    def precision(self) -> str:
        return self.base.precision

    @property
    def scan_mode(self) -> str:
        return self.base.scan_mode

    @property
    def scan_strategy(self) -> str:
        return self.base.scan_strategy

    @property
    def nprobe(self) -> int:
        return self.base.nprobe

    @property
    def index(self):
        return self.base.index

    @property
    def spec(self) -> tuple:
        return self.base.spec

    @property
    def dim(self) -> int:
        return self.base.dim

    @property
    def num_nodes(self) -> int:
        """Total id space [0, N) — tombstoned rows INCLUDED (ids are
        row indices; a deleted id stays addressable-and-rejected)."""
        return int(self.master.num_rows)

    @property
    def num_live(self) -> int:
        return int(self.master.num_rows) - len(self._deleted)

    @property
    def generation(self) -> int:
        return self._gen

    @property
    def segment_rows(self) -> int:
        return len(self._slot_of)

    @property
    def scan_signature(self) -> tuple:
        """The base signature + the segment generation: the batcher's
        fingerprint-keyed LRU then CANNOT serve a pre-mutation row to a
        post-mutation request — the keys differ structurally."""
        return self.base.scan_signature + ("gen", self._gen)

    def scan_signature_for(self, nprobe: int) -> tuple:
        return self.base.scan_signature_for(nprobe) + ("gen", self._gen)

    # --- queries --------------------------------------------------------------

    def _sync_device(self):
        with self._lock:
            if self._dirty or self._dev is None:
                self._dev = (jnp.asarray(self._rows),
                             jnp.asarray(self._ids),
                             jnp.asarray(self._pen),
                             jnp.asarray(self._drop))
                self._dirty = False
            return self._dev, self.base

    def _check_live_ids(self, ids, name: str) -> np.ndarray:
        arr = np.asarray(ids)
        if arr.ndim != 1 or arr.size == 0:
            raise ValueError(f"{name} must be a non-empty 1-D id array")
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError(f"{name} must be integer ids; got {arr.dtype}")
        if arr.size and (arr.min() < 0 or arr.max() >= self.num_nodes):
            raise ValueError(
                f"{name} out of range [0, {self.num_nodes}): "
                f"min={arr.min()}, max={arr.max()}")
        dead = [int(i) for i in arr if int(i) in self._deleted]
        if dead:
            raise ValueError(
                f"{name} refers to deleted id(s) {sorted(set(dead))[:8]} "
                "— tombstoned rows cannot be queried")
        return arr.astype(np.int64)

    def topk_neighbors(self, q_idx, k: int, *, exclude_self: bool = True,
                       nprobe: Optional[int] = None):
        """``(neighbors [B, k] int32, dists [B, k] f32)`` over the LIVE
        view: base scan with the tombstone mask, merged with the exact
        delta-segment scan, both scoring the query's FRESH master row.
        Sorted ascending; a tombstoned or superseded row can never
        appear.  Raises the under-filled ``ValueError`` when fewer than
        ``k`` live rows are reachable (k > live-row-count included) —
        never serves a tombstone as filler."""
        arr = self._check_live_ids(q_idx, "q_idx")
        k = int(k)
        limit = self.num_nodes - (1 if exclude_self else 0)
        if not 1 <= k <= limit:
            raise ValueError(
                f"k={k} out of range [1, {limit}] for a {self.num_nodes}-"
                f"row table (exclude_self={exclude_self})")
        # snapshot the device mirrors + base under the lock (an upsert
        # mid-query must not hand us gen-g rows with a gen-g+1 mask)
        (d_rows, d_ids, d_pen, d_drop), base = self._sync_device()
        q_rows = self.master.gather(arr)  # FRESH post-upsert vectors
        base_k = min(k, base.num_nodes - (1 if exclude_self else 0))
        if base.scan_strategy == "ivf":
            base_k = min(base_k, base.nprobe * base.index.max_cell)
        base_k = max(base_k, 1)
        bi, bd = base.topk_neighbors(
            arr.astype(np.int32), base_k, exclude_self=exclude_self,
            nprobe=nprobe, q_rows=q_rows, drop=d_drop,
            allow_underfill=True)
        dd = _delta_scan(jnp.asarray(q_rows), d_rows, d_pen,
                         jnp.asarray(arr, jnp.int32), d_ids,
                         spec=base.spec, exclude_self=exclude_self)
        # host merge: [B, base_k + cap] candidates; tombstoned base rows
        # carry +inf (the drop penalty survives the rescore), free delta
        # slots carry +inf, and a delta-resident id's base copy is
        # tombstoned — so no id can appear twice at finite distance
        cand_d = np.concatenate([np.asarray(bd), np.asarray(dd)], axis=1)
        cand_i = np.concatenate(
            [np.asarray(bi),
             np.broadcast_to(np.asarray(d_ids)[None, :],
                             (arr.size, self.capacity))], axis=1)
        if k > cand_d.shape[1]:
            raise ValueError(
                f"live top-k under-filled: k={k} exceeds the "
                f"{cand_d.shape[1]} reachable candidate slots "
                f"({self.num_live} live of {self.num_nodes} rows) — "
                "lower k, raise nprobe=, or compact")
        part = np.argpartition(cand_d, k - 1, axis=1)[:, :k]
        rowix = np.arange(arr.size)[:, None]
        sel_d = cand_d[rowix, part]
        order = np.argsort(sel_d, axis=1, kind="stable")
        top = part[rowix, order]
        out_d = cand_d[rowix, top]
        out_i = cand_i[rowix, top].astype(np.int32)
        if np.isinf(out_d).any():
            raise ValueError(
                f"live top-k under-filled: k={k} exceeds the reachable "
                f"live rows ({self.num_live} live of {self.num_nodes}; "
                "tombstones are excluded, never served) — lower k or "
                "compact after fewer deletes")
        return out_i, out_d

    def score_edges(self, u_idx, v_idx, *, prob: bool = False,
                    fd_r: float = 2.0, fd_t: float = 1.0):
        """Per-pair distances over FRESH master rows (a scored endpoint
        updated one generation ago must score its new vector)."""
        u = self._check_live_ids(u_idx, "u_idx")
        v = self._check_live_ids(v_idx, "v_idx")
        if u.shape != v.shape:
            raise ValueError(
                f"u_idx {u.shape} and v_idx {v.shape} must match")
        xu = jnp.asarray(self.master.gather(u))
        xv = jnp.asarray(self.master.gather(v))
        return _edge_dist_rows(xu, xv, fd_r, fd_t, spec=self.base.spec,
                               prob=bool(prob))

    # --- mutations ------------------------------------------------------------

    def _validate_upsert(self, ids, rows):
        arr = np.asarray(ids)
        if arr.ndim != 1 or arr.size == 0:
            raise ValueError("ids must be a non-empty 1-D id array")
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError(f"ids must be integer ids; got {arr.dtype}")
        rows = np.asarray(rows, np.float32)
        if rows.shape != (arr.size, self.dim):
            raise ValueError(
                f"rows {rows.shape} must be ({arr.size}, {self.dim})")
        if arr.size and arr.min() < 0:
            raise ValueError(f"ids must be >= 0; got min={arr.min()}")
        return arr.astype(np.int64), rows

    def upsert(self, ids, rows) -> dict:
        """Insert or update rows; returns ``{"upserted", "inserted",
        "generation", "segment_rows"}``.

        Updates target existing (possibly deleted — that's a
        reinsert) ids; inserts must extend the id space CONTIGUOUSLY
        from ``num_nodes`` (ids are row indices — a gap would be an
        unaddressable hole forever).  Duplicate ids in one batch
        resolve last-write-wins, like a re-upsert across batches.
        Write order: master first (write-through), then the delta slot,
        then the tombstone on the superseded base row, then the
        generation bump — a concurrent query holds the previous
        generation's consistent view throughout."""
        arr, rows = self._validate_upsert(ids, rows)
        with self._lock:
            n0 = self.num_nodes
            new = np.unique(arr[arr >= n0])
            want = np.arange(n0, n0 + new.size, dtype=np.int64)
            if new.size and not np.array_equal(np.sort(new), want):
                raise ValueError(
                    f"insert ids must be contiguous from {n0} (ids are "
                    f"row indices); got new ids {sorted(new.tolist())[:8]}")
            # last-write-wins within the batch: keep the final
            # occurrence of each id, in id order of final writes
            last = {}
            for j, i in enumerate(arr.tolist()):
                last[i] = j
            uniq = np.fromiter(last.keys(), np.int64, len(last))
            take = np.fromiter(last.values(), np.int64, len(last))
            urows = rows[take]
            need = sum(1 for i in uniq.tolist()
                       if int(i) not in self._slot_of)
            if need > len(self._free):
                # segment full: fold it into the base, then retry —
                # compaction empties every slot at or before its seq
                self._compact_locked()
                if need > len(self._free):
                    raise ValueError(
                        f"upsert batch needs {need} delta slots; "
                        f"capacity is {self.capacity} — raise "
                        "delta_cap or split the batch")
            # write-through to the beyond-HBM master
            ins = uniq >= n0
            if ins.any():
                order = np.argsort(uniq[ins])
                got = self.master.append_rows(urows[ins][order])
                assert np.array_equal(got, np.sort(uniq[ins]))
            if (~ins).any():
                self.master.write_back(uniq[~ins], urows[~ins])
            inserted = int(ins.sum())
            seq = self._next_seq
            self._next_seq += 1
            for i, r in zip(uniq.tolist(), urows):
                i = int(i)
                slot = self._slot_of.get(i)
                if slot is None:
                    slot = self._free.pop()
                    self._slot_of[i] = slot
                self._rows[slot] = r
                self._ids[slot] = i
                self._pen[slot] = 0.0
                self._seq[slot] = seq
                self._deleted.discard(i)
                if i < self.base.num_nodes:
                    # the frozen base row is now stale — tombstone it
                    self._drop[i] = np.inf
            self._gen += 1
            self._dirty = True
            telem.inc("serve/upserts", len(uniq))
            telem.set_gauge("serve/segment_rows", self.segment_rows)
            out = {"upserted": int(len(uniq)), "inserted": inserted,
                   "generation": self._gen,
                   "segment_rows": self.segment_rows}
        self._maybe_compact_async()
        return out

    def delete(self, ids) -> dict:
        """Tombstone rows; returns ``{"deleted", "generation"}``.  The
        id stays allocated (rows are never renumbered) but can no
        longer be queried or returned; re-upserting it later revives
        it (delete-then-reinsert works across compactions)."""
        arr = self._check_live_ids(ids, "ids")
        uniq = np.unique(arr)
        with self._lock:
            for i in uniq.tolist():
                i = int(i)
                self._deleted.add(i)
                slot = self._slot_of.pop(i, None)
                if slot is not None:
                    self._ids[slot] = -1
                    self._pen[slot] = np.inf
                    self._seq[slot] = 0
                    self._free.append(slot)
                if i < self.base.num_nodes:
                    self._drop[i] = np.inf
            self._gen += 1
            self._dirty = True
            telem.inc("serve/tombstones", len(uniq))
            telem.set_gauge("serve/segment_rows", self.segment_rows)
            return {"deleted": int(len(uniq)), "generation": self._gen}

    # --- compaction -----------------------------------------------------------

    def _maybe_compact_async(self):
        if not self.auto_compact:
            return
        if self.segment_rows < self.compact_at * self.capacity:
            return
        if not self._compact_lock.acquire(blocking=False):
            return  # one compaction at a time; the running one covers us
        t = threading.Thread(
            target=self._compact_bg, name="delta-compact", daemon=True)
        t.start()

    def _compact_bg(self):
        try:
            self._compact_inner()
        finally:
            self._compact_lock.release()

    def compact(self) -> dict:
        """Synchronous compaction: fold the delta into a rebuilt frozen
        base and swap atomically.  Returns ``{"generation",
        "fingerprint", "segment_rows"}``."""
        with self._compact_lock:
            return self._compact_inner()

    def _compact_locked(self):
        """Compact while already holding ``self._lock`` (the full-
        segment upsert path).  RLock re-entry keeps the snapshot and
        swap atomic with the caller's batch."""
        if self._compact_lock.acquire(blocking=False):
            try:
                self._compact_inner()
            finally:
                self._compact_lock.release()

    def _compact_inner(self) -> dict:
        base = self.base
        with self._lock:
            # mutations hold self._lock, so this snapshot is a
            # consistent point-in-time copy; entries written after it
            # (seq > mark) stay in the delta
            mark = self._next_seq - 1
            arr = self.master.to_array()
        index = None
        if self._ncells:
            # streaming hyperbolic-k-means rebuild over the merged
            # table (host-resident capable — build_index chunks it)
            from hyperspace_tpu.serve.index import build_index
            snap = HostEmbedTable.from_array(arr)
            index = build_index(snap, base.spec, self._ncells)
        new_base = QueryEngine(
            arr, base.spec, chunk_rows=base.chunk_rows,
            mesh=base.mesh, mesh_axis=base.mesh_axis,
            scan_mode=base.scan_mode, precision=base.precision,
            index=index, nprobe=base.nprobe if index is not None else 0)
        with self._lock:
            self.base = new_base
            # purge every slot the snapshot covered; keep post-mark
            # writers (their master rows are newer than the snapshot,
            # so their NEW base copies are stale and stay tombstoned)
            for i, slot in list(self._slot_of.items()):
                if self._seq[slot] <= mark:
                    del self._slot_of[i]
                    self._ids[slot] = -1
                    self._pen[slot] = np.inf
                    self._seq[slot] = 0
                    self._free.append(slot)
            drop = np.zeros((new_base.table.shape[0],), np.float32)
            for i in self._deleted:
                if i < new_base.num_nodes:
                    drop[i] = np.inf
            for i in self._slot_of:
                if i < new_base.num_nodes:
                    drop[i] = np.inf
            self._drop = drop
            self._gen += 1
            self._dirty = True
            telem.inc("serve/compactions", 1)
            telem.set_gauge("serve/segment_rows", self.segment_rows)
            return {"generation": self._gen,
                    "fingerprint": new_base.fingerprint,
                    "segment_rows": self.segment_rows}
