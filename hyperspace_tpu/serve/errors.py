"""The serve error taxonomy (docs/serving.md "Error taxonomy").

Every failed request must answer with a machine-readable ``error.kind``
a client can branch on — "retry later" (``overloaded``), "your fault,
fix the request" (``parse``/``validation``), "give up on this attempt"
(``deadline_exceeded``), "page someone" (``internal``).  One generic
error string cannot carry that decision.

Kinds:

==================  ====================================================
kind                meaning
==================  ====================================================
``parse``           the input line is not valid JSON
``validation``      valid JSON, invalid request (bad op, bad ids/k,
                    wrong types — the reject-don't-coerce failures)
``deadline_exceeded``  the request's ``deadline_ms`` expired before a
                    result could be honestly returned (never silently
                    dropped, never dispatched late)
``overloaded``      admission control shed the request (bounded queue
                    full), or the degradation ladder is answering
                    cache-only and the request missed
``unknown_tenant``  the request named a tenant / artifact fingerprint
                    the engine registry does not hold — a routing miss,
                    not a malformed request (HTTP answers 404, never a
                    generic 400: the client's payload was fine, the
                    NAME doesn't resolve)
``internal``        anything else — a server-side bug
==================  ====================================================

:class:`ServeError` subclasses raise from the batcher with their kind
attached; the CLI maps stdlib validation exceptions (ValueError & co.)
onto ``validation`` and JSON decode failures onto ``parse``.
"""

from __future__ import annotations

ERROR_KINDS = ("parse", "validation", "deadline_exceeded", "overloaded",
               "unknown_tenant", "internal")


class ServeError(Exception):
    """Base of the typed serve failures; ``kind`` is the wire value."""

    kind = "internal"

    def payload(self) -> dict:
        """The response-line body: ``{"kind": ..., "message": ...}``."""
        return {"kind": self.kind, "message": str(self)}


class OverloadedError(ServeError):
    """Admission queue full (shed) or cache-only degradation miss."""

    kind = "overloaded"


class DeadlineExceededError(ServeError):
    """The request's deadline expired before an honest answer existed."""

    kind = "deadline_exceeded"


class UnknownTenantError(ServeError):
    """The named tenant / fingerprint is not in the engine registry.

    Typed separately from ``validation`` so the HTTP path can answer
    404 (the resource doesn't exist) instead of 400 (the request is
    malformed) — a client retrying a 400 forever would never learn the
    difference between a typo'd payload and a tenant that was simply
    never registered (or already retired)."""

    kind = "unknown_tenant"

    def __init__(self, tenant):
        super().__init__(f"unknown tenant or fingerprint: {tenant!r}")
        self.tenant = tenant


def kind_of(exc: BaseException) -> str:
    """The taxonomy kind an exception answers with — the ONE
    exception→kind classification, shared by the wire responses
    (:func:`error_response`) and the access-log outcomes
    (``RequestBatcher.emit_access`` call sites): the two surfaces can
    never diverge when the taxonomy grows."""
    if isinstance(exc, ServeError):
        return exc.kind
    if isinstance(exc, (ValueError, KeyError, TypeError, OverflowError)):
        return "validation"
    return "internal"


def error_response(exc: BaseException) -> dict:
    """Map an exception to the one wire shape every failed request
    answers with: ``{"error": {"kind": ..., "message": ...}}``."""
    if isinstance(exc, ServeError):
        return {"error": exc.payload()}
    return {"error": {"kind": kind_of(exc),
                      "message": f"{type(exc).__name__}: {exc}"}}
