"""Query-serving subsystem: checkpoint → artifact → batched inference.

The path from a trained Poincaré/Lorentz/product embedding run to
answering retrieval queries (docs/serving.md):

  artifact.py  frozen params-only serving artifacts (atomic export from
               a CheckpointManager directory, commit marker, content
               fingerprint, optional IVF index payload)
  engine.py    jitted batched k-NN + edge scoring over the frozen table
               (fused distmat kernels, chunked table walk, compiles
               keyed on (bucket, k, nprobe), optional IVF probing)
  index.py     offline IVF builder: hyperbolic k-means (geodesic
               k-means++ seeding, Lorentz-centroid / Fréchet-mean
               updates) + dense [ncells, max_cell] cell layout
  batcher.py   request micro-batcher: power-of-two bucket padding + LRU
               result cache, serve/* telemetry counters; overload
               safety — per-request deadlines, bounded admission queue,
               hysteresis degradation ladder (docs/resilience.md)
  errors.py    the typed error taxonomy (`error.kind`: parse /
               validation / deadline_exceeded / overloaded / internal)
  access.py    request-addressable observability: request ids, the
               structured JSONL access log, and the flight recorder
               (bounded ring + incident dumps on error bursts /
               degrade transitions / drain)
  collator.py  continuous-batching collator: fill a power-of-two bucket
               or flush at the max-wait deadline, one shared dispatch
               per flush through a single dispatch executor
  delta.py     live mutable index: LSM-style delta-segment upserts /
               tombstone deletes over a frozen base, write-through to
               the host master, background compaction, generation-
               folded scan signatures (stale cache rows structurally
               unreachable)
  rollover.py  blue-green rollover: prewarmed standby engine, health-
               gated atomic flip, old-stack drain — zero-downtime
               artifact replacement behind the front door
  registry.py  multi-tenant engine registry: per-tenant serving stacks
               routed by name/fingerprint, weighted-fair (deficit
               round robin) scheduling of the one shared dispatch
               executor, whole-engine paging under a device-memory
               budget (artifact = host master, device tables = cache)
  server.py    asyncio HTTP/1.1 front door (stdlib only): concurrent
               POST /v1/topk | /v1/score | /v1/upsert | /v1/delete |
               /v1/stats + /admin/rollover + /healthz, deadline
               propagation from socket accept, 429/504 typed errors,
               SIGTERM drain
  cli/serve.py the `export` / `query` / `serve` / `serve-http` entry
               points
"""

from hyperspace_tpu.serve.access import (  # noqa: F401
    AccessLog,
    FlightRecorder,
    new_request_id,
)
from hyperspace_tpu.serve.artifact import (  # noqa: F401
    QuantPayload,
    ServingArtifact,
    build_quant_payload,
    export_artifact,
    export_from_checkpoint,
    is_committed,
    load_artifact,
    manifold_from_spec,
    spec_from_manifold,
)
from hyperspace_tpu.serve.batcher import RequestBatcher  # noqa: F401
from hyperspace_tpu.serve.collator import Collator  # noqa: F401
from hyperspace_tpu.serve.delta import LiveQueryEngine  # noqa: F401
from hyperspace_tpu.serve.engine import QueryEngine  # noqa: F401
from hyperspace_tpu.serve.errors import (  # noqa: F401
    DeadlineExceededError,
    OverloadedError,
    ServeError,
    UnknownTenantError,
    error_response,
)
from hyperspace_tpu.serve.index import (  # noqa: F401
    ServingIndex,
    auto_ncells,
    build_index,
)
from hyperspace_tpu.serve.registry import (  # noqa: F401
    EngineRegistry,
    TenantStack,
    engine_device_bytes,
)
from hyperspace_tpu.serve.rollover import (  # noqa: F401
    RolloverCoordinator,
    gate_flip,
    standby_health,
)
